"""Serving benchmarks: v2-vs-v1 throughput, and an open-loop SLO harness.

Two entry points:

- :func:`run` — the round-1 closed-loop throughput comparison (v2 ragged
  continuous batching vs the naive v1 dense engine);
- :func:`run_slo` — an OPEN-LOOP SLO harness (``BENCH_MODE=serve_slo``,
  ``make serve-slo``): requests arrive on a Poisson clock regardless of
  whether the engine keeps up (the production traffic model — closed
  loops hide queueing collapse because a slow server slows its own
  offered load). Reports p50/p99 TTFT (queue wait INCLUDED), per-decode-
  token latency, tokens/s, goodput under a TTFT deadline, the queue-
  depth timeline, and the prefix-cache / speculative-decode counters, as
  one JSON line. ``SLO_COMPARE=1`` reruns the same workload with the
  prefix cache + speculation disabled and reports the speedup. The JSON
  embeds the per-request SLO attribution (per-phase p50/p99 + dominant
  miss phase; observability/request_trace.py); ``SLO_TRACE=1``
  additionally (a) asserts every trace's phase decomposition sums to
  its measured e2e/TTFT wall time (check_phase_closure — the trace-math
  regression gate), (b) dumps the per-request trace JSONL that
  ``tools/serve_top.py report`` consumes, and (c) exports per-request
  Perfetto lanes (``SLO_TRACE_DIR``, default /tmp/dstpu_serve_slo),
  printing the "why did p99 miss" table to stderr.


VERDICT r4 #9 asked for a serving performance number against the
reference's FastGen claim (2.3x vs vLLM, blogs/deepspeed-fastgen/
README.md:28 — the win comes from continuous batching + SplitFuse
keeping the chip at a constant token budget while the naive engine
decodes lock-step with the slowest sequence).

This benchmark serves the same workload through both engines on the
current backend and prints ONE JSON line:

  {"metric": "serve tokens/s (v2 ragged)", "value": ..., "v1_value": ...,
   "speedup_vs_v1": ...}

Workload: N prompts of mixed length, G new tokens each, greedy. The v2
engine admits continuously under a token budget; v1 decodes the whole
batch dense and synchronous (its per-step work scales with max prompt
length padding + every sequence decoding until the last finishes).

Env knobs: SERVE_MODEL (zoo name, default llama3-8b geometry cut to
SERVE_LAYERS=3), SERVE_SEQS (default 24), SERVE_PROMPT (default 128),
SERVE_GEN (default 128), SERVE_BUDGET (v2 max_tokens_per_step, 256).

Driver capture: ``BENCH_MODE=serve python bench.py`` routes here
(bench.py), so the serving number is recordable by the same harness as
the training headline.
"""

from __future__ import annotations

import json
import os
import sys
import time


def run() -> dict:
    import jax
    import numpy as np

    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models.zoo import get_model

    on_tpu = jax.default_backend() == "tpu"
    model_name = os.environ.get("SERVE_MODEL", "llama3-8b")
    layers = int(os.environ.get("SERVE_LAYERS", 3))
    n_seqs = int(os.environ.get("SERVE_SEQS", 24 if on_tpu else 4))
    prompt_len = int(os.environ.get("SERVE_PROMPT", 128 if on_tpu else 16))
    gen = int(os.environ.get("SERVE_GEN", 128 if on_tpu else 8))
    budget = int(os.environ.get("SERVE_BUDGET", 256 if on_tpu else 32))
    decode_steps = int(os.environ.get("SERVE_DECODE_STEPS", 8))
    max_seq_len = 1 << (prompt_len + gen + 1).bit_length()

    model = get_model(model_name, num_layers=layers, max_seq_len=max_seq_len,
                      remat=False)
    cfg = model.config
    rng = np.random.default_rng(0)
    import jax.numpy as jnp

    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    # mixed prompt lengths: half full, quarter 3/4, quarter 1/2 — the
    # ragged engine's reason to exist
    lens = [prompt_len, prompt_len * 3 // 4, prompt_len // 2,
            prompt_len] * (n_seqs // 4 + 1)
    lens = [max(4, l) for l in lens[:n_seqs]]
    prompts = [rng.integers(0, cfg.vocab_size, (l,)).astype(np.int32)
               for l in lens]

    # -- v1: dense synchronous decode -----------------------------------
    v1 = InferenceEngine(model, params=params, max_batch=n_seqs,
                         max_seq_len=max_seq_len)
    pad = max(lens)
    batch = np.zeros((n_seqs, pad), np.int32)
    for i, p in enumerate(prompts):
        batch[i, :len(p)] = p  # right-pad; v1 decodes from the padded end

    def v1_run():
        return v1.generate(batch, max_new_tokens=gen)

    v1_run()  # compile
    t0 = time.perf_counter()
    v1_run()
    t1 = time.perf_counter()
    v1_toks = n_seqs * gen / (t1 - t0)

    # -- v2: ragged continuous batching ---------------------------------
    block = 16
    blocks_per_seq = (max(lens) + gen) // block + 2
    kv_blocks = blocks_per_seq * n_seqs + 2

    def make_v2():
        return InferenceEngineV2(
            model, params=params, kv_blocks=kv_blocks, kv_block_size=block,
            max_tokens_per_step=budget,
            max_seqs_per_step=min(n_seqs, budget),
            max_blocks_per_seq=blocks_per_seq, decode_steps=decode_steps)

    def v2_run(engine):
        engine.put(list(range(n_seqs)), prompts, max_new_tokens=gen)
        out = engine.generate_all()
        total = sum(len(v) for v in out.values())
        assert total >= n_seqs * (gen - 1), (total, n_seqs * gen)
        return total

    engine = make_v2()
    v2_run(engine)  # compile pass; generate_all drains the KV pool
    t0 = time.perf_counter()
    total = v2_run(engine)
    t1 = time.perf_counter()
    v2_toks = total / (t1 - t0)
    snap = engine.snapshot()

    return {
        "metric": f"{model_name}-geometry({layers}L) serve tokens/s "
                  f"(v2 ragged, {n_seqs} seqs, prompt~{prompt_len}, "
                  f"gen {gen}, {'tpu' if on_tpu else 'cpu'})",
        "value": round(v2_toks, 1),
        "unit": "tokens/s",
        "v1_value": round(v1_toks, 1),
        "speedup_vs_v1": round(v2_toks / max(v1_toks, 1e-9), 3),
        "v1_note": (
            "upper-bound comparison: the v1 baseline right-pads every "
            "prompt to the longest in the batch, so it computes (and is "
            "billed for) padded-prompt work the ragged v2 path never "
            "runs — a length-sorted or uniform-length workload would "
            "narrow the gap"),
        "kernel_steps": (engine.stats.get("decode_kernel_steps", 0)
                         + engine.stats.get("prefill_kernel_steps", 0)),
        "fallback_steps": engine.stats.get("prefill_gather_fallbacks", 0),
        "serve_snapshot": {
            k: snap[k]
            for k in ("ttft", "decode_token_latency", "burst_efficiency")
            if k in snap},
    }


def _drive_open_loop(engine, prompts, arrivals, gen, deadline_s):
    """Drive one engine through an open-loop arrival schedule.

    Requests are put() at their scheduled arrival instant whether or not
    the engine has room (that is the open loop); TTFT is measured from
    the SCHEDULED arrival, so admission-queue wait counts against the
    SLO exactly as a client would experience it.
    """
    import numpy as np

    # warm pass: the whole workload once, closed loop — compiles every
    # bucket shape the timed phase will hit (cold prefill, prefix-hit
    # prefill, decode bursts, speculative chunks) and brings the prefix
    # cache to serving steady state, so the timed open-loop phase
    # measures serving, not XLA
    engine.put([(1 << 30) + i for i in range(len(prompts))], prompts,
               max_new_tokens=gen)
    engine.generate_all()
    # ...plus one lone request: the open loop's ramp-up runs low-
    # cardinality batches the all-at-once pass never shapes
    engine.put([1 << 29], [prompts[0]], max_new_tokens=gen)
    engine.generate_all()
    counter_keys = ("admitted", "preempted", "requeued", "prefix_hit_tokens",
                    "spec_steps", "spec_proposed", "spec_accepted",
                    "truncated")
    base = {k: engine.stats.get(k, 0) for k in counter_keys}
    base_prefill = engine.scheduler.stats["prefill_tokens"]
    for h in (engine._ttft_hist, engine._decode_hist, engine._step_hist,
              engine._admission_hist, engine._spec_hist):
        h.reset()
    tracer = getattr(engine, "tracer", None)
    if tracer is not None:
        tracer.reset()  # warmup traces must not pollute attribution

    n = len(prompts)
    first = {}
    counts = {uid: 0 for uid in range(n)}
    timeline = []
    completed = 0
    i = 0
    t0 = time.perf_counter()
    while completed < n:
        now = time.perf_counter() - t0
        while i < n and arrivals[i] <= now:
            engine.put([i], [prompts[i]], max_new_tokens=gen)
            i += 1
        if not engine.state.seqs and not engine._queue:
            if i >= n:
                break  # drained; anything incomplete was truncated
            time.sleep(min(max(arrivals[i] - (time.perf_counter() - t0),
                               0.0), 0.02))
            continue
        out = engine.serve_step()
        tnow = time.perf_counter() - t0
        timeline.append((round(tnow, 4), len(engine._queue),
                         len(engine.state.seqs)))
        for uid, toks in out.items():
            if not toks or uid not in counts:
                continue
            if uid not in first:
                first[uid] = tnow - arrivals[uid]
            counts[uid] += len(toks)
            if counts[uid] >= gen:
                completed += 1
    wall = time.perf_counter() - t0

    ttfts = np.asarray(sorted(first.values()), np.float64)
    total_tokens = int(sum(counts.values()))
    good_tokens = sum(counts[uid] for uid, t in first.items()
                      if t <= deadline_s)
    stride = max(1, len(timeline) // 40)
    decode = engine._decode_hist.snapshot()
    attribution = None
    if tracer is not None and tracer.enabled:
        from deepspeed_tpu.observability.request_trace import \
            slo_attribution

        rep = slo_attribution(tracer.finished(), deadline_s)
        # compact embed: per-phase p50/p99 + the "why" aggregates; the
        # per-request detail rows live in the trace JSONL that
        # tools/serve_top.py consumes, not in the one-line bench JSON
        attribution = {k: rep[k] for k in
                       ("schema", "requests", "slo_misses", "phase_seconds",
                        "miss_ttft_phase_seconds", "miss_dominant_phase",
                        "ttft", "e2e")}
    return {
        "completed": completed,
        "dropped": n - completed,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / max(wall, 1e-9), 1),
        "goodput_tokens_per_s": round(good_tokens / max(wall, 1e-9), 1),
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4)
                      if len(ttfts) else None,
        "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 4)
                      if len(ttfts) else None,
        "decode_token_p50_s": decode.get("p50"),
        "decode_token_p99_s": decode.get("p99"),
        "queue_depth_timeline": [list(t) for t in timeline[::stride]],
        "prefill_tokens": engine.scheduler.stats["prefill_tokens"]
                          - base_prefill,
        "attribution": attribution,
        **{k: engine.stats.get(k, 0) - base[k] for k in counter_keys},
    }


def run_slo() -> dict:
    import jax
    import numpy as np

    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models.zoo import get_model

    on_tpu = jax.default_backend() == "tpu"
    model_name = os.environ.get("SLO_MODEL",
                                "llama3-8b" if on_tpu else "tiny")
    layers = int(os.environ.get("SLO_LAYERS", 3 if on_tpu else 2))
    n_req = int(os.environ.get("SLO_REQUESTS", 96 if on_tpu else 24))
    prompt_len = int(os.environ.get("SLO_PROMPT", 256 if on_tpu else 48))
    shared_len = int(os.environ.get("SLO_SHARED_PREFIX",
                                    prompt_len * 3 // 4))
    gen = int(os.environ.get("SLO_GEN", 64 if on_tpu else 16))
    rate = float(os.environ.get("SLO_RATE", 8.0 if on_tpu else 40.0))
    deadline_s = float(os.environ.get("SLO_DEADLINE_MS",
                                      2000 if on_tpu else 4000)) / 1000.0
    budget = int(os.environ.get("SLO_BUDGET", 256 if on_tpu else 64))
    seed = int(os.environ.get("SLO_SEED", 0))
    use_spec = os.environ.get("SLO_SPEC", "1") == "1"
    use_prefix = os.environ.get("SLO_PREFIX_CACHE", "1") == "1"
    compare = os.environ.get("SLO_COMPARE", "0") == "1"
    trace_arm = os.environ.get("SLO_TRACE", "0") == "1"
    # full sampling by default: the bench wants the attribution over the
    # whole window, not a slice (production default is 0.05 — see
    # config.observability.request_trace)
    trace_sample = float(os.environ.get("SLO_TRACE_SAMPLE", 1.0))
    block = 16
    max_seq_len = 1 << (prompt_len + gen + 8).bit_length()

    model = get_model(model_name, num_layers=layers,
                      max_seq_len=max_seq_len, remat=False)
    cfg = model.config
    import jax.numpy as jnp

    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    if on_tpu:
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    # workload: one shared prefix (the system-prompt pattern the prefix
    # cache exists for) + a short repeated per-request motif (the
    # repetitive tail prompt-lookup speculation exists for)
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, cfg.vocab_size, (shared_len,))
    prompts = []
    for _ in range(n_req):
        motif = rng.integers(0, cfg.vocab_size, (4,))
        tail = np.tile(motif, (prompt_len - shared_len) // 4 + 1)
        prompts.append(np.concatenate(
            [shared, tail])[:prompt_len].astype(np.int32))
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))

    # KV pool sized to ~1/3 of the offered concurrency so the Poisson
    # burst actually exercises the admission queue and preemption paths
    blocks_per_seq = (prompt_len + gen) // block + 3
    kv_blocks = int(os.environ.get(
        "SLO_KV_BLOCKS", blocks_per_seq * max(3, n_req // 3) + 2))

    def make_engine(prefix_cache, spec_decode):
        return InferenceEngineV2(
            model, params=params, kv_blocks=kv_blocks, kv_block_size=block,
            max_tokens_per_step=budget,
            max_seqs_per_step=min(16 if not on_tpu else 64, budget),
            max_blocks_per_seq=blocks_per_seq,
            decode_steps=int(os.environ.get("SLO_DECODE_STEPS", 4)),
            prefix_cache=prefix_cache, spec_decode=spec_decode,
            spec_k=int(os.environ.get("SLO_SPEC_K", 4)),
            request_trace={"sample_rate": trace_sample,
                           "ring_size": max(4096, 2 * n_req),
                           "slo_deadline_ms": deadline_s * 1000.0})

    engine = make_engine(use_prefix, use_spec)
    opt = _drive_open_loop(engine, prompts, arrivals, gen, deadline_s)
    out = {
        "metric": f"{model_name}-geometry({layers}L) serve_slo "
                  f"tokens/s ({n_req} req, poisson {rate}/s, "
                  f"prompt {prompt_len} shared {shared_len}, gen {gen}, "
                  f"{'tpu' if on_tpu else 'cpu'})",
        "value": opt["tokens_per_s"],
        "unit": "tokens/s",
        "slo_deadline_ms": deadline_s * 1000.0,
        "kv_blocks": kv_blocks,
        "spec_decode": use_spec,
        "prefix_cache": use_prefix,
        "slo": opt,
    }
    if trace_arm and engine.tracer.enabled:
        from deepspeed_tpu.observability.chrome_trace import \
            export_request_traces
        from deepspeed_tpu.observability.request_trace import \
            check_phase_closure, slo_attribution_markdown

        traces = engine.tracer.finished()
        # the regression gate: every trace's phase decomposition must
        # sum to its measured e2e (and TTFT) wall time — raises on drift
        out["phase_closure"] = check_phase_closure(traces)
        trace_dir = os.environ.get("SLO_TRACE_DIR", "/tmp/dstpu_serve_slo")
        os.makedirs(trace_dir, exist_ok=True)
        out["trace_jsonl"] = engine.tracer.dump_jsonl(
            os.path.join(trace_dir, "request_traces.jsonl"))
        flight_events = [{"ts": ts, "kind": kind, **fields}
                         for ts, kind, fields in engine._flight.events()]
        out["perfetto_trace"] = export_request_traces(
            os.path.join(trace_dir, "request_lanes.json"), traces,
            flight_events=flight_events)
        report = slo_attribution_markdown(dict(
            opt["attribution"], phases=list(opt["attribution"][
                "phase_seconds"]), deadline_s=deadline_s))
        print(report, file=sys.stderr)
    if compare:
        base = _drive_open_loop(make_engine(False, False), prompts,
                                arrivals, gen, deadline_s)
        out["baseline"] = base
        out["speedup_vs_baseline"] = round(
            opt["tokens_per_s"] / max(base["tokens_per_s"], 1e-9), 3)
    return out


def _drive_fleet_arm(arm, model, params, prompts, arrivals, gen,
                     deadline_s, knobs) -> dict:
    """One fleet arm (``unified`` or ``disagg``) over the SAME workload
    and arrival schedule: warm pass (compile + prefix-cache steady
    state), then a timed open-loop run on threaded replicas."""
    import threading

    import numpy as np

    from deepspeed_tpu.config.config import RouterConfig
    from deepspeed_tpu.serving.router import build_fleet

    cfg = RouterConfig(
        replicas=knobs["replicas"], mode=arm,
        prefill_replicas=knobs["prefill_replicas"] if arm == "disagg" else 1,
        stale_after_seconds=knobs["stale_after_s"])
    cfg.validate()
    router = build_fleet(model, cfg, engine_kw=dict(
        params=params, kv_blocks=knobs["kv_blocks"],
        kv_block_size=knobs["block"],
        max_tokens_per_step=knobs["budget"],
        max_seqs_per_step=min(16, knobs["budget"]),
        max_blocks_per_seq=knobs["blocks_per_seq"],
        decode_steps=knobs["decode_steps"],
        prefix_cache=True,
        request_trace={"sample_rate": 1.0,
                       "ring_size": max(4096, 2 * len(prompts)),
                       "slo_deadline_ms": deadline_s * 1000.0}))

    n = len(prompts)
    warm_base = 1 << 30
    for i, p in enumerate(prompts):
        router.submit(warm_base + i, p, max_new_tokens=gen)
    router.run_until_complete()
    warm = {uid - warm_base: toks for uid, toks in router.results().items()
            if uid >= warm_base}
    for r in router.replicas.values():
        e = r.engine
        for h in (e._ttft_hist, e._decode_hist, e._step_hist,
                  e._admission_hist, e._spec_hist):
            h.reset()
        e.tracer.reset()  # warm traces must not pollute attribution
    base_stats = dict(router.stats)

    # TTFT from the SCHEDULED arrival, observed at the router's emission
    # callback — for the disagg arm this is the prefill replica's first
    # token, i.e. the client-visible TTFT before the handoff
    first_tok = {}
    tlock = threading.Lock()
    t0_box = [None]
    for r in router.replicas.values():
        orig_cb = r.emit_callback

        def cb(replica, emitted, _orig=orig_cb):
            if t0_box[0] is not None:
                tnow = time.perf_counter() - t0_box[0]
                with tlock:
                    for uid in emitted:
                        if uid < warm_base and uid not in first_tok:
                            first_tok[uid] = tnow
            _orig(replica, emitted)

        r.emit_callback = cb

    router.start()
    t0 = time.perf_counter()
    t0_box[0] = t0
    for i, p in enumerate(prompts):
        delay = arrivals[i] - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        router.submit(i, p, max_new_tokens=gen)
    router.drain(timeout_s=knobs["drain_timeout_s"])
    wall = time.perf_counter() - t0
    router.stop()

    out = {uid: toks for uid, toks in router.results().items()
           if uid < warm_base}
    completed = sum(1 for toks in out.values() if len(toks) >= gen)
    total_tokens = sum(len(t) for t in out.values())
    ttfts = np.asarray(sorted(
        first_tok[uid] - arrivals[uid] for uid in first_tok), np.float64)
    good_tokens = sum(len(out.get(uid, []))
                      for uid, t in first_tok.items()
                      if t - arrivals[uid] <= deadline_s)

    # per-replica decode latency: each engine owns its own (labeled)
    # histogram, so the decode pool's p99 is directly readable — the
    # disagg acceptance number (decode never waits behind a prompt)
    per_replica = {}
    for rid, r in sorted(router.replicas.items()):
        snap = r.engine._decode_hist.snapshot()
        rep = r.load_report()
        per_replica[r.name] = {
            "role": r.role, "steps": r.steps,
            "decode_token_p50_s": snap.get("p50"),
            "decode_token_p99_s": snap.get("p99"),
            "goodput_tokens_per_s": rep["goodput_tokens_per_s"],
        }
    decode_pool = [router.replicas[rid] for rid in router.decode_pool]
    pool_p99 = [s for s in (per_replica[r.name]["decode_token_p99_s"]
                            for r in decode_pool) if s is not None]
    pool_p50 = [s for s in (per_replica[r.name]["decode_token_p50_s"]
                            for r in decode_pool) if s is not None]

    trace_dir = knobs["trace_dir"]
    os.makedirs(trace_dir, exist_ok=True)
    snapshot = router.fleet_snapshot(deadline_s=deadline_s)
    snap_path = os.path.join(trace_dir, f"fleet_{arm}.json")
    with open(snap_path, "w") as f:
        json.dump(snapshot, f, indent=1)
    perfetto = router.export_perfetto(
        os.path.join(trace_dir, f"fleet_{arm}_lanes.json"))

    stats = {k: router.stats[k] - base_stats.get(k, 0)
             for k in router.stats}
    attribution = snapshot["slo_attribution"]
    return {
        "arm": arm,
        "replicas": cfg.replicas,
        "prefill_replicas": len(router.prefill_pool),
        "requests": n,
        "completed": completed,
        "dropped": n - completed,
        # informational, not a gate: the warm pass runs closed-loop (all
        # prompts in one ragged batch) while the timed pass batches by
        # arrival, and greedy argmax can flip on near-tied logits across
        # batch compositions — the random tiny CPU model near-ties often;
        # the test-asserted bit-identity contract compares runs of equal
        # composition (tests/test_serving_fleet.py)
        "warm_reference_match_frac": round(sum(
            1 for uid in range(n)
            if out.get(uid) == warm.get(uid)) / max(n, 1), 3),
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / max(wall, 1e-9), 1),
        "goodput_tokens_per_s": round(good_tokens / max(wall, 1e-9), 1),
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4)
                      if len(ttfts) else None,
        "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 4)
                      if len(ttfts) else None,
        # worst decode-pool replica: the conservative fleet p99
        "decode_token_p50_s": max(pool_p50) if pool_p50 else None,
        "decode_token_p99_s": max(pool_p99) if pool_p99 else None,
        "handoffs": stats["handoffs"],
        "handoff_recompute": stats["handoff_recompute"],
        "affinity_hits": stats["affinity_hits"],
        "failovers": stats["failovers"],
        "slo_misses": attribution.get("slo_misses"),
        "per_replica": per_replica,
        "fleet_snapshot": snap_path,
        "perfetto_trace": perfetto,
    }


def run_fleet() -> list:
    """Multi-replica open-loop bench (``BENCH_MODE=serve_fleet``,
    ``make serve-fleet``): the SAME Poisson workload served by (a) a
    unified fleet — every replica prefills and decodes — and (b) a
    disaggregated fleet — prefill replicas hand KV blocks to decode
    replicas (serving/disagg.py). Replicas are in-process threads, so
    the arm runs on CPU CI; the number that matters is the decode-pool
    token p99: the disagg arm's decode replicas never run a prompt, so
    decode latency stays flat under concurrent prefill load. One JSON
    line per arm; each arm also writes the fleet snapshot (for
    ``serve_top --fleet``) and the per-replica Perfetto lanes into
    FLEET_TRACE_DIR."""
    import jax
    import numpy as np

    from deepspeed_tpu.models.zoo import get_model

    on_tpu = jax.default_backend() == "tpu"
    model_name = os.environ.get("FLEET_MODEL",
                                "llama3-8b" if on_tpu else "tiny")
    layers = int(os.environ.get("FLEET_LAYERS", 3 if on_tpu else 2))
    # CPU defaults pick a SUSTAINED arrival rate (inter-arrival on the
    # order of a serve step) rather than a one-shot burst: the disagg
    # claim — decode p99 isolated from prefill — only shows when
    # prompts keep arriving while earlier requests are still decoding
    n_req = int(os.environ.get("FLEET_REQUESTS", 96 if on_tpu else 24))
    prompt_len = int(os.environ.get("FLEET_PROMPT", 256 if on_tpu else 48))
    shared_len = int(os.environ.get("FLEET_SHARED_PREFIX",
                                    prompt_len * 3 // 4))
    gen = int(os.environ.get("FLEET_GEN", 64 if on_tpu else 24))
    rate = float(os.environ.get("FLEET_RATE", 16.0 if on_tpu else 12.0))
    deadline_s = float(os.environ.get("FLEET_DEADLINE_MS",
                                      2000 if on_tpu else 6000)) / 1000.0
    budget = int(os.environ.get("FLEET_BUDGET", 256 if on_tpu else 64))
    seed = int(os.environ.get("FLEET_SEED", 0))
    replicas = int(os.environ.get("FLEET_REPLICAS", 2))
    prefill_replicas = int(os.environ.get("FLEET_PREFILL", 1))
    arms = os.environ.get("FLEET_ARMS", "unified,disagg").split(",")
    block = 16
    max_seq_len = 1 << (prompt_len + gen + 8).bit_length()

    model = get_model(model_name, num_layers=layers,
                      max_seq_len=max_seq_len, remat=False)
    cfg = model.config
    import jax.numpy as jnp

    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    if on_tpu:
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    # same workload shape as run_slo: shared system prefix + per-request
    # motif tail, Poisson arrivals — identical schedule for both arms
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, cfg.vocab_size, (shared_len,))
    prompts = []
    for _ in range(n_req):
        motif = rng.integers(0, cfg.vocab_size, (4,))
        tail = np.tile(motif, (prompt_len - shared_len) // 4 + 1)
        prompts.append(np.concatenate(
            [shared, tail])[:prompt_len].astype(np.int32))
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))

    blocks_per_seq = (prompt_len + gen) // block + 3
    kv_blocks = int(os.environ.get(
        "FLEET_KV_BLOCKS", blocks_per_seq * max(4, n_req // 2) + 2))
    knobs = {
        "replicas": replicas, "prefill_replicas": prefill_replicas,
        "block": block, "blocks_per_seq": blocks_per_seq,
        "kv_blocks": kv_blocks, "budget": budget,
        "decode_steps": int(os.environ.get("FLEET_DECODE_STEPS", 4)),
        "stale_after_s": float(os.environ.get("FLEET_STALE_AFTER_S", 5.0)),
        "drain_timeout_s": float(os.environ.get("FLEET_DRAIN_TIMEOUT_S",
                                                300.0)),
        "trace_dir": os.environ.get("FLEET_TRACE_DIR",
                                    "/tmp/dstpu_serve_fleet"),
    }
    results = []
    for arm in arms:
        arm = arm.strip()
        res = _drive_fleet_arm(arm, model, params, prompts, arrivals, gen,
                               deadline_s, knobs)
        res["metric"] = (
            f"{model_name}-geometry({layers}L) serve_fleet[{arm}] "
            f"tokens/s ({replicas} replicas, {n_req} req, "
            f"poisson {rate}/s, prompt {prompt_len}, gen {gen}, "
            f"{'tpu' if on_tpu else 'cpu'})")
        res["value"] = res["tokens_per_s"]
        res["unit"] = "tokens/s"
        results.append(res)
    return results


def run_quant() -> dict:
    """Serving-quant capacity bench (``BENCH_MODE=serve_quant``,
    ``make serve-quant``): the int8 KV pool's two acceptance numbers on
    ONE fixed HBM byte budget.

    - **sessions per HBM budget** — both arms get the same pool byte
      budget; blocks come from the quant-aware
      ``KVCacheConfig.bytes_per_block`` (int8 payload + fp32 scale per
      head vector vs bf16), so the int8 arm fits
      ``2*head_dim/(head_dim+4)``x the blocks. Each arm then actually
      SERVES its capacity worth of concurrent sessions and reports the
      measured peak live count — the ratio must hold >=
      ``QUANT_SERVE_MIN_SESSIONS_RATIO`` (default 1.8).
    - **handoff wire bytes** — the same cached prompt chain serialized
      raw vs int4-packed (serving/disagg.py); the quantized wire must
      ship <= ``QUANT_SERVE_MAX_WIRE_FRAC`` (default 0.35) of the raw
      bytes.
    - **int4 storage arm** — the packed-nibble uint8 pool serves
      >= ``QUANT_SERVE_MIN_SESSIONS_RATIO_INT4`` (default 1.7) x the
      int8 arm's sessions on the same budget (head_dim 128: 1.94x
      blocks), and the codec's decode round-trip on the bf16 arm's real
      KV pool must hold >= ``QUANT_SERVE_MIN_DECODE_SNR_DB`` (default
      14 dB; per-vector int4 measures ~18-19 dB).

    Violations ride the payload's ``ok``/``violations`` keys, the same
    contract as ``make bench-quant`` — ``tools/bench_diff.py`` fails the
    run on any violation without needing a sentinel per number."""
    import jax
    import numpy as np

    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.inference.ragged.kv_cache import KVCacheConfig
    from deepspeed_tpu.models.zoo import get_model
    from deepspeed_tpu.serving import disagg

    on_tpu = jax.default_backend() == "tpu"
    model_name = os.environ.get("QUANT_SERVE_MODEL", "llama3-8b")
    layers = int(os.environ.get("QUANT_SERVE_LAYERS", 3 if on_tpu else 2))
    vocab = int(os.environ.get("QUANT_SERVE_VOCAB",
                               0 if on_tpu else 4096))
    prompt_len = int(os.environ.get("QUANT_SERVE_PROMPT",
                                    256 if on_tpu else 48))
    gen = int(os.environ.get("QUANT_SERVE_GEN", 64 if on_tpu else 8))
    # >= 6 sessions keeps the capacity ratio's floor-division
    # granularity below the 1.8x gate's slack (at 3 the int8 arm's
    # 1.94x byte advantage floors to 5/3 sessions)
    base_sessions = int(os.environ.get("QUANT_SERVE_SESSIONS",
                                       16 if on_tpu else 6))
    min_ratio = float(os.environ.get("QUANT_SERVE_MIN_SESSIONS_RATIO", 1.8))
    # int4 arm: packed-nibble pool must roughly double int8's capacity
    # again (head_dim 128: (128+4)/(64+4) = 1.94x blocks) and its
    # decoded KV must stay above the SNR floor — per-vector int4
    # measures ~18-19 dB on gaussian KV, a broken codec lands near 0
    min_ratio4 = float(os.environ.get(
        "QUANT_SERVE_MIN_SESSIONS_RATIO_INT4", 1.7))
    min_snr4 = float(os.environ.get(
        "QUANT_SERVE_MIN_DECODE_SNR_DB", 14.0))
    max_wire = float(os.environ.get("QUANT_SERVE_MAX_WIRE_FRAC", 0.35))
    block = 16
    max_seq_len = 1 << (prompt_len + gen + 1).bit_length()

    overrides = dict(num_layers=layers, max_seq_len=max_seq_len,
                     remat=False)
    if vocab:
        overrides["vocab_size"] = vocab  # CPU arm: shrink the embed table
    model = get_model(model_name, **overrides)
    cfg = model.config
    import jax.numpy as jnp

    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    rng = np.random.default_rng(0)
    blocks_per_seq = (prompt_len + gen) // block + 2

    def kv_cfg(bits, num_blocks=1):
        return KVCacheConfig(num_layers=layers, kv_heads=cfg.kv_heads,
                             head_dim=cfg.head_dim, block_size=block,
                             num_blocks=num_blocks, quant_bits=bits)

    # ONE byte budget for both arms: exactly base_sessions worth of bf16
    # blocks — the int8 arm's extra capacity is the headline
    hbm_budget = kv_cfg(None).bytes_per_block * blocks_per_seq * base_sessions

    def drive_arm(bits):
        kv_blocks = hbm_budget // kv_cfg(bits).bytes_per_block
        capacity = int(kv_blocks) // blocks_per_seq
        n_req = capacity
        prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
                   .astype(np.int32) for _ in range(n_req)]
        engine = InferenceEngineV2(
            model, params=params, kv_blocks=int(kv_blocks),
            kv_block_size=block, max_tokens_per_step=max(64, prompt_len),
            max_seqs_per_step=max(4, n_req),
            max_blocks_per_seq=blocks_per_seq, prefix_cache=True,
            kv_quant_bits=bits)
        engine.put(list(range(n_req)), prompts, max_new_tokens=gen)
        peak_live = 0
        emitted = {}
        t0 = time.perf_counter()
        while engine.state.seqs or engine._queue:
            out = engine.serve_step()
            live = sum(1 for s in engine.state.seqs.values() if not s.done)
            peak_live = max(peak_live, live)
            for uid, toks in out.items():
                emitted.setdefault(uid, []).extend(toks)
        wall = time.perf_counter() - t0
        total = sum(len(t) for t in emitted.values())
        return engine, prompts, {
            "kv_quant_bits": bits,
            "kv_blocks": int(kv_blocks),
            "bytes_per_block": kv_cfg(bits).bytes_per_block,
            "pool_bytes": int(kv_blocks) * kv_cfg(bits).bytes_per_block,
            "sessions_capacity": capacity,
            "peak_concurrent_sessions": peak_live,
            "requests": n_req,
            "tokens": total,
            "tokens_per_s": round(total / max(wall, 1e-9), 1),
        }

    bf16_engine, bf16_prompts, bf16_arm = drive_arm(None)
    _, _, int8_arm = drive_arm(8)
    _, _, int4_arm = drive_arm(4)
    ratio = (int8_arm["peak_concurrent_sessions"]
             / max(bf16_arm["peak_concurrent_sessions"], 1))
    ratio4 = (int4_arm["peak_concurrent_sessions"]
              / max(int8_arm["peak_concurrent_sessions"], 1))

    # decode-SNR of the packed-nibble codec on the bf16 arm's REAL kv
    # pool (the blocks the serve run just wrote, not synthetic data):
    # quantize → pack → unpack → dequantize round-trip
    from deepspeed_tpu.ops.pallas.quantization import (
        kv_dequantize, kv_pack, kv_quantize, kv_unpack)

    pool = np.asarray(bf16_engine.kv_cache.data, np.float32)
    live = np.abs(pool).reshape(pool.shape[0], pool.shape[1], -1).sum(
        (0, 2)) > 0
    sample = jnp.asarray(pool[:, live][:, :8])
    q4, s4 = kv_quantize(sample, bits=4)
    back = np.asarray(kv_dequantize(kv_unpack(kv_pack(q4, 4), 4), s4,
                                    dtype=jnp.float32))
    src = np.asarray(sample, np.float32)
    noise = float(((src - back) ** 2).mean())
    decode_snr_db = float(10.0 * np.log10(
        max(float((src ** 2).mean()), 1e-12) / max(noise, 1e-12)))

    # handoff wire: the SAME cached chain raw vs int4-packed
    raw_h = disagg.serialize_prefix(bf16_engine, bf16_prompts[0],
                                    wire="raw")
    q_h = disagg.serialize_prefix(bf16_engine, bf16_prompts[0],
                                  wire="int4")
    wire_frac = (q_h.wire_nbytes / max(raw_h.wire_nbytes, 1)
                 if raw_h is not None and q_h is not None else None)

    violations = []
    if ratio < min_ratio:
        violations.append({
            "region": "kv_capacity", "gate": "min_sessions_ratio",
            "limit": min_ratio, "got": round(ratio, 3)})
    if ratio4 < min_ratio4:
        violations.append({
            "region": "kv_capacity", "gate": "min_sessions_ratio_int4",
            "limit": min_ratio4, "got": round(ratio4, 3)})
    if decode_snr_db < min_snr4:
        violations.append({
            "region": "kv_decode", "gate": "min_decode_snr_db",
            "limit": min_snr4, "got": round(decode_snr_db, 2)})
    if wire_frac is None:
        violations.append({
            "region": "kv_wire", "gate": "serialized",
            "limit": "chain cached", "got": "no cached chain"})
    elif wire_frac > max_wire:
        violations.append({
            "region": "kv_wire", "gate": "max_wire_frac",
            "limit": max_wire, "got": round(wire_frac, 3)})
    return {
        "metric": f"{model_name}-geometry({layers}L) serve_quant "
                  f"sessions-per-HBM-budget ratio (int8/bf16, "
                  f"{'tpu' if on_tpu else 'cpu'})",
        "value": round(ratio, 3),
        "unit": "x",
        "hbm_budget_bytes": int(hbm_budget),
        "bf16": bf16_arm,
        "int8": int8_arm,
        "int4": int4_arm,
        "int4_sessions_ratio": round(ratio4, 3),
        "int4_decode_snr_db": round(decode_snr_db, 2),
        "handoff_wire_bytes_raw": (raw_h.wire_nbytes
                                   if raw_h is not None else None),
        "handoff_wire_bytes_int4": (q_h.wire_nbytes
                                    if q_h is not None else None),
        "handoff_wire_frac": (round(wire_frac, 4)
                              if wire_frac is not None else None),
        "handoff_wire_snr_db": (round(q_h.wire_snr_db, 2)
                                if q_h is not None
                                and q_h.wire_snr_db is not None else None),
        "ok": not violations,
        "violations": violations,
    }


def run_tier() -> dict:
    """Tiered-KV + adaptive-speculation bench (``BENCH_MODE=serve_tier``,
    ``make serve-tier``): the host-memory KV tier's two acceptance
    numbers plus the distilled drafter's acceptance edge, one JSON line.

    - **sessions per HBM GB** — both arms serve ``oversub``x more
      sessions than one fixed HBM byte budget holds. The HBM-only arm
      evicts cold chains (a returning session pays full re-prefill); the
      tiered arm pages them to host memory instead. A session counts as
      *held* when its full prompt chain is still servable without
      prefill (HBM prefix cache or host tier). The tiered arm must hold
      >= ``TIER_SERVE_MIN_SESSIONS_RATIO`` (default 2.0) x the HBM-only
      arm on the SAME budget.
    - **warm-resume TTFT** — a mid-decode session pages out
      (``engine.page_out``), then resumes: host->HBM block restore + one
      decode step, vs the cold path re-prefilling the same token count.
      Warm must cost <= ``TIER_SERVE_MAX_RESUME_RATIO`` (default 0.5) x
      cold.
    - **drafter acceptance** — a ``TransformerDrafter`` distilled
      against the target (weights persisted like ``docs/autotuned/``
      artifacts) vs model-free prompt lookup, both with adaptive draft
      length on: the distilled drafter must bank
      >= ``TIER_SERVE_MIN_ACCEPT_EDGE`` (default 1.05) x prompt
      lookup's ACCEPTED DRAFT TOKENS PER ENGINE STEP on the workload
      it was distilled for. Per-step, not raw accept_rate: lookup
      abstains whenever no n-gram matches, and abstention inflates
      accept_rate (a drafter that only drafts sure things scores ~1.0
      with zero speedup) — tokens banked per verify round is the
      number that pays for speculation.

    Violations ride ``ok``/``violations`` (the ``make serve-quant``
    contract); ``tier.sessions_per_gb`` / ``tier.warm_resume_ttft_ratio``
    / ``spec.accept_rate`` are round-over-round sentinels in
    ``tools/bench_diff.py``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.inference.ragged.kv_cache import KVCacheConfig
    from deepspeed_tpu.inference.spec_decode import (PromptLookupDrafter,
                                                     TransformerDrafter)
    from deepspeed_tpu.models.zoo import get_model

    on_tpu = jax.default_backend() == "tpu"
    block = 8
    prompt_len = int(os.environ.get("TIER_SERVE_PROMPT", 24))
    gen = int(os.environ.get("TIER_SERVE_GEN", 8))
    base_sessions = int(os.environ.get("TIER_SERVE_SESSIONS", 4))
    oversub = int(os.environ.get("TIER_SERVE_OVERSUB", 3))
    min_ratio = float(os.environ.get("TIER_SERVE_MIN_SESSIONS_RATIO", 2.0))
    max_resume = float(os.environ.get("TIER_SERVE_MAX_RESUME_RATIO", 0.5))
    min_edge = float(os.environ.get("TIER_SERVE_MIN_ACCEPT_EDGE", 1.05))
    distill_steps = int(os.environ.get("TIER_SERVE_DISTILL_STEPS", 300))

    model = get_model("tiny", dtype=jnp.float32, param_dtype=jnp.float32)
    cfg = model.config
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    blocks_per_seq = (prompt_len + gen) // block + 2
    kv_cfg = KVCacheConfig(num_layers=cfg.num_layers,
                           kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
                           block_size=block, num_blocks=1)
    hbm_budget = kv_cfg.bytes_per_block * blocks_per_seq * base_sessions
    kv_blocks = hbm_budget // kv_cfg.bytes_per_block
    n_req = base_sessions * oversub
    full_chain = (prompt_len - 1) // block  # final token stays uncached
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
               .astype(np.int32) for _ in range(n_req)]

    def drive_arm(tiered: bool):
        engine = InferenceEngineV2(
            model, params=params, dtype=jnp.float32,
            kv_blocks=int(kv_blocks), kv_block_size=block,
            max_tokens_per_step=32, max_seqs_per_step=base_sessions,
            max_blocks_per_seq=blocks_per_seq, prefix_cache=True,
            host_kv_tier=tiered, host_tier_mb=64)
        engine.put(list(range(n_req)), prompts, max_new_tokens=gen)
        tier = getattr(engine.kv_cache, "host_tier", None)
        peak_resident = 0
        t0 = time.perf_counter()
        emitted = {}
        while engine.state.seqs or engine._queue:
            out = engine.serve_step()
            live = sum(1 for s in engine.state.seqs.values() if not s.done)
            parked = 0 if tier is None else tier.session_count
            peak_resident = max(peak_resident, live + parked)
            for uid, toks in out.items():
                emitted.setdefault(uid, []).extend(toks)
        wall = time.perf_counter() - t0
        # a session is HELD when its whole prompt chain is still
        # servable without prefill (HBM prefix cache or host tier)
        held = sum(1 for p in prompts
                   if engine.holds_prefix_blocks(p) >= full_chain)
        snap = engine.snapshot()
        return engine, emitted, {
            "tiered": tiered,
            "kv_blocks": int(kv_blocks),
            "hbm_budget_bytes": int(hbm_budget),
            "requests": n_req,
            "sessions_held": held,
            "sessions_held_per_hbm_gb": round(
                held / (hbm_budget / (1 << 30)), 1),
            "peak_resident_sessions": peak_resident,
            "paged_out": snap["stats"]["paged_out"],
            "paged_in": snap["stats"]["paged_in"],
            "warm_resume_tokens": snap["stats"]["warm_resume_tokens"],
            "preempted": snap["stats"]["preempted"],
            "tokens": sum(len(t) for t in emitted.values()),
            "wall_s": round(wall, 3),
            "host_tier": snap.get("host_tier"),
        }

    base_engine, base_out, base_arm = drive_arm(False)
    tier_engine, tier_out, tier_arm = drive_arm(True)
    # paging is an optimization, never a semantics change: both arms
    # must emit the identical greedy streams
    bit_identical = all(base_out.get(u) == tier_out.get(u)
                        for u in range(n_req))
    sessions_ratio = (tier_arm["sessions_held"]
                      / max(base_arm["sessions_held"], 1))

    # -- warm-resume TTFT vs cold re-prefill (same engine, warm jit) ----
    resume_prompt_len = int(os.environ.get("TIER_SERVE_RESUME_PROMPT", 96))
    resume_gen = int(os.environ.get("TIER_SERVE_RESUME_GEN", 16))
    rng = np.random.default_rng(1)  # own stream: arms stay independent
    r_blocks_per_seq = (resume_prompt_len + 2 * resume_gen) // block + 2
    # decode_steps=1 keeps the TTFT honest: a multi-token burst would
    # pad BOTH arms' first-token step with K-1 extra decode tokens and
    # compress the warm/cold ratio toward 1
    r_engine = InferenceEngineV2(
        model, params=params, dtype=jnp.float32,
        kv_blocks=4 * r_blocks_per_seq, kv_block_size=block,
        max_tokens_per_step=16, max_seqs_per_step=2, decode_steps=1,
        max_blocks_per_seq=r_blocks_per_seq, prefix_cache=True,
        host_kv_tier=True, host_tier_mb=64)

    def first_token_latency(uid, toks, max_new):
        r_engine.put([uid], [toks], max_new_tokens=max_new)
        t0 = time.perf_counter()
        while True:
            out = r_engine.serve_step()
            if out.get(uid):
                return time.perf_counter() - t0

    def resume_cycle(uid, prompt, measure):
        """Decode ``resume_gen`` tokens, page out mid-decode, resume;
        returns the paged-out -> first-resumed-token latency. The
        un-measured warmup call runs the IDENTICAL shape first so the
        measured cycle times the steady state (host->HBM restore + one
        decode step), not first-compile of the restore path."""
        r_engine.put([uid], [prompt], max_new_tokens=2 * resume_gen)
        got = 0
        while got < resume_gen:
            got += len(r_engine.serve_step().get(uid, []))
        assert r_engine.page_out(uid), "page_out refused a live session"
        t0 = time.perf_counter()
        while True:
            if r_engine.serve_step().get(uid):
                dt = time.perf_counter() - t0
                break
        # drain to completion only for the warmup (compiles tail paths)
        if not measure:
            while any(not s.done
                      for s in r_engine.state.seqs.values()):
                r_engine.serve_step()
        r_engine.flush([uid])
        return dt

    warm_prompt = rng.integers(0, cfg.vocab_size, (resume_prompt_len,)
                               ).astype(np.int32)
    resume_cycle(1000, warm_prompt, measure=False)
    a_prompt = rng.integers(0, cfg.vocab_size, (resume_prompt_len,)
                            ).astype(np.int32)
    warm_ttft = resume_cycle(1, a_prompt, measure=True)
    # cold arm: the SAME token count arrives fresh (different tokens —
    # no prefix-cache help) and pays full re-prefill before its first
    # token
    cold_toks = rng.integers(
        0, cfg.vocab_size,
        (resume_prompt_len + resume_gen,)).astype(np.int32)
    cold_ttft = first_token_latency(2, cold_toks, resume_gen)
    resume_ratio = warm_ttft / max(cold_ttft, 1e-9)

    # -- distilled drafter vs prompt lookup (adaptive k on both) --------
    drafter_path = os.environ.get(
        "TIER_SERVE_DRAFTER_PATH",
        os.path.join(os.path.dirname(__file__), "..", "docs", "autotuned",
                     "spec_drafter_tiny.npz"))
    distilled = None
    if os.path.exists(drafter_path):
        try:
            distilled = TransformerDrafter.load(drafter_path)
            if distilled.model.config.vocab_size != cfg.vocab_size:
                distilled = None
        except Exception:
            distilled = None  # stale artifact: re-distill below
    if distilled is None:
        distilled = TransformerDrafter.small(cfg.vocab_size, window=64)
        # prefix_len tracks the serve prompt length: the drafter must
        # see random tokens in every position a prompt can occupy
        distilled.distill_from(model, params, steps=distill_steps,
                               batch=16, seed=0, prefix_len=16)
        distilled.save(drafter_path)

    rng = np.random.default_rng(2)  # own stream: arms stay independent
    spec_prompts = [rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
                    for _ in range(10)]

    def spec_arm(drafter):
        engine = InferenceEngineV2(
            model, params=params, dtype=jnp.float32,
            kv_blocks=64, kv_block_size=block,
            max_tokens_per_step=64, max_seqs_per_step=8,
            max_blocks_per_seq=16, prefix_cache=False,
            spec_decode=True, spec_k=4, spec_adaptive_k=True,
            drafter=drafter)
        engine.put(list(range(len(spec_prompts))), spec_prompts,
                   max_new_tokens=24)
        out, steps = {}, 0
        while engine.state.seqs or engine._queue:
            for uid, toks in engine.serve_step().items():
                out.setdefault(uid, []).extend(toks)
            steps += 1
        snap = engine.snapshot()
        drafted = snap["stats"]["spec_proposed"]
        accepted = snap["stats"]["spec_accepted"]
        return out, {
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            "accept_rate": round(accepted / max(drafted, 1), 4),
            # the throughput number: extra tokens each verify round
            # actually banked. Raw accept_rate rewards ABSTENTION (a
            # drafter that only drafts sure things scores ~1.0 with
            # zero speedup), so the drafter-vs-drafter edge is judged
            # on accepted tokens per engine step instead.
            "accepted_per_step": round(accepted / max(steps, 1), 4),
            "engine_steps": steps,
            "accept_ewma": snap.get("spec_accept_ewma"),
            "wasted_verify_tokens": snap.get(
                "spec_wasted_verify_tokens", 0),
            "spec_backoff_rounds": snap["stats"]["spec_backoff_rounds"],
        }

    lookup_out, lookup_arm = spec_arm(PromptLookupDrafter(max_ngram=3))
    distilled_out, distilled_arm = spec_arm(distilled)
    spec_identical = all(lookup_out.get(u) == distilled_out.get(u)
                         for u in range(len(spec_prompts)))
    accept_edge = (distilled_arm["accepted_per_step"]
                   / max(lookup_arm["accepted_per_step"], 1e-9))

    violations = []
    if sessions_ratio < min_ratio:
        violations.append({
            "region": "kv_tier", "gate": "min_sessions_ratio",
            "limit": min_ratio, "got": round(sessions_ratio, 3)})
    if resume_ratio > max_resume:
        violations.append({
            "region": "kv_tier", "gate": "max_warm_resume_ttft_ratio",
            "limit": max_resume, "got": round(resume_ratio, 3)})
    if not bit_identical:
        violations.append({
            "region": "kv_tier", "gate": "bit_identical_streams",
            "limit": True, "got": False})
    if not spec_identical:
        violations.append({
            "region": "spec", "gate": "bit_identical_streams",
            "limit": True, "got": False})
    if accept_edge < min_edge:
        violations.append({
            "region": "spec", "gate": "min_distilled_accept_edge",
            "limit": min_edge, "got": round(accept_edge, 3)})
    return {
        "metric": f"tiny serve_tier sessions-held ratio (tiered/HBM-only,"
                  f" {'tpu' if on_tpu else 'cpu'})",
        "value": round(sessions_ratio, 3),
        "unit": "x",
        "hbm_budget_bytes": int(hbm_budget),
        "hbm_only": base_arm,
        "tiered": tier_arm,
        "tier.sessions_per_gb": tier_arm["sessions_held_per_hbm_gb"],
        "tier.warm_resume_ttft_ratio": round(resume_ratio, 4),
        "warm_resume_ttft_ms": round(warm_ttft * 1e3, 2),
        "cold_ttft_ms": round(cold_ttft * 1e3, 2),
        "bit_identical": bit_identical,
        "spec_lookup": lookup_arm,
        "spec_distilled": distilled_arm,
        "spec.accept_rate": distilled_arm["accept_rate"],
        "spec_accept_edge": round(accept_edge, 3),
        "drafter_artifact": os.path.relpath(
            drafter_path, os.path.join(os.path.dirname(__file__), "..")),
        "drafter_distill": distilled.distill_summary,
        "ok": not violations,
        "violations": violations,
    }


def _nhpp_arrivals(n, rate, period_s, burst_factor, burst_frac, rng):
    """Nonhomogeneous Poisson arrivals by thinning: a diurnal sinusoid
    (the day/night cycle compressed to ``period_s``) with a burst window
    at ``burst_factor``x the base rate in the first ``burst_frac`` of
    each period — the two arrival shapes a router's tail latency has to
    survive (slow swell and sudden spike)."""
    import math

    import numpy as np

    lam_max = rate * (1.5 + burst_factor)
    out = []
    t = 0.0
    while len(out) < n:
        t += rng.exponential(1.0 / lam_max)
        diurnal = 1.0 + 0.5 * math.sin(2.0 * math.pi * t / period_s)
        in_burst = (t % period_s) / period_s < burst_frac
        lam = rate * diurnal * (burst_factor if in_burst else 1.0)
        if rng.random() < lam / lam_max:
            out.append(t)
    return np.asarray(out)


def _percentiles_ms(ttfts):
    import numpy as np

    if not len(ttfts):
        return {"ttft_p50_ms": None, "ttft_p99_ms": None,
                "ttft_p999_ms": None}
    a = np.asarray(sorted(ttfts), np.float64) * 1e3
    return {"ttft_p50_ms": round(float(np.percentile(a, 50)), 2),
            "ttft_p99_ms": round(float(np.percentile(a, 99)), 2),
            "ttft_p999_ms": round(float(np.percentile(a, 99.9)), 2)}


def _drive_procs_arm(arm, base_dir, model_spec, engine_spec, prompts,
                     arrivals, gen, deadline_s, knobs):
    """One process-fleet arm over the SAME workload and schedule.

    ``least_loaded`` / ``predictive``: N unified workers, the last one
    degraded by ``slow_step_ms`` of per-round delay — the A/B that
    predictive routing must win on TTFT p99. ``chaos``: healthy workers
    plus a ``DSTPU_CHAOS`` self-kill on one of them mid-run (the
    training-side kill_rank spec, reused verbatim) and a scripted
    autoscale swing — measures p99.9 TTFT and zero drops through
    SIGKILL + restart + scale-up/drain. ``disagg``: prefill->decode over
    the socket with the int4 wire codec.
    """
    import threading

    import numpy as np

    from deepspeed_tpu.serving import (AutoscaleSignal, FleetRouter,
                                       ReplicaSupervisor)

    run_dir = os.path.join(base_dir, arm)
    engine = dict(engine_spec)
    if arm == "disagg":
        engine["handoff_wire"] = knobs["wire"]
    sup = ReplicaSupervisor(run_dir, model=model_spec, engine=engine,
                            seed=knobs["seed"])
    n_rep = knobs["replicas"]
    chaos_victim = None
    if arm == "disagg":
        remotes = [sup.spawn(role="prefill")]
        remotes += [sup.spawn(role="decode")
                    for _ in range(max(1, n_rep - 1))]
    elif arm == "chaos":
        remotes = [sup.spawn(role="unified")]
        # the victim self-kills via the training-side chaos spec after
        # kill_step busy serve rounds — no test scaffolding, the worker
        # dies exactly the way a chaos drill kills a training rank
        chaos_victim = sup.spawn(role="unified", env_extra={
            "DSTPU_CHAOS": (f"kill_rank=1,kill_step={knobs['kill_step']},"
                            f"kill_signal=SIGKILL")})
        remotes.append(chaos_victim)
        remotes += [sup.spawn(role="unified")
                    for _ in range(max(0, n_rep - 2))]
    else:
        remotes = [sup.spawn(role="unified")
                   for _ in range(max(1, n_rep - 1))]
        remotes.append(sup.spawn(role="unified",
                                 step_delay_ms=knobs["slow_step_ms"]))
    # chaos arm only: a signal whose organic thresholds can never fire
    # (queue_low < 0, queue_high huge), so the victim is not drained
    # out from under the chaos kill — the scripted desired swing and
    # the restart act are what land in its decision history
    autoscale = AutoscaleSignal(
        min_replicas=n_rep, max_replicas=n_rep + 2,
        queue_low=-1.0, queue_high=1e9) if arm == "chaos" else None
    router = FleetRouter(
        remotes, stale_after_s=knobs["stale_after_s"],
        affinity_blocks=0,
        routing="predictive" if arm in ("predictive", "chaos") else
        "least_loaded", autoscale=autoscale)
    sup.router = router

    n = len(prompts)
    first_tok = {}
    tlock = threading.Lock()
    t0_box = [None]

    def _wrap_new():
        for r in router.replicas.values():
            if getattr(r, "_bench_wrapped", False):
                continue
            orig_cb = r.emit_callback

            def cb(replica, emitted, _orig=orig_cb):
                if t0_box[0] is not None:
                    tnow = time.perf_counter() - t0_box[0]
                    with tlock:
                        for uid in emitted:
                            if uid not in first_tok:
                                first_tok[uid] = tnow
                _orig(replica, emitted)

            r.emit_callback = cb
            r._bench_wrapped = True

    _wrap_new()
    # compile warm-up OUTSIDE the timed window (run_slo's warm-pass
    # idiom): one request per worker. Routed THROUGH the router — cold
    # predictions tie, so load-score round-robins the warmups across
    # the workers — which doubles as a canary probe: by the time the
    # clock starts, the predictor has a measured service EWMA and
    # prefill rate for every replica instead of a cold-start guess
    # (a cold replica with no observed prefill rate predicts
    # optimistically and would swallow a whole burst). The chaos arm
    # warms via the stubs instead and skips the victim: its busy-round
    # budget belongs to the mid-run kill, and the predictor's cold
    # optimism toward the unprobed victim is exactly what feeds it
    # work before the kill fires.
    from deepspeed_tpu.serving.replica import Submission
    if arm == "chaos":
        warm = [r for r in remotes if r is not chaos_victim]
        for j, r in enumerate(warm):
            r.submit(Submission(uid=1_000_000 + j, tokens=prompts[0],
                                max_new_tokens=gen))

        def _warm_done():
            return all(r.load_report().get("inflight", 0) == 0
                       for r in warm)
    else:
        # TWO sequential rounds: round 1 pays the one-time JIT compile
        # (the router discards each signal's first per-replica sample
        # as exactly that), round 2 measures steady-state — its rates
        # are the first samples the EWMAs keep. Within a round the cold
        # predictions tie at zero, so the load-score tiebreak spreads
        # the probes one per replica.
        for wround in range(2):
            for j in range(len(remotes)):
                router.submit(1_000_000 + wround * len(remotes) + j,
                              prompts[0], max_new_tokens=gen)
            round_deadline = time.time() + 120.0
            while time.time() < round_deadline and router.pending() > 0:
                sup.maintain()
                router.check_health()
                time.sleep(0.05)

        def _warm_done():
            return router.pending() == 0

    warm_deadline = time.time() + 120.0
    while time.time() < warm_deadline and not _warm_done():
        sup.maintain()
        router.check_health()
        time.sleep(0.05)
    t0 = time.perf_counter()
    t0_box[0] = t0
    i = 0
    scaled_up = scaled_down = False
    last_maint = 0.0
    while i < n:
        now = time.perf_counter() - t0
        if arrivals[i] <= now:
            router.submit(i, prompts[i], max_new_tokens=gen)
            i += 1
            if autoscale is not None:
                # scripted swing: the signal demands one more replica
                # mid-burst, then releases it — maintain() does the
                # spin-up and the drain, both recorded in the history
                # fixed targets, not live-count deltas: a crash in the
                # same burst would make `live+1` collapse back to the
                # fleet size and the swing would never move the needle
                if not scaled_up and i >= int(0.5 * n):
                    autoscale.desired = n_rep + 1
                    scaled_up = True
                    sup.maintain()  # act now: a burst can starve the
                    _wrap_new()     # cadenced maintain past the swing
                elif scaled_up and not scaled_down and i >= int(0.85 * n):
                    autoscale.desired = max(1, n_rep)
                    scaled_down = True
                    sup.maintain()
            continue
        if now - last_maint >= knobs["maintain_s"]:
            sup.maintain()
            router.check_health()
            _wrap_new()
            last_maint = now
        time.sleep(min(max(arrivals[i] - now, 0.0), 0.01))
    deadline = time.time() + knobs["drain_timeout_s"]
    while time.time() < deadline:
        sup.maintain()
        router.check_health()
        _wrap_new()
        if router.pending() == 0:
            break
        time.sleep(0.02)
    wall = time.perf_counter() - t0
    snapshot_path = sup.write_fleet_snapshot()
    results = router.results()
    reports = [r.load_report() for r in sup.replicas.values()]
    transport = {r.name: dict(zip(("tx_bytes", "rx_bytes"),
                                  r.transport_bytes()))
                 for r in sup.replicas.values()}
    sup.shutdown()

    # uids >= 1e6 are router-routed warm-up probes, not workload
    results = {uid: t for uid, t in results.items() if uid < n}
    completed = sum(1 for t in results.values() if len(t) >= gen)
    total_tokens = sum(len(t) for t in results.values())
    ttfts = {uid: t - arrivals[uid] for uid, t in first_tok.items()
             if uid < n}
    good = sum(len(results.get(uid, [])) for uid, t in ttfts.items()
               if t <= deadline_s)
    wire = sum(r["handoff_wire_bytes"] for r in reports)
    logical = sum(r["handoff_logical_bytes"] for r in reports)
    out = {
        "arm": arm,
        "routing": router.routing,
        "requests": n,
        "completed": completed,
        "dropped": n - completed,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / max(wall, 1e-9), 1),
        "goodput_tokens_per_s": round(good / max(wall, 1e-9), 1),
        **_percentiles_ms(list(ttfts.values())),
        "handoffs": router.stats["handoffs"],
        "handoff_recompute": router.stats["handoff_recompute"],
        "failed_over_requests": router.stats["failed_over_requests"],
        "handoff_wire_bytes": wire,
        "handoff_logical_bytes": logical,
        "kv_wire_ratio": (round(wire / logical, 4) if logical else None),
        "transport": transport,
        "supervisor_actions": [[round(ts - t0, 3), act, rid]
                               for ts, act, rid in sup.actions],
        "fleet_snapshot": snapshot_path,
    }
    if autoscale is not None:
        out["autoscale_history"] = [
            list(h[1:]) for h in autoscale.history]
    return out


def run_procs() -> dict:
    """Cross-process fleet bench (``BENCH_MODE=serve_procs``,
    ``make serve-procs``): real worker subprocesses behind the socket
    transport, serving one diurnal + bursty open-loop workload through
    four arms — ``least_loaded`` vs ``predictive`` (same fleet with one
    degraded worker: the routing A/B), ``chaos`` (mid-run SIGKILL via
    the DSTPU_CHAOS kill_rank spec + a scripted autoscale swing: p99.9
    TTFT and the zero-drop guarantee), and ``disagg`` (prefill->decode
    KV handoffs over the int4 wire). One JSON line; violations ride the
    ``ok``/``violations`` keys, so ``tools/bench_diff.py`` fails the
    round on any broken gate.

    Gates: predictive TTFT p99 < least_loaded TTFT p99; chaos arm
    drops == 0 with a restart recorded and both scale acts in the
    autoscale decision history; disagg ships >=1 handoff with
    ``kv_wire_ratio`` <= PROCS_MAX_WIRE_RATIO (default 0.5 — int4 wire
    bytes vs the logical pool bytes) whose payloads crossed a real
    socket (the prefill channel's rx byte counter bounds them below).

    Env knobs (CPU defaults in parens): PROCS_REQUESTS (20) — a
    10k-session sweep on real accelerators is PROCS_REQUESTS=10000
    PROCS_RATE=200 PROCS_PERIOD_S=50 PROCS_GEN=32 PROCS_REPLICAS=8
    with PROCS_DRAIN_TIMEOUT_S raised to ~3600; PROCS_PROMPT (48),
    PROCS_SHARED_PREFIX (3/4 of prompt), PROCS_GEN (12), PROCS_RATE
    (1.5/s — ~1.2-1.5x one CPU worker's service rate, see
    _drive_procs_arm), PROCS_PERIOD_S (6) diurnal period,
    PROCS_BURST_FACTOR (3), PROCS_BURST_FRAC (0.2); PROCS_REPLICAS (2),
    PROCS_SLOW_STEP_MS (2000) — the degraded worker's per-round delay;
    PROCS_KILL_STEP (3) busy rounds before the chaos self-kill (a
    round emits decode_steps tokens per sequence, so one request is
    only a handful of busy rounds);
    PROCS_WIRE (int4), PROCS_MAX_WIRE_RATIO (0.5);
    PROCS_DEADLINE_MS (6000), PROCS_ARMS, PROCS_RUN_DIR, PROCS_SEED.
    """
    import numpy as np

    base_dir = os.environ.get("PROCS_RUN_DIR", "/tmp/dstpu_serve_procs")
    model_name = os.environ.get("PROCS_MODEL", "tiny")
    n_req = int(os.environ.get("PROCS_REQUESTS", 20))
    prompt_len = int(os.environ.get("PROCS_PROMPT", 48))
    shared_len = int(os.environ.get("PROCS_SHARED_PREFIX",
                                    prompt_len * 3 // 4))
    gen = int(os.environ.get("PROCS_GEN", 12))
    # ~1.2-1.5x the fast worker's CPU service rate: enough contention
    # that least-loaded overflows onto the degraded worker while the
    # predictor can still win by queueing on the fast one — full
    # saturation would make every policy equally bad
    rate = float(os.environ.get("PROCS_RATE", 1.5))
    period_s = float(os.environ.get("PROCS_PERIOD_S", 6.0))
    burst_factor = float(os.environ.get("PROCS_BURST_FACTOR", 3.0))
    burst_frac = float(os.environ.get("PROCS_BURST_FRAC", 0.2))
    deadline_s = float(os.environ.get("PROCS_DEADLINE_MS", 6000)) / 1e3
    seed = int(os.environ.get("PROCS_SEED", 0))
    arms = os.environ.get(
        "PROCS_ARMS", "least_loaded,predictive,chaos,disagg").split(",")
    block = 8
    blocks_per_seq = (prompt_len + gen) // block + 3

    model_spec = {"name": model_name,
                  "overrides": {"dtype": "float32",
                                "param_dtype": "float32"}}
    engine_spec = dict(
        kv_blocks=blocks_per_seq * max(4, n_req // 2) + 2,
        kv_block_size=block,
        max_tokens_per_step=int(os.environ.get("PROCS_BUDGET", 64)),
        max_seqs_per_step=8, max_blocks_per_seq=blocks_per_seq,
        dtype="float32", request_trace={"sample_rate": 1.0})

    rng = np.random.default_rng(seed)
    vocab = 256
    shared = rng.integers(0, vocab, (shared_len,))
    prompts = []
    for _ in range(n_req):
        motif = rng.integers(0, vocab, (4,))
        tail = np.tile(motif, (prompt_len - shared_len) // 4 + 1)
        prompts.append(np.concatenate(
            [shared, tail])[:prompt_len].astype(np.int32))
    arrivals = _nhpp_arrivals(n_req, rate, period_s, burst_factor,
                              burst_frac, rng)

    knobs = {
        "replicas": int(os.environ.get("PROCS_REPLICAS", 2)),
        "slow_step_ms": float(os.environ.get("PROCS_SLOW_STEP_MS", 2000.0)),
        # busy PUMP ROUNDS, not tokens: a round emits decode_steps
        # tokens per sequence, so one request is only ~4-5 busy rounds —
        # 3 lands the kill mid-first-request on the victim
        "kill_step": int(os.environ.get("PROCS_KILL_STEP", 3)),
        "wire": os.environ.get("PROCS_WIRE", "int4"),
        "stale_after_s": float(os.environ.get("PROCS_STALE_AFTER_S", 5.0)),
        "maintain_s": 0.05,
        "drain_timeout_s": float(os.environ.get("PROCS_DRAIN_TIMEOUT_S",
                                                300.0)),
        "seed": seed,
        "max_wire_ratio": float(os.environ.get("PROCS_MAX_WIRE_RATIO",
                                               0.5)),
    }
    results = {}
    for arm in arms:
        arm = arm.strip()
        results[arm] = _drive_procs_arm(
            arm, base_dir, model_spec, engine_spec, prompts, arrivals,
            gen, deadline_s, knobs)

    violations = []
    ll, pred = results.get("least_loaded"), results.get("predictive")
    if ll and pred and ll["ttft_p99_ms"] and pred["ttft_p99_ms"]:
        if pred["ttft_p99_ms"] >= ll["ttft_p99_ms"]:
            violations.append({
                "region": "routing", "gate": "predictive_beats_p99",
                "limit": ll["ttft_p99_ms"], "got": pred["ttft_p99_ms"]})
    chaos = results.get("chaos")
    if chaos:
        if chaos["dropped"] > 0:
            violations.append({
                "region": "chaos", "gate": "zero_drops",
                "limit": 0, "got": chaos["dropped"]})
        acts = [a[1] for a in chaos["supervisor_actions"]]
        if "restart" not in acts:
            violations.append({
                "region": "chaos", "gate": "restart_recorded",
                "limit": ">=1 restart", "got": acts})
        hist_acts = [h[1] for h in chaos.get("autoscale_history", [])
                     if len(h) == 2]
        if not any(a.startswith("spawn:") for a in hist_acts) or \
                not any(a.startswith("drain:") for a in hist_acts):
            violations.append({
                "region": "autoscale", "gate": "acts_in_history",
                "limit": "spawn + drain", "got": hist_acts})
    dis = results.get("disagg")
    if dis:
        if dis["handoffs"] < 1:
            violations.append({
                "region": "disagg", "gate": "handoffs",
                "limit": ">=1", "got": dis["handoffs"]})
        ratio = dis["kv_wire_ratio"]
        if ratio is None or ratio > knobs["max_wire_ratio"]:
            violations.append({
                "region": "disagg", "gate": "kv_wire_ratio",
                "limit": knobs["max_wire_ratio"], "got": ratio})
        prefill_rx = max((t["rx_bytes"]
                          for t in dis["transport"].values()), default=0)
        if dis["handoff_wire_bytes"] > 0 and \
                prefill_rx < dis["handoff_wire_bytes"]:
            violations.append({
                "region": "disagg", "gate": "wire_over_socket",
                "limit": dis["handoff_wire_bytes"], "got": prefill_rx})

    headline = pred or ll or chaos or dis
    return {
        "metric": f"{model_name} serve_procs tokens/s "
                  f"({knobs['replicas']} worker procs, {n_req} req, "
                  f"nhpp {rate}/s x{burst_factor} bursts, "
                  f"prompt {prompt_len}, gen {gen}, socket transport)",
        "value": headline["tokens_per_s"] if headline else None,
        "unit": "tokens/s",
        "ttft_p999_ms": (chaos or headline or {}).get("ttft_p999_ms"),
        "kv_wire_ratio": (dis or {}).get("kv_wire_ratio"),
        "deadline_ms": deadline_s * 1e3,
        "arms": results,
        "ok": not violations,
        "violations": violations,
    }


def _drive_chaos_arm(arm, base_dir, model_spec, engine_spec, prompts,
                     arrivals, gen, knobs):
    """One chaos-certification arm: the SAME workload and arrival
    schedule through a 2-worker socket fleet, with exactly one fault
    family armed.

    Net faults (``drop``/``delay``/``dup``/``corrupt``/``partition``)
    are armed as the process-global chaos injector in THIS process, so
    they hit the supervisor-side channel endpoints — real frames on the
    real socket. ``kill`` and ``crashloop`` reuse the worker-side
    ``DSTPU_CHAOS`` self-kill. ``hedge`` degrades one worker with a
    per-round delay and lets hedged requests race around it. Fault arms
    run with hedging enabled: a submit frame the fault family ate is a
    request with no stream anywhere, and the hedge deadline is what
    resurrects it (the seq-gap ChannelError then recycles the worker).
    """
    import threading

    from deepspeed_tpu.resilience.chaos import (ChaosInjector, ChaosSpec,
                                                reset_chaos_injector,
                                                set_chaos_injector)
    from deepspeed_tpu.serving import FleetRouter, ReplicaSupervisor
    from deepspeed_tpu.serving.replica import Submission

    net_specs = {
        "drop": f"net_drop_frac={knobs['drop_frac']},net_seed=7",
        "delay": "net_delay_ms=5",
        "dup": "net_dup=2",
        "corrupt": "net_corrupt=6",
        "partition": f"net_partition=r1:{knobs['partition_ops']}",
    }
    run_dir = os.path.join(base_dir, arm)
    crashloop = arm == "crashloop"
    sup = ReplicaSupervisor(
        run_dir, model=model_spec, engine=dict(engine_spec),
        seed=knobs["seed"],
        max_restarts_per_window=2 if crashloop else 3,
        restart_window_s=60.0 if crashloop else 30.0,
        min_healthy=1)
    n_rep = knobs["replicas"]
    remotes = [sup.spawn(role="unified")]
    if arm == "kill":
        remotes.append(sup.spawn(role="unified", env_extra={
            "DSTPU_CHAOS": "kill_rank=1,kill_step=2,kill_signal=SIGKILL"}))
    elif crashloop:
        # no kill_rank: every respawned incarnation crashes on its
        # first busy round — the supervisor's breaker must contain it
        remotes.append(sup.spawn(role="unified", env_extra={
            "DSTPU_CHAOS": "kill_step=1,kill_signal=SIGKILL"}))
    elif arm == "hedge":
        remotes.append(sup.spawn(role="unified",
                                 step_delay_ms=knobs["slow_step_ms"]))
    else:
        remotes += [sup.spawn(role="unified")
                    for _ in range(max(1, n_rep - 1))]
    router = FleetRouter(
        remotes, stale_after_s=knobs["stale_after_s"],
        affinity_blocks=0,
        # least_loaded for the hedge arm so the degraded worker keeps
        # RECEIVING work (predictive would learn to dodge it and the
        # hedge path would never fire)
        routing="least_loaded" if arm == "hedge" else "predictive",
        hedge_enabled=arm != "none",
        hedge_ttft_factor=2.0 if arm == "hedge" else 3.0,
        hedge_min_s=0.3 if arm == "hedge" else 1.0)
    sup.router = router

    n = len(prompts)
    first_tok = {}
    tlock = threading.Lock()
    t0_box = [None]

    def _wrap_new():
        for r in router.replicas.values():
            if getattr(r, "_bench_wrapped", False):
                continue
            orig_cb = r.emit_callback

            def cb(replica, emitted, _orig=orig_cb):
                if t0_box[0] is not None:
                    tnow = time.perf_counter() - t0_box[0]
                    with tlock:
                        for uid in emitted:
                            if uid not in first_tok:
                                first_tok[uid] = tnow
                _orig(replica, emitted)

            r.emit_callback = cb
            r._bench_wrapped = True

    _wrap_new()

    # each DSTPU_CHAOS incarnation gets one direct probe (uid >= 2e6,
    # outside the workload) so its busy-round kill actually fires —
    # routed traffic alone might starve a fresh replica and leave the
    # drill unexercised
    probed = set()

    def _probe_chaos_workers():
        for rid, remote in list(sup.replicas.items()):
            if rid in probed or remote.draining or remote.exited:
                continue
            if "DSTPU_CHAOS" not in (sup._env_extra.get(rid) or {}):
                continue
            probed.add(rid)
            remote.submit(Submission(uid=2_000_000 + rid,
                                     tokens=prompts[0],
                                     max_new_tokens=4))

    # compile warm-up OUTSIDE the timed window and BEFORE the injector
    # arms (a dropped warm probe would wedge the warm barrier): direct
    # stub probes, skipping DSTPU_CHAOS victims — their busy-round
    # budget belongs to the drill
    warm = [r for r in remotes
            if "DSTPU_CHAOS" not in (
                sup._env_extra.get(r.replica_id) or {})]
    for j, r in enumerate(warm):
        r.submit(Submission(uid=1_000_000 + j, tokens=prompts[0],
                            max_new_tokens=gen))
    warm_deadline = time.time() + 180.0
    while time.time() < warm_deadline and not all(
            r.load_report().get("inflight", 0) == 0 for r in warm):
        sup.maintain()
        router.check_health()
        time.sleep(0.05)

    if arm in net_specs:
        set_chaos_injector(
            ChaosInjector(ChaosSpec.parse(net_specs[arm]), rank=0))
    try:
        from deepspeed_tpu.resilience.chaos import get_chaos_injector

        t0 = time.perf_counter()
        t0_box[0] = t0
        i = 0
        last_maint = 0.0
        inj_stats = None
        while i < n:
            now = time.perf_counter() - t0
            if arrivals[i] <= now:
                router.submit(i, prompts[i], max_new_tokens=gen)
                i += 1
                continue
            if now - last_maint >= knobs["maintain_s"]:
                sup.maintain()
                router.check_health()
                _wrap_new()
                _probe_chaos_workers()
                last_maint = now
            time.sleep(min(max(arrivals[i] - now, 0.0), 0.01))
        if arm == "corrupt":
            # corruption is the one fault that kills workers faster
            # than the restart window forgives: every Nth frame corrupt
            # FOREVER means each failover burst re-corrupts, and the
            # breaker (correctly) quarantines the whole fleet — that is
            # a broken NIC, not a survivable fault. The drill models a
            # bounded corruption burst instead: faults through the
            # arrival window, clean wire for the drain, so what gets
            # certified is the recovery (CRC trip -> worker dies loud
            # -> restart + failover) and not a dead-wire verdict.
            inj_stats = dict(get_chaos_injector().net_stats)
            reset_chaos_injector()
        deadline = time.time() + knobs["drain_timeout_s"]
        while time.time() < deadline:
            sup.maintain()
            router.check_health()
            _wrap_new()
            _probe_chaos_workers()
            if router.pending() == 0:
                break
            time.sleep(0.02)
        if crashloop:
            # the workload can drain before the looper's final crash —
            # keep supervising until the breaker verdict is in (each
            # respawned incarnation is probed so its busy-round kill
            # actually fires)
            cl_deadline = time.time() + 60.0
            while time.time() < cl_deadline and not sup.quarantined:
                sup.maintain()
                router.check_health()
                _probe_chaos_workers()
                time.sleep(0.05)
        wall = time.perf_counter() - t0
        if inj_stats is None and arm in net_specs:
            inj_stats = dict(get_chaos_injector().net_stats)
    finally:
        if arm in net_specs:
            reset_chaos_injector()
    sup.write_fleet_snapshot()
    results = router.results()
    live_end = len(sup._live_ids())
    dup_frames = sum(getattr(r.channel, "dup_frames", 0)
                     for r in sup.replicas.values())
    sup.shutdown()

    results = {uid: t for uid, t in results.items() if uid < n}
    completed = sum(1 for t in results.values() if len(t) >= gen)
    total_tokens = sum(len(t) for t in results.values())
    ttfts = {uid: t - arrivals[uid] for uid, t in first_tok.items()
             if uid < n}
    acts = [a[1] for a in sup.actions]
    return {
        "arm": arm,
        "requests": n,
        "completed": completed,
        "dropped": n - completed,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / max(wall, 1e-9), 1),
        **_percentiles_ms(list(ttfts.values())),
        "tokens": {str(uid): results[uid] for uid in sorted(results)},
        "restarts": acts.count("restart"),
        "quarantines": acts.count("quarantine"),
        "quarantined_lineages": sorted(sup.quarantined),
        "drain_refused": acts.count("drain_refused"),
        "live_at_end": live_end,
        "failed_over_requests": router.stats["failed_over_requests"],
        "hedged": router.stats["hedged"],
        "hedge_wins": router.stats["hedge_wins"],
        "rx_dup_frames": dup_frames,
        "net_faults": inj_stats,
        "supervisor_actions": [[round(ts - t0, 3), act, rid]
                               for ts, act, rid in sup.actions],
    }


def run_chaos_fleet() -> dict:
    """Chaos-certification bench (``BENCH_MODE=chaos_fleet``,
    ``make chaos-fleet``): the PR-13 diurnal + bursty open-loop workload
    served through a socket process fleet while one fault family at a
    time is armed — ``drop``/``delay``/``dup``/``corrupt`` (seeded
    frame-level transport faults), ``partition`` (both directions of one
    worker's link blackholed for a wire-op window), ``kill`` (worker
    SIGKILLs itself mid-request), ``crashloop`` (every respawn crashes
    until the supervisor's circuit breaker quarantines the lineage), and
    ``hedge`` (one degraded worker, hedged requests race around it) —
    against a fault-free ``none`` baseline. One JSON line; violations
    ride ``ok``/``violations`` so ``tools/bench_diff.py`` fails the
    round on any broken gate.

    Gates: every arm drops zero requests (``chaos.zero_drops``); every
    completed stream is bit-identical to the fault-free baseline
    (``chaos.bit_identical`` — greedy decoding through failover,
    hedging, dups and partitions must not change a single token); the
    worst fault-arm TTFT p99.9 stays within CHAOS_MAX_P999_RATIO of the
    baseline (``chaos.ttft_p999_ratio``); the crash-looper is
    quarantined exactly once with restarts bounded by the breaker
    window and the min-healthy floor held; no other arm quarantines
    anything; the hedge arm records ``hedge_wins >= 1``
    (``chaos.hedge_wins``).

    Env knobs (CPU defaults in parens): CHAOS_FLEET_REQUESTS (8),
    CHAOS_FLEET_PROMPT (32), CHAOS_FLEET_GEN (8), CHAOS_FLEET_RATE
    (2.0/s), CHAOS_FLEET_PERIOD_S (4), CHAOS_FLEET_REPLICAS (2),
    CHAOS_FLEET_STALE_S (1.0), CHAOS_FLEET_SLOW_STEP_MS (1500),
    CHAOS_FLEET_DROP_FRAC (0.12), CHAOS_FLEET_PARTITION_OPS (60),
    CHAOS_MAX_P999_RATIO (50), CHAOS_FLEET_ARMS, CHAOS_FLEET_RUN_DIR,
    CHAOS_FLEET_SEED, CHAOS_FLEET_DRAIN_TIMEOUT_S (180)."""
    import numpy as np

    base_dir = os.environ.get("CHAOS_FLEET_RUN_DIR",
                              "/tmp/dstpu_chaos_fleet")
    model_name = os.environ.get("CHAOS_FLEET_MODEL", "tiny")
    n_req = int(os.environ.get("CHAOS_FLEET_REQUESTS", 8))
    prompt_len = int(os.environ.get("CHAOS_FLEET_PROMPT", 32))
    gen = int(os.environ.get("CHAOS_FLEET_GEN", 8))
    rate = float(os.environ.get("CHAOS_FLEET_RATE", 2.0))
    period_s = float(os.environ.get("CHAOS_FLEET_PERIOD_S", 4.0))
    seed = int(os.environ.get("CHAOS_FLEET_SEED", 0))
    max_ratio = float(os.environ.get("CHAOS_MAX_P999_RATIO", 50.0))
    arms = os.environ.get(
        "CHAOS_FLEET_ARMS",
        "none,drop,delay,dup,corrupt,partition,kill,crashloop,hedge"
    ).split(",")
    block = 8
    blocks_per_seq = (prompt_len + gen) // block + 3

    model_spec = {"name": model_name,
                  "overrides": {"dtype": "float32",
                                "param_dtype": "float32"}}
    engine_spec = dict(
        kv_blocks=blocks_per_seq * max(4, n_req) + 2,
        kv_block_size=block, max_tokens_per_step=64,
        max_seqs_per_step=8, max_blocks_per_seq=blocks_per_seq,
        dtype="float32", request_trace={"sample_rate": 1.0})

    rng = np.random.default_rng(seed)
    vocab = 256
    shared = rng.integers(0, vocab, (prompt_len * 3 // 4,))
    prompts = []
    for _ in range(n_req):
        tail = rng.integers(0, vocab,
                            (prompt_len - len(shared),))
        prompts.append(np.concatenate(
            [shared, tail]).astype(np.int32))
    arrivals = _nhpp_arrivals(n_req, rate, period_s, 3.0, 0.2, rng)

    knobs = {
        "replicas": int(os.environ.get("CHAOS_FLEET_REPLICAS", 2)),
        "stale_after_s": float(os.environ.get("CHAOS_FLEET_STALE_S",
                                              1.0)),
        "slow_step_ms": float(os.environ.get("CHAOS_FLEET_SLOW_STEP_MS",
                                             1500.0)),
        "drop_frac": float(os.environ.get("CHAOS_FLEET_DROP_FRAC",
                                          0.12)),
        "partition_ops": int(os.environ.get("CHAOS_FLEET_PARTITION_OPS",
                                            60)),
        "maintain_s": 0.05,
        "drain_timeout_s": float(os.environ.get(
            "CHAOS_FLEET_DRAIN_TIMEOUT_S", 180.0)),
        "seed": seed,
    }
    results = {}
    for arm in arms:
        arm = arm.strip()
        results[arm] = _drive_chaos_arm(
            arm, base_dir, model_spec, engine_spec, prompts, arrivals,
            gen, knobs)

    violations = []
    base = results.get("none")
    fault_arms = [a for a in results if a != "none"]
    for arm, r in results.items():
        if r["dropped"] > 0:
            violations.append({
                "region": arm, "gate": "zero_drops",
                "limit": 0, "got": r["dropped"]})
    bit_identical = True
    if base:
        for arm in fault_arms:
            if results[arm]["tokens"] != base["tokens"]:
                bit_identical = False
                diff = [u for u in base["tokens"]
                        if results[arm]["tokens"].get(u)
                        != base["tokens"][u]]
                violations.append({
                    "region": arm, "gate": "bit_identical",
                    "limit": "tokens == fault-free baseline",
                    "got": f"streams differ for uids {diff[:8]}"})
    p999_ratio = None
    if base and base.get("ttft_p999_ms"):
        worst = max((results[a]["ttft_p999_ms"] for a in fault_arms
                     if results[a].get("ttft_p999_ms")), default=None)
        if worst is not None:
            p999_ratio = round(worst / base["ttft_p999_ms"], 3)
            if p999_ratio > max_ratio:
                violations.append({
                    "region": "chaos", "gate": "ttft_p999_ratio",
                    "limit": max_ratio, "got": p999_ratio})
    cl = results.get("crashloop")
    if cl:
        if not cl["quarantined_lineages"]:
            violations.append({
                "region": "crashloop", "gate": "quarantined",
                "limit": ">=1 lineage", "got": cl["quarantines"]})
        if cl["quarantines"] > len(cl["quarantined_lineages"]):
            violations.append({
                "region": "crashloop", "gate": "no_quarantine_flaps",
                "limit": "one quarantine act per lineage",
                "got": cl["quarantines"]})
        if cl["restarts"] > 2:
            violations.append({
                "region": "crashloop", "gate": "restarts_bounded",
                "limit": 2, "got": cl["restarts"]})
        if cl["live_at_end"] < 1:
            violations.append({
                "region": "crashloop", "gate": "min_healthy_floor",
                "limit": ">=1 live worker", "got": cl["live_at_end"]})
    for arm in results:
        if arm != "crashloop" and results[arm]["quarantines"] > 0:
            violations.append({
                "region": arm, "gate": "no_stray_quarantine",
                "limit": 0, "got": results[arm]["quarantines"]})
    hedge = results.get("hedge")
    if hedge and hedge["hedge_wins"] < 1:
        violations.append({
            "region": "hedge", "gate": "hedge_wins",
            "limit": ">=1", "got": hedge["hedge_wins"]})
    for r in results.values():
        r.pop("tokens", None)  # compared above; too bulky to print

    return {
        "metric": f"{model_name} chaos_fleet tokens/s "
                  f"({knobs['replicas']} worker procs, {n_req} req, "
                  f"{len(results)} fault arms, socket transport)",
        "value": base["tokens_per_s"] if base else None,
        "unit": "tokens/s",
        "chaos.zero_drops": all(r["dropped"] == 0
                                for r in results.values()),
        "chaos.bit_identical": bit_identical,
        "chaos.ttft_p999_ratio": p999_ratio,
        "chaos.hedge_wins": hedge["hedge_wins"] if hedge else None,
        "chaos.quarantined": (len(cl["quarantined_lineages"])
                              if cl else None),
        "arms": results,
        "ok": not violations,
        "violations": violations,
    }


def _obs_clock_arm(arm: str, spec_text: str, skew_s: float,
                   rounds: int) -> dict:
    """One clock-sync accuracy arm: an echo worker subprocess whose wall
    clock is skewed by ``skew_s`` (DSTPU_CLOCK_SKEW_S in its env), pinged
    ``rounds`` times through a real socket channel while the parent-side
    chaos injector runs one net-fault family. Pings are interleaved with
    regular echo messages so the worker's 10 s recv timeout never fires
    and the parent's recv drains the pongs en route.

    ``net_drop`` is deliberately NOT in the matrix: a dropped frame is a
    sequence gap, i.e. a dead channel by design — clock sync on a dead
    channel is meaningless. Delay and dup are the faults a live channel
    survives. The delay arm slows every parent-side outbound frame,
    which both delays the ping's departure (after t0 is stamped) and —
    because the interleaved data send sleeps before the parent drains
    its socket — the pong's processing (t3): the round trip inflates by
    ~2x the delay, and the gate asserts the estimator's *widened*
    uncertainty still covers its true error (the honest-bound
    property), not that the error stays tiny."""
    import subprocess

    from deepspeed_tpu.observability.clocksync import ClockSyncEstimator
    from deepspeed_tpu.resilience.chaos import (ChaosInjector, ChaosSpec,
                                                reset_chaos_injector,
                                                set_chaos_injector)
    from deepspeed_tpu.serving.transport import ChannelError, SocketServer

    echo_worker = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "transport_echo_worker.py")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the worker never imports jax
    env["DSTPU_CLOCK_SKEW_S"] = repr(skew_s)
    env.pop("DSTPU_CHAOS", None)  # faults are parent-side only

    srv = SocketServer()
    proc = subprocess.Popen([sys.executable, echo_worker, str(srv.port)],
                            env=env)
    out = {"arm": arm, "synced": False, "offset_ms": None,
           "uncertainty_ms": None, "err_ms": None, "within_bound": False,
           "rounds": 0}
    chan = None
    try:
        chan = srv.accept(timeout=10.0)
        chan.clock = ClockSyncEstimator()
        if spec_text:
            set_chaos_injector(ChaosInjector(ChaosSpec.parse(spec_text)))
        try:
            for i in range(rounds):
                chan.ping_clock()
                chan.send({"type": "obs", "i": i})
                reply = chan.recv(timeout=10.0)
                if reply is None:
                    break
                out["rounds"] += 1
        finally:
            reset_chaos_injector()
        est = chan.clock
        out["synced"] = est.synced
        if est.synced:
            off, unc = est.offset_s, est.uncertainty_s
            err = abs(off - skew_s)
            out["offset_ms"] = round(off * 1e3, 3)
            out["uncertainty_ms"] = round(unc * 1e3, 3)
            out["err_ms"] = round(err * 1e3, 3)
            # honest-bound gate: the error must sit inside the
            # estimator's own reported uncertainty (+1 ms measurement
            # noise floor for CI jitter)
            out["within_bound"] = err <= unc + 1e-3
        chan.send({"type": "quit"})
    except ChannelError as e:
        out["error"] = str(e)
    finally:
        if chan is not None:
            chan.close()
        srv.close()
        try:
            proc.wait(timeout=10.0)
        except Exception:
            proc.kill()
            proc.wait(timeout=10.0)
    return out


def run_obs_fleet() -> dict:
    """Observability-plane certification (``BENCH_MODE=obs_fleet``,
    ``make obs-fleet``): two gates, one JSON line.

    1. **Tracing overhead** — drive N synthetic request lifecycles
       (enqueue/admit/prefill/emit*G/finish) through a RequestTracer at
       sample_rate=1.0 and again through a disabled tracer; the per-
       request delta must stay under OBS_MAX_TRACE_OVERHEAD_US
       (``obs.trace_overhead_ok``). This is the "tracing is within noise
       of the untraced serve bench" gate, measured at the emit points
       themselves so it cannot be washed out by model time.

    2. **Clock-sync accuracy under the chaos matrix** — a real echo-
       worker subprocess with a skewed wall clock (OBS_FLEET_SKEW_S,
       default 0.25 s — the ±250 ms fleet-skew scenario) is pinged
       through a socket channel under ``clean`` / ``delay``
       (net_delay_ms on the parent's wire path) / ``dup`` arms. Every
       arm must
       converge with |estimate - true skew| inside the estimator's OWN
       reported uncertainty (``obs.offset_bound_ok``) and under the
       absolute cap OBS_MAX_OFFSET_ERR_MS.

    Env knobs: OBS_TRACE_REQUESTS (200), OBS_TRACE_GEN (16),
    OBS_MAX_TRACE_OVERHEAD_US (250), OBS_FLEET_SKEW_S (0.25),
    OBS_CLOCK_ROUNDS (12), OBS_MAX_OFFSET_ERR_MS (50),
    OBS_FLEET_DELAY_MS (5), OBS_FLEET_ARMS (clean,delay,dup)."""
    from deepspeed_tpu.observability.request_trace import RequestTracer

    n_req = int(os.environ.get("OBS_TRACE_REQUESTS", 200))
    gen = int(os.environ.get("OBS_TRACE_GEN", 16))
    max_overhead_us = float(os.environ.get("OBS_MAX_TRACE_OVERHEAD_US",
                                           250.0))
    skew_s = float(os.environ.get("OBS_FLEET_SKEW_S", 0.25))
    rounds = int(os.environ.get("OBS_CLOCK_ROUNDS", 12))
    max_err_ms = float(os.environ.get("OBS_MAX_OFFSET_ERR_MS", 50.0))
    delay_ms = float(os.environ.get("OBS_FLEET_DELAY_MS", 5.0))
    arm_names = os.environ.get("OBS_FLEET_ARMS",
                               "clean,delay,dup").split(",")

    # -- gate 1: emit-point overhead, traced vs disabled ---------------
    def _drive(tracer: RequestTracer) -> float:
        t0 = time.perf_counter()
        for uid in range(n_req):
            tracer.on_enqueue(uid, prompt_tokens=32, queue_depth=1)
            tracer.on_admit(uid, wait_s=0.0)
            tracer.on_prefill(uid, start=time.time(), dur_ms=1.0,
                              tokens=32, start_pos=0)
            for _ in range(gen):
                tracer.on_emit(uid, 1)
            tracer.on_finish(uid)
        return time.perf_counter() - t0

    _drive(RequestTracer(enabled=True, sample_rate=1.0,
                         ring_size=n_req))  # warm up code paths
    traced_s = _drive(RequestTracer(enabled=True, sample_rate=1.0,
                                    ring_size=n_req))
    disabled_s = _drive(RequestTracer(enabled=False))
    overhead_us = max(0.0, (traced_s - disabled_s) / n_req * 1e6)

    # -- gate 2: clock offset accuracy under net faults ----------------
    specs = {"clean": "", "delay": f"net_delay_ms={delay_ms}",
             "dup": "net_dup=3"}
    arms = {}
    for arm in arm_names:
        arm = arm.strip()
        arms[arm] = _obs_clock_arm(arm, specs.get(arm, ""), skew_s,
                                   rounds)

    violations = []
    if overhead_us > max_overhead_us:
        violations.append({"region": "trace", "gate": "overhead_us",
                           "limit": max_overhead_us,
                           "got": round(overhead_us, 1)})
    for arm, r in arms.items():
        if not r["synced"]:
            violations.append({"region": arm, "gate": "clock_synced",
                               "limit": "estimator converged",
                               "got": r.get("error", "unsynced")})
            continue
        if not r["within_bound"]:
            violations.append({"region": arm, "gate": "offset_bound",
                               "limit": f"err <= {r['uncertainty_ms']}ms"
                                        " (own bound)",
                               "got": r["err_ms"]})
        if r["err_ms"] > max_err_ms:
            violations.append({"region": arm, "gate": "offset_err_ms",
                               "limit": max_err_ms, "got": r["err_ms"]})

    worst_err = max((r["err_ms"] for r in arms.values()
                     if r.get("err_ms") is not None), default=None)
    return {
        "metric": f"obs_fleet trace overhead ({n_req} req, "
                  f"{len(arms)} clock arms, skew {skew_s * 1e3:.0f}ms)",
        "value": round(overhead_us, 2),
        "unit": "us/request",
        "obs.trace_overhead_us": round(overhead_us, 2),
        "obs.trace_overhead_ok": overhead_us <= max_overhead_us,
        "obs.offset_err_ms": worst_err,
        "obs.offset_bound_ok": all(r["synced"] and r["within_bound"]
                                   for r in arms.values()),
        "arms": arms,
        "ok": not violations,
        "violations": violations,
    }


def _record_replay_arm(base_dir, journal_path, model_spec, engine_spec,
                       prompts, arrivals, gen, knobs, fault_spec):
    """Record arm of the replay bench: one chaos-fault pass of the
    2-worker socket fleet with the fleet journal installed in THIS
    (driver) process — so the router's ADMIT/ROUTE/EMIT ingress, the
    supervisor's lifecycle acts and the injector's frame-level faults
    all land in one journal, stamped with the config fingerprint and
    the literal re-drive recipe ``tools/replay.py`` consumes."""
    from deepspeed_tpu.observability.clocksync import wall_time
    from deepspeed_tpu.observability.journal import (FleetJournal,
                                                     config_fingerprint,
                                                     reset_journal,
                                                     set_journal)
    from deepspeed_tpu.resilience.chaos import (ChaosInjector, ChaosSpec,
                                                get_chaos_injector,
                                                reset_chaos_injector,
                                                set_chaos_injector)
    from deepspeed_tpu.serving import FleetRouter, ReplicaSupervisor
    from deepspeed_tpu.serving.replica import Submission

    n = len(prompts)
    n_rep = knobs["replicas"]
    router_kw = dict(stale_after_s=knobs["stale_after_s"],
                     affinity_blocks=0, routing="predictive",
                     hedge_enabled=True, hedge_ttft_factor=3.0,
                     hedge_min_s=1.0)
    recipe = {"model": model_spec, "seed": knobs["seed"],
              "engine": dict(engine_spec), "router": router_kw,
              "eos_token_id": None,
              "replicas": [{"replica_id": i, "role": "unified"}
                           for i in range(n_rep)]}
    jr = FleetJournal(journal_path, max_mb=64.0)
    set_journal(jr)
    jr.write_header(
        config_fingerprint(model=model_spec, engine=engine_spec,
                           router=router_kw, seed=knobs["seed"],
                           fault=fault_spec),
        replay=recipe, fault=fault_spec)

    sup = ReplicaSupervisor(
        os.path.join(base_dir, "record"), model=model_spec,
        engine=dict(engine_spec), seed=knobs["seed"], min_healthy=1)
    remotes = [sup.spawn(role="unified") for _ in range(n_rep)]
    router = FleetRouter(remotes, **router_kw)
    sup.router = router

    # compile warm-up outside the recorded workload: direct probes
    # (no router.submit, so nothing lands in the journal's admissions)
    for j, r in enumerate(remotes):
        r.submit(Submission(uid=1_000_000 + j, tokens=prompts[0],
                            max_new_tokens=gen))
    warm_deadline = time.time() + 180.0
    while time.time() < warm_deadline and not all(
            r.load_report().get("inflight", 0) == 0 for r in remotes):
        sup.maintain()
        router.check_health()
        time.sleep(0.05)

    if fault_spec:
        # the replayer re-arms exactly this spec (CHAOS_SPEC note)
        jr.note("CHAOS_SPEC", spec=fault_spec, rank=0)
        set_chaos_injector(
            ChaosInjector(ChaosSpec.parse(fault_spec), rank=0))
    # rebase the journal clock to the workload start so ADMIT offsets
    # encode the replayable arrival schedule, not spawn/warm-up time
    jr.t0 = wall_time()
    try:
        t0 = time.perf_counter()
        i = 0
        last_maint = 0.0
        while i < n:
            now = time.perf_counter() - t0
            if arrivals[i] <= now:
                router.submit(i, prompts[i], max_new_tokens=gen)
                i += 1
                continue
            if now - last_maint >= knobs["maintain_s"]:
                sup.maintain()
                router.check_health()
                last_maint = now
            time.sleep(min(max(arrivals[i] - now, 0.0), 0.01))
        if fault_spec:
            # bounded fault burst, same rationale as the chaos bench's
            # corrupt arm: faults through the arrival window, clean
            # wire for the drain. A fault armed FOREVER means each
            # failover burst re-trips it, and on a loaded box the
            # restart churn outruns the breaker window — that is a
            # broken NIC, not a survivable incident. The journal gate
            # certifies the capture of faults + recovery decisions and
            # the replay's bit-identity, not a dead-wire verdict.
            inj = get_chaos_injector()
            if inj is not None:
                jr.note("CHAOS_DISARM", stats=dict(inj.net_stats))
            reset_chaos_injector()
        deadline = time.time() + knobs["drain_timeout_s"]
        while time.time() < deadline:
            sup.maintain()
            router.check_health()
            if router.pending() == 0:
                break
            time.sleep(0.02)
        wall = time.perf_counter() - t0
    finally:
        if fault_spec:
            reset_chaos_injector()
    sup.write_fleet_snapshot()  # serving_fleet/v3 with the journal block
    results = router.results()
    live_end = len(sup._live_ids())
    sup.shutdown()
    stats = jr.snapshot()
    reset_journal()  # close + uninstall: the replay must not re-record

    results = {uid: t for uid, t in results.items() if uid < n}
    completed = sum(1 for t in results.values() if len(t) >= gen)
    total_tokens = sum(len(t) for t in results.values())
    acts = [a[1] for a in sup.actions]
    return {
        "requests": n,
        "completed": completed,
        "dropped": n - completed,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / max(wall, 1e-9), 1),
        "hedged": router.stats["hedged"],
        "failed_over_requests": router.stats["failed_over_requests"],
        "restarts": acts.count("restart"),
        "quarantines": acts.count("quarantine"),
        "live_at_end": live_end,
        "journal": stats,
    }


def run_replay_fleet() -> dict:
    """Fleet black-box certification (``BENCH_MODE=replay_fleet``,
    ``make replay-fleet``): record one chaos-fault fleet arm into the
    append-only journal (observability/journal.py), then (a) re-drive a
    fresh in-process fleet from the journal alone (``tools/replay.py``,
    scheduled-arrival mode) and require every replayed token stream
    bit-identical to the recorded checksum chains; (b) corrupt exactly
    one recorded chain link, replay again through the CLI path, and
    require a nonzero exit naming the exact diverging uid + decode
    step; (c) bound the recorder's cost — journal append overhead per
    request and journal bytes per request.

    Gates → bench_diff sentinels: ``replay.bit_identical``
    (must_stay_true), ``replay.journal_overhead_us`` (max_ratio),
    ``replay.journal_bytes_per_request`` (max_ratio).

    Env knobs (CPU defaults in parens): REPLAY_FLEET_REQUESTS (6),
    REPLAY_FLEET_PROMPT (32), REPLAY_FLEET_GEN (8), REPLAY_FLEET_RATE
    (2.0/s), REPLAY_FLEET_PERIOD_S (4), REPLAY_FLEET_REPLICAS (2),
    REPLAY_FLEET_STALE_S (1.0), REPLAY_FLEET_SEED (0),
    REPLAY_FLEET_FAULT (drop | delay | dup | none | raw ChaosSpec
    text), REPLAY_FLEET_MODE (scheduled | afap), REPLAY_FLEET_RUN_DIR
    (/tmp/dstpu_replay_fleet), REPLAY_MAX_JOURNAL_US (2500),
    REPLAY_MAX_JOURNAL_BYTES (8192),
    REPLAY_FLEET_DRAIN_TIMEOUT_S (180)."""
    import contextlib

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import replay as replay_tool

    from deepspeed_tpu.observability.journal import (dump_journal,
                                                     load_journal)

    base_dir = os.environ.get("REPLAY_FLEET_RUN_DIR",
                              "/tmp/dstpu_replay_fleet")
    model_name = os.environ.get("REPLAY_FLEET_MODEL", "tiny")
    n_req = int(os.environ.get("REPLAY_FLEET_REQUESTS", 6))
    prompt_len = int(os.environ.get("REPLAY_FLEET_PROMPT", 32))
    gen = int(os.environ.get("REPLAY_FLEET_GEN", 8))
    rate = float(os.environ.get("REPLAY_FLEET_RATE", 2.0))
    period_s = float(os.environ.get("REPLAY_FLEET_PERIOD_S", 4.0))
    seed = int(os.environ.get("REPLAY_FLEET_SEED", 0))
    mode = os.environ.get("REPLAY_FLEET_MODE", "scheduled")
    fault = os.environ.get("REPLAY_FLEET_FAULT", "drop")
    max_us = float(os.environ.get("REPLAY_MAX_JOURNAL_US", 2500.0))
    max_bytes = float(os.environ.get("REPLAY_MAX_JOURNAL_BYTES",
                                     8192.0))
    # delay injects without recording (nothing to journal); drop is the
    # default because every eaten frame lands as a CHAOS record
    fault_specs = {"drop": "net_drop_frac=0.12,net_seed=7",
                   "delay": "net_delay_ms=5", "dup": "net_dup=2",
                   "none": ""}
    fault_spec = fault_specs.get(fault, fault)
    block = 8
    blocks_per_seq = (prompt_len + gen) // block + 3
    model_spec = {"name": model_name,
                  "overrides": {"dtype": "float32",
                                "param_dtype": "float32"}}
    engine_spec = dict(
        kv_blocks=blocks_per_seq * max(4, n_req) + 2,
        kv_block_size=block, max_tokens_per_step=64,
        max_seqs_per_step=8, max_blocks_per_seq=blocks_per_seq,
        dtype="float32", request_trace={"sample_rate": 1.0})

    rng = np.random.default_rng(seed)
    vocab = 256
    shared = rng.integers(0, vocab, (prompt_len * 3 // 4,))
    prompts = []
    for _ in range(n_req):
        tail = rng.integers(0, vocab, (prompt_len - len(shared),))
        prompts.append(np.concatenate([shared, tail]).astype(np.int32))
    arrivals = _nhpp_arrivals(n_req, rate, period_s, 3.0, 0.2, rng)

    knobs = {
        "replicas": int(os.environ.get("REPLAY_FLEET_REPLICAS", 2)),
        "stale_after_s": float(os.environ.get("REPLAY_FLEET_STALE_S",
                                              1.0)),
        "maintain_s": 0.05,
        "drain_timeout_s": float(os.environ.get(
            "REPLAY_FLEET_DRAIN_TIMEOUT_S", 180.0)),
        "seed": seed,
    }
    os.makedirs(base_dir, exist_ok=True)
    journal_path = os.path.join(base_dir, "fleet.journal")
    record = _record_replay_arm(base_dir, journal_path, model_spec,
                                engine_spec, prompts, arrivals, gen,
                                knobs, fault_spec)

    # (a) clean replay: fresh in-process fleet from the journal alone
    with contextlib.redirect_stdout(sys.stderr):
        verdict = replay_tool.replay_journal(
            journal_path, mode=mode, perfetto=True,
            drain_timeout_s=knobs["drain_timeout_s"])

    # (b) corrupt one chain link mid-journal; the CLI replay must exit
    # nonzero and name exactly that uid + decode step
    records = load_journal(journal_path)
    corrupt_path = os.path.join(base_dir, "fleet.corrupt.journal")
    mut_uid = mut_step = None
    for rec in records:
        if rec.get("kind") == "EMIT" and rec.get("chain"):
            rec["chain"][-1] = int(rec["chain"][-1]) ^ 0x5A5A5A
            mut_uid = rec.get("uid")
            mut_step = int(rec.get("start", 0)) + len(rec["chain"]) - 1
            break
    dump_journal(corrupt_path, records)
    with contextlib.redirect_stdout(sys.stderr):
        corrupt_rc = replay_tool.main(
            [corrupt_path, "--mode", "afap", "--no-warm",
             "--drain-timeout-s", str(knobs["drain_timeout_s"])])
    try:
        with open(corrupt_path + ".verdict.json") as f:
            cd = json.load(f).get("first_divergence") or {}
    except (OSError, ValueError):
        cd = {}
    corrupt_named = (corrupt_rc != 0
                     and str(cd.get("uid")) == str(mut_uid)
                     and cd.get("step") == mut_step)

    overhead_us = record["journal"]["append_us_per_request"]
    bytes_pr = record["journal"]["bytes_per_request"]
    violations = []
    if record["dropped"] > 0:
        violations.append({"region": "record", "gate": "zero_drops",
                           "limit": 0, "got": record["dropped"]})
    if not verdict.get("bit_identical"):
        violations.append({
            "region": "replay", "gate": "bit_identical",
            "limit": "replayed streams == recorded chains",
            "got": verdict.get("first_divergence")})
    if overhead_us > max_us:
        violations.append({"region": "record",
                           "gate": "journal_overhead_us",
                           "limit": max_us, "got": overhead_us})
    if bytes_pr > max_bytes:
        violations.append({"region": "record",
                           "gate": "journal_bytes_per_request",
                           "limit": max_bytes, "got": bytes_pr})
    if mut_uid is None or not corrupt_named:
        violations.append({
            "region": "corrupt", "gate": "divergence_named",
            "limit": f"rc!=0 naming uid={mut_uid} step={mut_step}",
            "got": {"rc": corrupt_rc, "first_divergence": cd}})

    return {
        "metric": f"{model_name} replay_fleet journal overhead "
                  f"({n_req} req, {knobs['replicas']} worker procs, "
                  f"fault={fault or 'none'}, {mode} replay)",
        "value": overhead_us,
        "unit": "us/request",
        "replay.bit_identical": bool(verdict.get("bit_identical")),
        "replay.journal_overhead_us": overhead_us,
        "replay.journal_bytes_per_request": bytes_pr,
        "replay.verified_tokens": verdict.get("verified_tokens"),
        "replay.corrupt_detected": bool(corrupt_named),
        "record": record,
        "replay": {k: verdict.get(k) for k in
                   ("bit_identical", "requests", "verified_tokens",
                    "divergent_requests", "first_divergence", "mode",
                    "chaos_rearmed", "wall_s", "perfetto")},
        "corrupt": {"rc": corrupt_rc,
                    "expected": {"uid": mut_uid, "step": mut_step},
                    "first_divergence": cd},
        "ok": not violations,
        "violations": violations,
    }


def _drive_deploy_arm(arm, base_dir, model_spec, engine_spec, prompts,
                      arrivals, gen, knobs):
    """One deploy-drill arm. ``quiet`` is the reference: the diurnal
    peak workload through a plain 2-worker socket fleet, no events.
    ``drill`` serves the SAME workload and arrival schedule while the
    whole zero-downtime playbook runs against it in one pass:

    * one worker SIGKILLs itself mid-request (``DSTPU_CHAOS``) — the
      supervisor restarts it, the router fails the stream over;
    * a same-seed weight release rolls across the fleet
      (``rolling_swap``) while a designated long decode session is
      mid-stream — quiescing its owner migrates it out WARM (committed
      KV over the quantized wire, zero re-prefill on the target);
    * the autoscale signal swings desired up one (supervisor spawns)
      then back down (migration-backed drain of the newest worker);
    * after the drain, a release with deliberately corrupted canary
      chains is rolled — the A/B parity gate must abort the rollout,
      roll the replica back, and leave the fleet serving.

    Every event is gated later in ``run_deploy_drill``: zero drops,
    token streams bit-identical to the quiet arm, p99.9 TTFT ratio
    bounded, >=1 warm migration, parity-abort observed. The drill arm
    records a fleet journal so MIGRATE/SWAP/SCALE decisions land as
    replayable forensics."""
    import threading

    from deepspeed_tpu.observability.journal import (FleetJournal,
                                                     config_fingerprint,
                                                     reset_journal,
                                                     set_journal)
    from deepspeed_tpu.serving import FleetRouter, ReplicaSupervisor
    from deepspeed_tpu.serving.autoscale import AutoscaleSignal
    from deepspeed_tpu.serving.replica import Submission

    drill = arm == "drill"
    run_dir = os.path.join(base_dir, arm)
    os.makedirs(run_dir, exist_ok=True)
    jr = None
    if drill:
        jr = FleetJournal(os.path.join(run_dir, "journal.bin"),
                          max_mb=64.0)
        set_journal(jr)
        jr.write_header(config_fingerprint(
            model=model_spec, engine=engine_spec, seed=knobs["seed"],
            drill=True))
    sup = ReplicaSupervisor(
        run_dir, model=model_spec, engine=dict(engine_spec),
        seed=knobs["seed"], min_healthy=1)
    remotes = [sup.spawn(role="unified")]
    if drill:
        # the rush-hour casualty: SIGKILLs itself on its second busy
        # round (same self-kill the chaos bench certifies); its respawn
        # carries a different rank, so the kill fires exactly once
        remotes.append(sup.spawn(role="unified", env_extra={
            "DSTPU_CHAOS": "kill_rank=1,kill_step=2,kill_signal=SIGKILL"}))
    else:
        remotes += [sup.spawn(role="unified")
                    for _ in range(max(1, knobs["replicas"] - 1))]
    router = FleetRouter(
        remotes, stale_after_s=knobs["stale_after_s"],
        affinity_blocks=0, routing="predictive",
        hedge_enabled=drill, hedge_ttft_factor=3.0, hedge_min_s=1.0)
    sup.router = router
    auto = None
    if drill:
        # scripted swing: the drill drives ``desired`` directly (the
        # signal's own thresholds are certified in unit tests) — what
        # is certified HERE is that the supervisor closes the
        # desired-vs-live loop with spawn and migration-backed drain
        auto = AutoscaleSignal(min_replicas=knobs["replicas"],
                               max_replicas=knobs["replicas"] + 1)
        router.autoscale = auto

    n = len(prompts)
    mig_uid = 900_000  # the long session the swap must move warm
    first_tok = {}
    tlock = threading.Lock()
    t0_box = [None]

    def _wrap_new():
        for r in router.replicas.values():
            if getattr(r, "_bench_wrapped", False):
                continue
            orig_cb = r.emit_callback

            def cb(replica, emitted, _orig=orig_cb):
                if t0_box[0] is not None:
                    tnow = time.perf_counter() - t0_box[0]
                    with tlock:
                        for uid in emitted:
                            if uid not in first_tok:
                                first_tok[uid] = tnow
                _orig(replica, emitted)

            r.emit_callback = cb
            r._bench_wrapped = True

    _wrap_new()

    probed = set()

    def _probe_chaos_workers():
        for rid, remote in list(sup.replicas.items()):
            if rid in probed or remote.draining or remote.exited:
                continue
            if "DSTPU_CHAOS" not in (sup._env_extra.get(rid) or {}):
                continue
            probed.add(rid)
            remote.submit(Submission(uid=2_000_000 + rid,
                                     tokens=prompts[0],
                                     max_new_tokens=4))

    # warm-up outside the timed window, skipping the chaos victim (its
    # busy-round budget belongs to the drill)
    warm = [r for r in remotes
            if "DSTPU_CHAOS" not in (
                sup._env_extra.get(r.replica_id) or {})]
    for j, r in enumerate(warm):
        r.submit(Submission(uid=1_000_000 + j, tokens=prompts[0],
                            max_new_tokens=gen))
    warm_deadline = time.time() + 180.0
    while time.time() < warm_deadline and not all(
            r.load_report().get("inflight", 0) == 0 for r in warm):
        sup.maintain()
        router.check_health()
        time.sleep(0.05)

    if drill:
        # publish both releases before the clock starts: "v2" is the
        # honest same-seed release (bit-identical weights, so swapped
        # replicas keep producing the reference streams); "bad" seals a
        # VALID manifest around deliberately wrong canary chains — the
        # parity gate, not the checksum gate, must catch it
        sup.publish_weights("v2", seed=knobs["seed"],
                            canary_prompts=knobs["canary_prompts"],
                            canary_gen=knobs["canary_gen"])
        sup.publish_weights("bad", seed=knobs["seed"],
                            canary_prompts=knobs["canary_prompts"],
                            canary_gen=knobs["canary_gen"],
                            canary_chains={"0": [12345]})

    st = {"swap": None, "scaled_up": False, "scaled_down": False}

    def _events():
        if not drill:
            return
        if st["swap"] is None:
            # deploy mid-rush, but only after the SIGKILL casualty has
            # been restarted (the rollout walks LIVE replicas) and the
            # long session is provably mid-decode — that is what makes
            # the warm migration deterministic, not a timing race
            rec = router._requests.get(mig_uid)
            acts = [a[1] for a in sup.actions]
            if (rec is not None and not rec.done
                    and len(rec.emitted) >= 2
                    and "restart" in acts
                    and len(sup._live_ids()) >= knobs["replicas"]):
                st["swap"] = sup.rolling_swap(
                    "v2", timeout_s=knobs["swap_timeout_s"])
            return
        if not st["scaled_up"]:
            auto.desired = knobs["replicas"] + 1
            st["scaled_up"] = True
            return
        if (not st["scaled_down"]
                and len(sup._live_ids()) >= knobs["replicas"] + 1):
            auto.desired = knobs["replicas"]
            st["scaled_down"] = True

    t0 = time.perf_counter()
    t0_box[0] = t0
    # the designated migration victim: a decode stream long enough to
    # still be mid-flight when its owner quiesces for the swap; the
    # quiet arm runs it too, so its tokens are reference-compared
    router.submit(mig_uid, prompts[0],
                  max_new_tokens=knobs["mig_gen"])
    i = 0
    last_maint = 0.0
    while i < n:
        now = time.perf_counter() - t0
        if arrivals[i] <= now:
            router.submit(i, prompts[i], max_new_tokens=gen)
            i += 1
            continue
        if now - last_maint >= knobs["maintain_s"]:
            sup.maintain()
            router.check_health()
            _wrap_new()
            _probe_chaos_workers()
            _events()
            last_maint = now
        time.sleep(min(max(arrivals[i] - now, 0.0), 0.01))
    deadline = time.time() + knobs["drain_timeout_s"]
    while time.time() < deadline:
        sup.maintain()
        router.check_health()
        _wrap_new()
        _probe_chaos_workers()
        _events()
        if router.pending() == 0 and (not drill
                                      or st["scaled_down"]):
            break
        time.sleep(0.02)
    wall = time.perf_counter() - t0

    swap_bad = None
    post_abort_ok = None
    if drill:
        # parity-abort sub-drill on the live (now idle) fleet: the
        # corrupted release must abort, roll back, and leave the fleet
        # able to serve — certified by a probe request afterwards
        swap_bad = sup.rolling_swap("bad",
                                    timeout_s=knobs["swap_timeout_s"])
        router.submit(910_000, prompts[0], max_new_tokens=4)
        probe_deadline = time.time() + 60.0
        while time.time() < probe_deadline:
            sup.maintain()
            router.check_health()
            if router.pending() == 0:
                break
            time.sleep(0.02)
        post_abort_ok = len(router.results().get(910_000, [])) >= 4

    sup.write_fleet_snapshot()
    results = router.results()
    live_end = len(sup._live_ids())
    migrated_in = 0
    for r in sup.replicas.values():
        if r.exited or r._send_failed:
            continue
        try:
            migrated_in += int(r.load_report().get("migrated_in", 0))
        except Exception:
            pass
    sup.shutdown()
    journal_stats = None
    journal_warm = 0
    if jr is not None:
        journal_stats = jr.snapshot()
        jpath = jr.path
        reset_journal()
        # the durable evidence of a warm move: worker-side migrated_in
        # counters are wiped when the target itself gets swapped
        # (reload = fresh engine), so certify from the decision journal
        try:
            from deepspeed_tpu.observability.journal import load_journal
            journal_warm = sum(
                1 for rec in load_journal(jpath)
                if rec.get("kind") == "MIGRATE"
                and rec.get("rung") == "warm")
        except Exception:
            journal_warm = 0

    tokens = {str(uid): results[uid] for uid in sorted(results)
              if uid < n or uid == mig_uid}
    completed = sum(1 for uid in results
                    if uid < n and len(results[uid]) >= gen)
    mig_done = len(results.get(mig_uid, [])) >= knobs["mig_gen"]
    ttfts = {uid: t - arrivals[uid] for uid, t in first_tok.items()
             if uid < n}
    acts = [a[1] for a in sup.actions]
    rs = router.stats
    out = {
        "arm": arm,
        "requests": n + 1,
        "completed": completed + (1 if mig_done else 0),
        "dropped": (n - completed) + (0 if mig_done else 1),
        "wall_s": round(wall, 3),
        **_percentiles_ms(list(ttfts.values())),
        "tokens": tokens,
        "restarts": acts.count("restart"),
        "spawns": acts.count("spawn"),
        "drains": acts.count("drain"),
        "drain_refused": acts.count("drain_refused"),
        "live_at_end": live_end,
        "failed_over_requests": rs["failed_over_requests"],
        "migrations": rs["migrations"],
        "migrate_recompute": rs["migrate_recompute"],
        "migrate_skipped": rs["migrate_skipped"],
        "migrate_wire_bytes": rs["migrate_wire_bytes"],
        "migrated_in_workers": migrated_in,
        "supervisor_actions": [[round(ts - t0, 3), act, rid]
                               for ts, act, rid in sup.actions],
    }
    if drill:
        out["swap"] = st["swap"]
        out["swap_bad"] = swap_bad
        out["post_abort_probe_ok"] = post_abort_ok
        out["journal"] = journal_stats
        out["journal_warm_migrations"] = journal_warm
    return out


def run_deploy_drill() -> dict:
    """Deploy-during-rush-hour certification (``BENCH_MODE=
    deploy_drill``, ``make deploy-drill``): the PR-13 diurnal peak
    workload through a socket process fleet while the ENTIRE
    zero-downtime playbook runs in one pass — a worker SIGKILLed
    mid-request, a same-seed weight release rolled replica-by-replica
    (live sessions migrating out warm ahead of each reload, A/B canary
    parity gating each rejoin), an autoscale swing up and back down
    (migration-backed drain), and a corrupted-canary release whose
    parity gate must abort the rollout and roll back — against a quiet
    2-worker reference arm serving the same schedule.

    Gates: zero dropped requests in both arms (``drill.zero_drops``);
    every stream — including the deliberately migrated long session —
    bit-identical to the quiet arm (``drill.bit_identical``); drill
    TTFT p99.9 within DRILL_MAX_P999_RATIO of quiet
    (``drill.ttft_p999_ratio``); at least one session moved WARM with
    its wire bytes accounted (``migrate.wire_bytes_per_session``); the
    good rollout swaps every replica with parity intact
    (``swap.parity_ok``); the corrupted rollout aborts, rolls back,
    and the fleet still serves (``swap.abort_ok``); the autoscale
    swing both spawned and drained, ending at the floor.

    Env knobs (CPU defaults in parens): DRILL_REQUESTS (8),
    DRILL_PROMPT (32), DRILL_GEN (8), DRILL_MIG_GEN (48), DRILL_RATE
    (2.0/s), DRILL_PERIOD_S (4), DRILL_REPLICAS (2), DRILL_STALE_S
    (1.0), DRILL_MAX_P999_RATIO (80), DRILL_SEED (0), DRILL_RUN_DIR,
    DRILL_DRAIN_TIMEOUT_S (180), DRILL_SWAP_TIMEOUT_S (60)."""
    import numpy as np

    base_dir = os.environ.get("DRILL_RUN_DIR", "/tmp/dstpu_deploy_drill")
    model_name = os.environ.get("DRILL_MODEL", "tiny")
    n_req = int(os.environ.get("DRILL_REQUESTS", 8))
    prompt_len = int(os.environ.get("DRILL_PROMPT", 32))
    gen = int(os.environ.get("DRILL_GEN", 8))
    mig_gen = int(os.environ.get("DRILL_MIG_GEN", 48))
    rate = float(os.environ.get("DRILL_RATE", 2.0))
    period_s = float(os.environ.get("DRILL_PERIOD_S", 4.0))
    seed = int(os.environ.get("DRILL_SEED", 0))
    max_ratio = float(os.environ.get("DRILL_MAX_P999_RATIO", 80.0))
    n_rep = int(os.environ.get("DRILL_REPLICAS", 2))
    block = 8
    blocks_per_seq = (prompt_len + max(gen, mig_gen)) // block + 3

    model_spec = {"name": model_name,
                  "overrides": {"dtype": "float32",
                                "param_dtype": "float32"}}
    engine_spec = dict(
        kv_blocks=blocks_per_seq * max(4, n_req + 1) + 2,
        kv_block_size=block, max_tokens_per_step=64,
        max_seqs_per_step=8, max_blocks_per_seq=blocks_per_seq,
        dtype="float32", request_trace={"sample_rate": 1.0})

    rng = np.random.default_rng(seed)
    vocab = 256
    shared = rng.integers(0, vocab, (prompt_len * 3 // 4,))
    prompts = []
    for _ in range(n_req):
        tail = rng.integers(0, vocab, (prompt_len - len(shared),))
        prompts.append(np.concatenate([shared, tail]).astype(np.int32))
    arrivals = _nhpp_arrivals(n_req, rate, period_s, 3.0, 0.2, rng)
    canary_prompts = [
        [int(t) for t in rng.integers(0, vocab, (prompt_len // 2,))]
        for _ in range(2)]

    knobs = {
        "replicas": n_rep,
        "stale_after_s": float(os.environ.get("DRILL_STALE_S", 1.0)),
        "maintain_s": 0.05,
        "drain_timeout_s": float(os.environ.get(
            "DRILL_DRAIN_TIMEOUT_S", 180.0)),
        "swap_timeout_s": float(os.environ.get(
            "DRILL_SWAP_TIMEOUT_S", 60.0)),
        "seed": seed,
        "mig_gen": mig_gen,
        "canary_prompts": canary_prompts,
        "canary_gen": 8,
    }
    quiet = _drive_deploy_arm("quiet", base_dir, model_spec,
                              engine_spec, prompts, arrivals, gen,
                              knobs)
    drill = _drive_deploy_arm("drill", base_dir, model_spec,
                              engine_spec, prompts, arrivals, gen,
                              knobs)

    violations = []
    for r in (quiet, drill):
        if r["dropped"] > 0:
            violations.append({"region": r["arm"], "gate": "zero_drops",
                               "limit": 0, "got": r["dropped"]})
    bit_identical = drill["tokens"] == quiet["tokens"]
    if not bit_identical:
        diff = [u for u in quiet["tokens"]
                if drill["tokens"].get(u) != quiet["tokens"][u]]
        violations.append({
            "region": "drill", "gate": "bit_identical",
            "limit": "tokens == quiet reference",
            "got": f"streams differ for uids {diff[:8]}"})
    p999_ratio = None
    if quiet.get("ttft_p999_ms") and drill.get("ttft_p999_ms"):
        p999_ratio = round(drill["ttft_p999_ms"]
                           / quiet["ttft_p999_ms"], 3)
        if p999_ratio > max_ratio:
            violations.append({
                "region": "drill", "gate": "ttft_p999_ratio",
                "limit": max_ratio, "got": p999_ratio})
    if drill["migrations"] < 1:
        violations.append({
            "region": "drill", "gate": "warm_migrations",
            "limit": ">=1", "got": drill["migrations"]})
    # worker-side migrated_in counters die with the target's own swap
    # reload, so the warm-install proof comes from the decision journal
    if drill.get("journal_warm_migrations", 0) < 1:
        violations.append({
            "region": "drill", "gate": "journal_warm_migrations",
            "limit": ">=1", "got": drill.get("journal_warm_migrations")})
    wire_per_session = (
        round(drill["migrate_wire_bytes"]
              / max(1, drill["migrations"]), 1)
        if drill["migrations"] else None)
    swap = drill.get("swap") or {}
    parity_ok = bool(swap and not swap.get("aborted")
                     and swap.get("parity_ok")
                     and swap.get("swapped", 0) >= 1)
    if not parity_ok:
        violations.append({
            "region": "swap", "gate": "parity_ok",
            "limit": "rollout completes with canary parity",
            "got": swap or "swap never ran"})
    bad = drill.get("swap_bad") or {}
    abort_ok = bool(bad.get("aborted")
                    and bad.get("parity_ok") is False
                    and bad.get("rolled_back", 0) >= 1
                    and drill.get("post_abort_probe_ok"))
    if not abort_ok:
        violations.append({
            "region": "swap", "gate": "abort_ok",
            "limit": "corrupt canary aborts + rolls back + serves",
            "got": {"swap_bad": bad,
                    "post_abort_probe_ok":
                        drill.get("post_abort_probe_ok")}})
    if drill["spawns"] < 1 or drill["drains"] < 1:
        violations.append({
            "region": "autoscale", "gate": "swing",
            "limit": ">=1 spawn and >=1 migration-backed drain",
            "got": {"spawns": drill["spawns"],
                    "drains": drill["drains"]}})
    if drill["live_at_end"] != n_rep:
        violations.append({
            "region": "autoscale", "gate": "settled_at_floor",
            "limit": n_rep, "got": drill["live_at_end"]})
    for r in (quiet, drill):
        r.pop("tokens", None)  # compared above; too bulky to print

    total_tokens_s = None
    if quiet["wall_s"]:
        total_tokens_s = round(
            (quiet["requests"] - 1) * gen / quiet["wall_s"], 1)
    return {
        "metric": f"{model_name} deploy_drill "
                  f"({n_rep} worker procs, {n_req}+1 req, kill + "
                  f"rolling swap + autoscale swing, socket transport)",
        "value": total_tokens_s,
        "unit": "tokens/s",
        "drill.zero_drops": all(r["dropped"] == 0
                                for r in (quiet, drill)),
        "drill.bit_identical": bit_identical,
        "drill.ttft_p999_ratio": p999_ratio,
        "drill.warm_migrations": drill["migrations"],
        "swap.parity_ok": parity_ok,
        "swap.abort_ok": abort_ok,
        "migrate.wire_bytes_per_session": wire_per_session,
        "arms": {"quiet": quiet, "drill": drill},
        "ok": not violations,
        "violations": violations,
    }


if __name__ == "__main__":
    mode = os.environ.get("BENCH_MODE", "serve")
    if mode == "serve_fleet":
        for arm_result in run_fleet():
            print(json.dumps(arm_result))
    elif mode == "serve_procs":
        _pp = run_procs()
        print(json.dumps(_pp))
        if not _pp.get("ok", True):
            raise SystemExit(1)
    elif mode == "chaos_fleet":
        _cp = run_chaos_fleet()
        print(json.dumps(_cp))
        if not _cp.get("ok", True):
            raise SystemExit(1)
    elif mode == "obs_fleet":
        _op = run_obs_fleet()
        print(json.dumps(_op))
        if not _op.get("ok", True):
            raise SystemExit(1)
    elif mode == "deploy_drill":
        _dp = run_deploy_drill()
        print(json.dumps(_dp))
        if not _dp.get("ok", True):
            raise SystemExit(1)
    elif mode == "replay_fleet":
        _rp = run_replay_fleet()
        print(json.dumps(_rp))
        if not _rp.get("ok", True):
            raise SystemExit(1)
    elif mode == "serve_quant":
        _qp = run_quant()
        print(json.dumps(_qp))
        if not _qp.get("ok", True):
            raise SystemExit(1)
    elif mode == "serve_tier":
        _tp = run_tier()
        print(json.dumps(_tp))
        if not _tp.get("ok", True):
            raise SystemExit(1)
    else:
        print(json.dumps(run_slo() if mode == "serve_slo" else run()))
