"""Serving trace viewer: the "why did p99 miss" report from a trace dump.

The serving engine's RequestTracer (observability/request_trace.py)
keeps a tail-sampled ring of finished request traces — every SLO
violator plus a random slice of the healthy bulk. ``make serve-slo``
with ``SLO_TRACE=1`` (and any embedding application via
``tracer.dump_jsonl()``) writes that ring as a JSON-lines file; this
tool is the read side — pure host code, no jax:

  python tools/serve_top.py TRACES.jsonl                # attribution table
  python tools/serve_top.py TRACES.jsonl --json         # raw report dict
  python tools/serve_top.py TRACES.jsonl --deadline-ms 500
  python tools/serve_top.py TRACES.jsonl --worst 5      # slowest requests
  python tools/serve_top.py TRACES.jsonl --chrome-trace --out lanes.json
                                                        # Perfetto export
  python tools/serve_top.py --demo                      # CPU demo run
  python tools/serve_top.py --fleet SNAP.json           # fleet snapshot
  python tools/serve_top.py --fleet RUN_DIR             # cross-process run
  python tools/serve_top.py --fleet --demo              # 2-replica demo
  python tools/serve_top.py --journal J                 # incident log
  python tools/serve_top.py --replay-verdict V          # replay verdict

``--fleet`` reads a ``serving_fleet/v3`` snapshot document
(``FleetRouter.fleet_snapshot()``; ``make serve-fleet`` writes one per
arm into FLEET_TRACE_DIR) — v1/v2 documents from older runs still
render, minus newer columns — and prints the per-replica load-report
table
(including the PR 15 health state machine state and hedge counters),
the router counters (handoffs, failovers, affinity hits, hedges), the
autoscale state, the supervisor's restart/quarantine tallies, and the
fleet-level SLO attribution with per-replica miss counts. Given a *directory* (a ``make serve-procs`` run dir), it loads
the supervisor's merged ``fleet_snapshot.json`` — falling back to the
raw per-worker reports under ``<run_dir>/replicas/`` — so a
cross-process fleet is observable mid-run from a second terminal.

The table decomposes each request's TTFT and e2e wall time into
queue_wait / prefill / decode / preempted / spec_overhead phases and
names the dominant phase of every missed request — the answer to "what
do I fix first" (docs/serving.md "Request tracing & SLO attribution").

``--journal`` reads a fleet black-box journal
(observability/journal.py, recorded by any journaled router run or
``make replay-fleet``) and prints the human-readable incident log —
every admission, routing decision WITH its per-candidate scores,
preemption/hedge/failover/autoscale/supervisor act with its triggering
state, live-migration/weight-swap/scale act (MIGRATE with source and
target scores + landed rung, SWAP with its parity verdict per stage,
SCALE with desired-vs-actual), and chaos injection, on one
wall-clock-offset timeline —
followed by the per-request outcome table. ``--replay-verdict`` prints
a ``tools/replay.py`` verdict (a ``*.verdict.json`` file, or a journal
path whose verdict sits next to it) and exits nonzero on divergence
(docs/observability.md "Fleet black box & incident replay").
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from deepspeed_tpu.observability.request_trace import (
    load_traces_jsonl, slo_attribution, slo_attribution_markdown)


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="serve_top")
    p.add_argument("traces", nargs="?",
                   help="request-trace JSON-lines file "
                        "(RequestTracer.dump_jsonl / make serve-slo "
                        "SLO_TRACE=1 output)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="TTFT SLO deadline; default: the deadline "
                        "stamped in the trace file, if any")
    p.add_argument("--json", action="store_true",
                   help="emit the raw attribution report dict as JSON")
    p.add_argument("--worst", type=int, default=0, metavar="N",
                   help="also list the N slowest-TTFT requests with "
                        "their phase split")
    p.add_argument("--chrome-trace", action="store_true",
                   help="export per-request Perfetto lanes and exit")
    p.add_argument("--out", default="request_lanes.json",
                   help="output path for --chrome-trace")
    p.add_argument("--demo", action="store_true",
                   help="run a small CPU serve_step workload through the "
                        "v2 engine and print its attribution table")
    p.add_argument("--fleet", action="store_true",
                   help="treat the positional arg as a serving_fleet/v2 "
                        "snapshot (FleetRouter.fleet_snapshot / make "
                        "serve-fleet) or a cross-process run dir (make "
                        "serve-procs) and print the per-replica fleet "
                        "view; with --demo, run a 2-replica in-process "
                        "fleet first")
    p.add_argument("--journal", metavar="PATH",
                   help="print the incident log + per-request outcome "
                        "table from a fleet black-box journal "
                        "(observability/journal.py)")
    p.add_argument("--replay-verdict", metavar="PATH",
                   help="print a tools/replay.py verdict (a "
                        "*.verdict.json, or a journal path with one "
                        "next to it); exits 1 on divergence")
    return p.parse_args(argv)


def _stamped_deadline_ms(path: str):
    """Recover the SLO deadline dump_jsonl stamps on every line."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                return json.loads(line).get("slo_deadline_ms")
            except json.JSONDecodeError:
                return None
    return None


def _worst_table(traces, n: int) -> str:
    scored = [t for t in traces if t.ttft_s is not None]
    scored.sort(key=lambda t: -t.ttft_s)
    lines = ["", f"### {min(n, len(scored))} slowest requests (by TTFT)", "",
             "| trace | ttft (ms) | e2e (ms) | preempts | "
             "dominant ttft phase | phase split (ms) |",
             "|---|---|---|---|---|---|"]
    for t in scored[:n]:
        tph = t.ttft_phases()
        dom = max(tph, key=lambda k: tph[k]) if any(tph.values()) else "-"
        split = " ".join(f"{k}={v * 1e3:.1f}"
                         for k, v in t.phases().items() if v > 0)
        lines.append(f"| {t.trace_id} | {t.ttft_s * 1e3:.1f} | "
                     f"{(t.e2e_s or 0) * 1e3:.1f} | {t.preemptions} | "
                     f"{dom} | {split} |")
    return "\n".join(lines)


def _run_demo() -> int:
    """Tiny-model serving burst on CPU: more offered load than the KV
    pool fits, so the queue/preemption paths actually show up in the
    table. Everything stays in-process."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models.zoo import get_model

    import jax.numpy as jnp

    model = get_model("tiny", dtype=jnp.float32, param_dtype=jnp.float32)
    deadline_ms = 200.0
    # 20-block pool vs 10 requests growing to ~7 blocks each: the pool
    # exhausts mid-decode, so the table shows real preempt round trips
    engine = InferenceEngineV2(
        model, kv_blocks=20, kv_block_size=8, max_tokens_per_step=32,
        max_seqs_per_step=4, max_blocks_per_seq=16, prefix_cache=True,
        spec_decode=True,
        request_trace={"sample_rate": 1.0,
                       "slo_deadline_ms": deadline_ms})
    rng = np.random.default_rng(0)
    shared = rng.integers(0, model.config.vocab_size, (16,))
    prompts = [np.concatenate([shared, rng.integers(
        0, model.config.vocab_size, (8,))]).astype(np.int32)
        for _ in range(10)]
    engine.put(list(range(len(prompts))), prompts, max_new_tokens=40)
    engine.generate_all()
    traces = engine.tracer.finished()
    rep = slo_attribution(traces, deadline_s=deadline_ms / 1e3)
    print(slo_attribution_markdown(rep))
    print(_worst_table(traces, 3))
    snap = engine.snapshot()
    print(f"\n=> {rep['requests']} requests traced "
          f"({snap['request_trace']['kept']} kept, "
          f"{snap['stats']['preempted']} preemptions, "
          f"prefix hits {snap['stats']['prefix_hit_tokens']} tokens)")
    return 0


def _fleet_table(snap: dict) -> str:
    """Render a serving_fleet/v2 snapshot as the fleet dashboard
    (v1 documents render too — health falls back to the dead set)."""
    lines = [f"## serving fleet ({snap.get('mode', '?')} mode)", "",
             "| replica | role | steps | queue | live | inflight | "
             "kv free | goodput tok/s | kv quant | wire | "
             "handoff wire/logical | host tier | spec acc | kv SNR dB | "
             "state |",
             "|---|---|---|---|---|---|---|---|---|---|---|---|---|"
             "---|---|"]
    dead = set(snap.get("dead_replicas", []))
    health = snap.get("health") or {}  # v2; absent in v1 documents
    for r in snap.get("replicas", []):
        h = health.get(str(r["replica"]))
        if h:
            state = h["state"]
            if h.get("transitions"):
                state += f" ({h['transitions']}x)"
        else:
            state = ("DEAD" if r["replica"] in dead
                     else "killed" if r.get("killed") else "up")
        bits = r.get("kv_quant_bits")
        quant = ("bf16" if bits is None
                 else bits if isinstance(bits, str) else f"int{bits}")
        wire = r.get("handoff_wire", "auto")
        wb, lb = (r.get("handoff_wire_bytes", 0),
                  r.get("handoff_logical_bytes", 0))
        hand = f"{wb}/{lb}" if lb else "-"
        # host-tier occupancy: bytes parked below HBM + parked session
        # count ("-" for an HBM-only replica)
        tb, ts = r.get("host_tier_bytes", 0), r.get("host_tier_sessions", 0)
        tier = f"{tb / (1 << 20):.1f}MB/{ts}s" if tb or ts else "-"
        acc = r.get("spec_accept_ewma")
        acc_s = "-" if acc is None else f"{acc:.2f}"
        snr = r.get("kv_wire_snr_db")
        snr_s = "-" if snr is None else f"{snr:.1f}"
        lines.append(
            f"| r{r['replica']} | {r['role']} | {r['steps']} | "
            f"{r['queue_wait_depth']} | {r['live_seqs']} | "
            f"{r['inflight']} | {r['kv_free_frac'] * 100:.0f}% | "
            f"{r['goodput_tokens_per_s']} | {quant} | {wire} | "
            f"{hand} | {tier} | {acc_s} | {snr_s} | {state} |")
    st = snap.get("router", {})
    lines += ["", "router: " + "  ".join(
        f"{k}={st[k]}" for k in ("submitted", "completed", "handoffs",
                                 "handoff_recompute", "failovers",
                                 "failed_over_requests", "affinity_hits",
                                 "tier_affinity_hits",
                                 "hedged", "hedge_wins",
                                 "migrations", "migrate_recompute",
                                 "migrate_skipped")
        if k in st)]
    auto = snap.get("autoscale")
    if auto:
        lines += ["autoscale: desired_replicas="
                  f"{auto.get('desired_replicas')} "
                  f"goodput_slope={auto.get('goodput_slope')} "
                  f"decisions={len(auto.get('decisions', []))}"]
    sup = snap.get("supervisor")
    if sup:
        procs = sup.get("procs", {})
        up = sum(1 for p in procs.values() if p.get("running"))
        acts = sup.get("actions", [])
        tail = "  ".join(f"{a['action']}:r{a['replica']}"
                         for a in acts[-6:])
        lines += [f"supervisor: {up}/{len(procs)} worker processes up  "
                  f"actions={len(acts)}" + (f"  [{tail}]" if tail else "")]
        extra = []
        if "restarts" in sup:
            extra.append(f"restarts={sup['restarts']}")
        if sup.get("quarantined"):
            q = ",".join(f"r{r}" for r in sup["quarantined"])
            extra.append(f"quarantined=[{q}]")
        if sup.get("pending_restarts"):
            extra.append(f"pending_restarts={sup['pending_restarts']}")
        if "min_healthy" in sup:
            extra.append(f"min_healthy={sup['min_healthy']}")
        if extra:
            lines += ["containment: " + "  ".join(extra)]
        wire = sup.get("transport", {})
        if wire:
            lines += ["transport: " + "  ".join(
                f"r{rid}:tx={w['tx_bytes']}:rx={w['rx_bytes']}"
                for rid, w in sorted(wire.items(),
                                     key=lambda kv: int(kv[0])))]
    clock = snap.get("clock") or {}
    if clock:
        parts = []
        for rid, c in sorted(clock.items(), key=lambda kv: str(kv[0])):
            if c.get("synced"):
                parts.append(f"r{rid}:offset={c['offset_ms']:+.2f}ms"
                             f"±{c['uncertainty_ms']:.2f}")
            else:
                parts.append(f"r{rid}:unsynced({c.get('samples', 0)})")
        lines += ["clock: " + "  ".join(parts)]
    alerts = snap.get("alerts")
    if alerts:
        ev = alerts.get("last_eval") or {}
        state = "FIRING" if alerts.get("firing") else "ok"
        lines += [f"slo alert [{state}]: "
                  f"objective={alerts.get('objective')} "
                  f"deadline={alerts.get('deadline_ms')}ms "
                  f"burn fast={ev.get('burn_fast', 0)} "
                  f"slow={ev.get('burn_slow', 0)} "
                  f"fired={alerts.get('stats', {}).get('alerts_fired', 0)}"]
    fm = snap.get("fleet_metrics") or {}
    if fm.get("counters") or fm.get("histograms"):
        lines += ["", "### fleet metrics (transport plane, "
                  f"{len(fm.get('replicas', []))} workers)", ""]
        if fm.get("counters"):
            lines += ["counters: " + "  ".join(
                f"{k}={v}" for k, v in sorted(fm["counters"].items()))]
        hists = fm.get("histograms") or {}
        if hists:
            lines += ["", "| histogram | count | mean | p50 | p95 | p99 |",
                      "|---|---|---|---|---|---|"]
            for name in sorted(hists):
                h = hists[name]
                lines.append(
                    f"| {name} | {h['count']} | {h['mean']:.4g} | "
                    f"{h['p50']:.4g} | {h['p95']:.4g} | {h['p99']:.4g} |")
        if fm.get("stale"):
            stale = "  ".join(f"{r}:{age}s"
                              for r, age in sorted(fm["stale"].items()))
            lines += [f"stale workers: {stale}"]
    attr = snap.get("slo_attribution") or {}
    per = attr.get("per_replica") or {}
    if per:
        lines += ["", "### fleet SLO attribution", "",
                  "| replica | traces | slo misses |", "|---|---|---|"]
        for rid in sorted(per, key=lambda x: int(x)):
            row = per[rid]
            lines.append(f"| r{rid} | {row['traces']} | "
                         f"{row['slo_misses']} |")
        if attr.get("miss_dominant_phase"):
            lines.append(f"\ndominant miss phase: "
                         f"{attr['miss_dominant_phase']}")
    return "\n".join(lines)


def _run_fleet_demo() -> int:
    """Two in-process unified replicas over a shared-prefix burst, then
    the fleet dashboard — the multi-replica analog of --demo."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from deepspeed_tpu.config.config import RouterConfig
    from deepspeed_tpu.serving.router import build_fleet
    from deepspeed_tpu.models.zoo import get_model

    model = get_model("tiny")
    router = build_fleet(model, RouterConfig(replicas=2), engine_kw=dict(
        kv_blocks=24, kv_block_size=8, max_tokens_per_step=32,
        max_seqs_per_step=4, max_blocks_per_seq=8, prefix_cache=True,
        request_trace={"sample_rate": 1.0, "slo_deadline_ms": 200.0}))
    rng = np.random.default_rng(0)
    shared = rng.integers(0, model.config.vocab_size, (16,))
    for uid in range(8):
        tail = rng.integers(0, model.config.vocab_size, (8,))
        router.submit(uid, np.concatenate([shared, tail]).astype(np.int32),
                      max_new_tokens=12)
    router.run_until_complete()
    print(_fleet_table(router.fleet_snapshot(deadline_s=0.2)))
    return 0


def _load_run_dir_snapshot(run_dir: str):
    """Cross-process fleets: prefer the supervisor's merged
    ``fleet_snapshot.json``; fall back to assembling a minimal snapshot
    from the per-replica load reports the workers publish under
    ``<run_dir>/replicas/`` — readable mid-run with no socket to join
    and no jax import."""
    path = os.path.join(run_dir, "fleet_snapshot.json")
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    from deepspeed_tpu.observability.fleet import read_replica_reports

    reports = read_replica_reports(run_dir)
    if not reports:
        return None
    roles = {r.get("role") for r in reports.values()}
    return {"schema": "serving_fleet/v2",
            "mode": "disagg" if "prefill" in roles else "unified",
            "replicas": [reports[k] for k in sorted(reports)]}


def _journal_report(path: str) -> str:
    """Incident log + per-request outcome table from a black-box
    journal: the decision timeline first (what the fleet did and what
    state it saw when it did it), then one row per request with its
    decision count and final outcome."""
    from deepspeed_tpu.observability.journal import (load_journal,
                                                     render_incident_log,
                                                     request_outcomes)

    records = load_journal(path)
    if not records:
        return f"serve_top: no complete journal records in {path}"
    lines = list(render_incident_log(records)) + [""]
    outcomes = request_outcomes(records)
    if outcomes:
        lines.append(f"{'uid':>8}  {'prompt':>6}  {'max_new':>7}  "
                     f"{'arrival+s':>9}  {'emitted':>7}  "
                     f"{'decisions':>9}  outcome")
        for o in outcomes.values():
            arr = o.get("arrival_offset_s")
            lines.append(
                f"{str(o['uid']):>8}  {o['prompt']:>6}  "
                f"{o['max_new_tokens']:>7}  "
                f"{(f'{arr:.3f}' if arr is not None else '-'):>9}  "
                f"{o['emitted']:>7}  {len(o['decisions']):>9}  "
                f"{o['outcome']}")
    return "\n".join(lines)


def _print_replay_verdict(path: str) -> int:
    """Render a replay verdict document; accepts either the
    ``*.verdict.json`` itself or the journal it sits next to."""
    vpath = path
    if not path.endswith(".verdict.json") and \
            os.path.exists(path + ".verdict.json"):
        vpath = path + ".verdict.json"
    try:
        with open(vpath) as f:
            verdict = json.load(f)
    except (OSError, ValueError) as e:
        print(f"serve_top: cannot read replay verdict {vpath}: {e}",
              file=sys.stderr)
        return 2
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from replay import divergence_report

    print(divergence_report(verdict))
    return 0 if verdict.get("bit_identical") else 1


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.journal:
        print(_journal_report(args.journal))
        return 0
    if args.replay_verdict:
        return _print_replay_verdict(args.replay_verdict)
    if args.fleet:
        if args.demo:
            return _run_fleet_demo()
        if not args.traces:
            print("serve_top: error: --fleet needs a snapshot file or "
                  "run dir (or --demo)", file=sys.stderr)
            return 2
        if os.path.isdir(args.traces):
            snap = _load_run_dir_snapshot(args.traces)
            if snap is None:
                print(f"serve_top: no fleet_snapshot.json or replicas/ "
                      f"reports under {args.traces}", file=sys.stderr)
                return 1
        else:
            with open(args.traces) as f:
                snap = json.load(f)
        if snap.get("schema") not in ("serving_fleet/v1",
                                      "serving_fleet/v2",
                                      "serving_fleet/v3"):
            print(f"serve_top: {args.traces} is not a serving_fleet "
                  f"v1/v2/v3 snapshot (schema={snap.get('schema')!r})",
                  file=sys.stderr)
            return 1
        print(_fleet_table(snap))
        return 0
    if args.demo:
        return _run_demo()
    if not args.traces:
        print("serve_top: error: no trace file (or --demo)",
              file=sys.stderr)
        return 2
    traces = load_traces_jsonl(args.traces)
    if not traces:
        print(f"serve_top: no traces in {args.traces}", file=sys.stderr)
        return 1
    if args.deadline_ms is None:
        args.deadline_ms = _stamped_deadline_ms(args.traces)
    if args.chrome_trace:
        from deepspeed_tpu.observability.chrome_trace import \
            export_request_traces

        export_request_traces(args.out, traces)
        print(f"wrote {len(traces)} request lanes to {args.out} "
              f"(open in Perfetto or chrome://tracing)")
        return 0
    report = slo_attribution(traces, deadline_s=(
        args.deadline_ms / 1e3 if args.deadline_ms is not None else None))
    if args.json:
        print(json.dumps(report, sort_keys=True))
        return 0
    print(slo_attribution_markdown(report))
    if args.worst:
        print(_worst_table(traces, args.worst))
    return 0


if __name__ == "__main__":
    sys.exit(main())
