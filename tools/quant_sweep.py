#!/usr/bin/env python
"""quant_sweep — the ZeRO++ before/after attribution table.

Sweeps the quantization knob grid {qwZ on/off} x {qgZ on/off} x {hpZ
partition size} over the analytic quantized-comm attribution
(``observability/attribution.py attribute_quant_step``) at a given
shape — by default the real 8L · 131k-vocab llama3-8b geometry — and
prints the before/after table docs/quantized_comm.md and
docs/roofline.md embed: per mode, the wire GB and roofline ms of the
``param_fetch`` and ``grad_reduce`` regions, the exposed comm ms after
the overlap engine's staged schedule, and the saving vs the all-off
baseline.

Entirely analytic (eval_shape for the byte model, closed-form wire
ratios, no compiled step) so it runs on CPU CI like
``latency_hiding_probe --analytic``. The error side of each mode —
whether the bytes saved cost acceptable precision — is the
``BENCH_QUANT=1`` arm's job (``make bench-quant``); this tool answers
the bytes/time side.

``--persist PATH`` writes the winning mode into the autotuner's
real-shape defaults file (docs/autotuned/real_shape.json) as the
``quant_mode`` key — the same file/key the ``quant_modes`` autotuner
axis persists and bench.py reads back.

Usage:
  python tools/quant_sweep.py                        # markdown table
  python tools/quant_sweep.py --json                 # machine-readable
  python tools/quant_sweep.py --chips 64 --slice 8 --hpz 1 8 16
  python tools/quant_sweep.py --persist docs/autotuned/real_shape.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCHEMA = "quant_sweep/v1"

# the knobs the persisted real-shape defaults carry besides quant_mode —
# kept in lockstep with the bench's measured defaults
# (bench.resolve_bench_defaults) so persisting a quant choice never
# shifts an untuned knob
MEASURED_REAL_SHAPE_DEFAULTS: Dict[str, Any] = {
    "train_micro_batch_size_per_chip": 4,
    "remat": True,
    "remat_policy": "nothing_saveable",
    "tiled_logits": 8,
    "attn_chunks": 0,
    "performance": {"param_prefetch_depth": 4, "overlap_depth": 4},
}


def build_sweep(cfg, *, n_chips: int, slice_size: int,
                hpz_list: List[int], micro: int, seq: int,
                peak_tflops: float, overlap_depth: int,
                ici_gbps: Optional[float] = None,
                dcn_gbps: Optional[float] = None) -> Dict[str, Any]:
    """Evaluate the knob grid; returns the JSON payload (schema
    ``quant_sweep/v1``) with one row per mode and the winner by total
    exposed comm ms."""
    from deepspeed_tpu.autotuning.autotuner import format_quant_mode
    from deepspeed_tpu.observability.attribution import (
        attribute_quant_step, overlap_split_ms)

    # the compute window transfers hide behind: one fwd/bwd layer stage
    flops_step = cfg.flops_per_token() * micro * seq
    compute_ms = flops_step / (peak_tflops * 1e12) * 1e3
    stages = 2 * max(cfg.num_layers, 1)
    stage_ms = compute_ms / stages

    rows: List[Dict[str, Any]] = []
    for qwz in (False, True):
        for qgz in (False, True):
            for hpz in hpz_list:
                regions = attribute_quant_step(
                    cfg, qwz=qwz, qgz=qgz, hpz=hpz, n_chips=n_chips,
                    slice_size=slice_size, ici_gbps=ici_gbps,
                    dcn_gbps=dcn_gbps)
                row: Dict[str, Any] = {
                    "mode": format_quant_mode(qwz, qgz, hpz),
                    "qwz": qwz, "qgz": qgz, "hpz": int(hpz),
                    "regions": {}, "wire_gb": 0.0, "exposed_ms": 0.0,
                }
                for r in regions:
                    ms = r.bytes_accessed / (r.gbps * 1e9) * 1e3
                    if r.overlapped:
                        split = overlap_split_ms(ms, stage_ms,
                                                 overlap_depth, stages)
                        exposed = split["exposed_ms"]
                    else:
                        exposed = ms
                    row["regions"][r.region] = {
                        "wire_gb": round(r.bytes_accessed / 1e9, 3),
                        "roofline_ms": round(ms, 2),
                        "exposed_ms": round(exposed, 2),
                        "link": r.link, "gbps": round(r.gbps, 2),
                        "note": r.note,
                    }
                    row["wire_gb"] += r.bytes_accessed / 1e9
                    row["exposed_ms"] += exposed
                row["wire_gb"] = round(row["wire_gb"], 3)
                row["exposed_ms"] = round(row["exposed_ms"], 2)
                rows.append(row)

    base = rows[0]  # qwz=False, qgz=False, first hpz — the off baseline
    for row in rows:
        row["wire_vs_off"] = (round(row["wire_gb"] / base["wire_gb"], 3)
                              if base["wire_gb"] else 1.0)
        row["exposed_vs_off"] = (
            round(row["exposed_ms"] / base["exposed_ms"], 3)
            if base["exposed_ms"] else 1.0)
    winner = min(rows, key=lambda r: (r["exposed_ms"], r["wire_gb"]))
    return {
        "schema": SCHEMA,
        "shape": {"model": "llama3-8b", "layers": cfg.num_layers,
                  "vocab": cfg.vocab_size, "seq": seq, "micro": micro,
                  "n_params": cfg.num_params()},
        "topology": {"n_chips": n_chips, "slice_size": slice_size},
        "overlap_depth": overlap_depth,
        "stage_ms": round(stage_ms, 3),
        "peak_tflops": peak_tflops,
        "rows": rows,
        "winner": {"mode": winner["mode"],
                   "exposed_ms": winner["exposed_ms"],
                   "wire_gb": winner["wire_gb"]},
    }


def sweep_markdown(payload: Dict[str, Any]) -> str:
    sh, topo = payload["shape"], payload["topology"]
    lines = [
        "### ZeRO++ quantization knob sweep — "
        f"{sh['model']} {sh['layers']}L vocab {sh['vocab']:,} "
        f"(analytic, {topo['n_chips']} chips / slice "
        f"{topo['slice_size']}, overlap_depth "
        f"{payload['overlap_depth']})", "",
        "| mode | param_fetch GB | fetch link | fetch ms | "
        "grad_reduce GB | reduce link | reduce ms | wire vs off | "
        "exposed ms | vs off |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for row in payload["rows"]:
        pf = row["regions"]["param_fetch"]
        gr = row["regions"]["grad_reduce"]
        mark = " ←" if row["mode"] == payload["winner"]["mode"] else ""
        lines.append(
            f"| {row['mode']}{mark} | {pf['wire_gb']:.2f} | "
            f"{pf['link']} | {pf['roofline_ms']:,.0f} | "
            f"{gr['wire_gb']:.2f} | {gr['link']} | "
            f"{gr['roofline_ms']:,.0f} | {row['wire_vs_off']:.3f}x | "
            f"{row['exposed_ms']:,.0f} | {row['exposed_vs_off']:.3f}x |")
    lines += [
        "",
        f"Winner: **{payload['winner']['mode']}** at "
        f"{payload['winner']['exposed_ms']:,.0f} ms exposed comm "
        f"({payload['winner']['wire_gb']:.2f} GB wire). Roofline ms = "
        "region bytes / link GB/s; exposed ms subtracts what the "
        "overlap engine hides behind the per-layer compute window "
        f"(stage {payload['stage_ms']:.1f} ms).",
    ]
    return "\n".join(lines)


def persist_winner(payload: Dict[str, Any], path: str) -> Dict[str, Any]:
    """Merge the winning quant_mode into the autotuner's persisted
    real-shape defaults (creating the file with the measured-default
    knobs when absent). Existing tuned keys are preserved — this only
    writes the quantization choice."""
    from deepspeed_tpu.autotuning.autotuner import parse_quant_mode

    try:
        with open(path) as f:
            tuned = json.load(f)
    except Exception:
        tuned = json.loads(json.dumps(MEASURED_REAL_SHAPE_DEFAULTS))
    mode = payload["winner"]["mode"]
    tuned["quant_mode"] = mode
    zo = tuned.setdefault("zero_optimization", {})
    zo.update(parse_quant_mode(mode))
    tuned["_quant_sweep"] = {
        "schema": payload["schema"],
        "topology": payload["topology"],
        "exposed_ms": payload["winner"]["exposed_ms"],
        "wire_gb": payload["winner"]["wire_gb"],
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(tuned, f, indent=2)
        f.write("\n")
    return tuned


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="quant_sweep",
        description="ZeRO++ {qwZ x qgZ x hpZ} before/after comm "
                    "attribution sweep (analytic, CPU-safe)")
    ap.add_argument("--model", default="llama3-8b")
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=131072)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--chips", type=int, default=16,
                    help="pod projection size (chips)")
    ap.add_argument("--slice", type=int, default=8, dest="slice_size",
                    help="chips per ICI slice; groups larger than this "
                         "ride DCN")
    ap.add_argument("--hpz", type=int, nargs="+", default=[1, 8],
                    help="hpZ partition sizes to sweep (1 = off)")
    ap.add_argument("--overlap-depth", type=int, default=4)
    ap.add_argument("--peak-tflops", type=float, default=None)
    ap.add_argument("--ici-gbps", type=float, default=None)
    ap.add_argument("--dcn-gbps", type=float, default=None)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--persist", default=None, metavar="PATH",
                    help="merge the winning quant_mode into this "
                         "real-shape defaults JSON "
                         "(docs/autotuned/real_shape.json)")
    args = ap.parse_args(argv)

    import dataclasses

    import jax

    from deepspeed_tpu.models.zoo import get_model
    from deepspeed_tpu.observability.roofline import detect_peak_tflops

    model = get_model(args.model, max_seq_len=args.seq)
    cfg = dataclasses.replace(model.config, num_layers=args.layers,
                              vocab_size=args.vocab)
    peak = args.peak_tflops or detect_peak_tflops(jax.devices()[0])

    payload = build_sweep(
        cfg, n_chips=args.chips, slice_size=args.slice_size,
        hpz_list=list(args.hpz), micro=args.micro, seq=args.seq,
        peak_tflops=peak, overlap_depth=args.overlap_depth,
        ici_gbps=args.ici_gbps, dcn_gbps=args.dcn_gbps)

    if args.persist:
        tuned = persist_winner(payload, args.persist)
        payload["persisted"] = {"path": args.persist,
                                "quant_mode": tuned["quant_mode"]}
    if args.json:
        print(json.dumps(payload, indent=2))
    else:
        print(sweep_markdown(payload))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
