"""Measure XLA's latency-hiding of per-layer parameter fetches (VERDICT
r3 weak #6 / next-round #4).

The ZeRO-3 story in this framework rests on XLA's latency-hiding
scheduler overlapping per-layer parameter all-gathers (or, in the
offload_param tier, host→device layer copies — the same fetch-on-use
structure against a slower link) with the previous layer's compute; the
reference instead hand-schedules prefetch (partitioned_param_coordinator
.py:310) and DeepCompile claims 1.28-1.54x from graph passes. This probe
measures the claim on the real chip:

  * config: llama3-8b layer geometry, depth N, offload_param streaming
    (each scan step fetches one fp32 layer from pinned host memory — a
    per-layer fetch of the same shape class as a pod's fsdp all-gather,
    over a link slow enough that failure to overlap is unmissable);
  * run A: the default program — XLA free to schedule/overlap fetches;
  * run B: the same model with DSTPU_SERIALIZE_FETCH=1 — an
    optimization barrier chains each layer's fetch on the previous
    layer's output, so the H2D copy provably cannot overlap compute
    (a program-level control that works on every backend; the axon
    build rejects the scheduler XLA_FLAGS);
  * overlap fraction = 1 - stepA/stepB. ~0 means XLA was not hiding
    anything (the DeepCompile-equivalent work item); >0.2 means the
    fetch pipeline is hiding meaningful copy time behind compute.

Run on a TPU host:   python tools/latency_hiding_probe.py
Outputs one JSON line; paste the result into docs/latency_hiding.md.

The probe re-execs itself with the env knob for run B (the model trace
reads it once).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LAYERS = int(os.environ.get("PROBE_LAYERS", "6"))
MICRO = int(os.environ.get("PROBE_MICRO", "4"))
SEQ = int(os.environ.get("PROBE_SEQ", "2048"))
STEPS = int(os.environ.get("PROBE_STEPS", "5"))



def measure() -> float:
    import jax
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.zoo import get_model

    model = get_model("llama3-8b", num_layers=LAYERS, vocab_size=8192,
                      max_seq_len=SEQ, remat=True,
                      remat_policy="nothing_saveable")
    config = {
        "train_micro_batch_size_per_chip": MICRO,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {
            "stage": 2,
            "offload_optimizer": {"device": "cpu",
                                  "grad_transfer_dtype": "bf16"},
            "offload_param": {"device": "cpu"},
        },
        "bf16": {"enabled": True},
        "steps_per_print": 10**6,
    }
    engine, *_ = dstpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    B = engine.micro_batch_size * engine.dp_world_size
    batch = {"input_ids": rng.integers(0, 8192, (B, SEQ + 1)).astype(np.int32)}

    def it():
        while True:
            yield batch

    data = it()
    # measure the DEVICE program only (grad_step), not the host optimizer:
    # the fetch-overlap question lives in the compiled fwd/bwd
    batches = engine._next_microbatches(data, engine.gradient_accumulation_steps)
    import jax.numpy as jnp

    scale = jnp.asarray(1.0, jnp.float32)
    grads, loss = engine._jit_grad_step(engine.params, batches, scale)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        grads, loss = engine._jit_grad_step(engine.params, batches, scale)
    jax.block_until_ready((grads, loss))
    return (time.perf_counter() - t0) / STEPS


def main():
    if os.environ.get("_PROBE_MODE") == "run":
        print(json.dumps({"step_s": measure()}))
        return
    env_a = dict(os.environ, _PROBE_MODE="run")
    env_b = dict(env_a, DSTPU_SERIALIZE_FETCH="1")

    def run(env):
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True)
        for line in reversed(out.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)["step_s"]
        raise RuntimeError(f"probe run failed:\n{out.stdout}\n{out.stderr}")

    a = run(env_a)  # overlap free
    b = run(env_b)  # fetches serialized by data dependency
    print(json.dumps({
        "metric": "offload_param per-layer-fetch overlap (llama3-8b geom)",
        "layers": LAYERS, "micro": MICRO, "seq": SEQ,
        "step_overlap_s": round(a, 4), "step_serialized_s": round(b, 4),
        "overlap_fraction": round(1.0 - a / b, 4) if b > 0 else None,
    }))


if __name__ == "__main__":
    main()
