"""Latency-hiding probe: exposed-vs-hidden transfer time, as JSON.

Two modes, one ``latency_hiding_probe/v2`` schema:

* ``--analytic`` (default off-TPU cost: one compile, runs on CPU CI):
  attribute the step per region (observability/attribution.py), split
  each transfer region into exposed vs hidden ms under the overlap
  engine's staged schedule at ``--overlap-depth`` — the same
  ``overlap_split_ms`` model the bench and docs/roofline.md round-7
  table use. k=0 reports the measured reality of the default schedule
  (no hiding); k>0 reports what the pin_stage staging buys.

* measured (no flag): the original A/B experiment on the attached
  chips. Run A is the default program (XLA free to schedule the
  per-layer host→device fetches); run B re-execs with
  DSTPU_SERIALIZE_FETCH=1, an optimization barrier chaining each
  layer's fetch on the previous layer's output so the copy provably
  cannot overlap compute. overlap_fraction = 1 - stepA/stepB: ~0 means
  XLA hid nothing on its own (the measured v5e-1 result that motivated
  the overlap engine — docs/latency_hiding.md); the measured dict rides
  alongside the analytic split so one JSON carries both.

History: VERDICT r3 weak #6 / round-4. The ZeRO-3 story originally
rested on XLA's latency-hiding scheduler overlapping per-layer fetches
(reference hand-schedules prefetch, partitioned_param_coordinator
.py:310; DeepCompile claims 1.28-1.54x from graph passes); measurement
refuted the assumption and runtime/param_stream.py's explicit ring +
pin_stage staging is the replacement.

Usage:
    python tools/latency_hiding_probe.py --analytic [--overlap-depth 2]
    python tools/latency_hiding_probe.py            # measured A/B (TPU)
Outputs one JSON document on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "latency_hiding_probe/v2"


def _args(argv=None):
    ap = argparse.ArgumentParser(
        prog="latency_hiding_probe",
        description="exposed-vs-hidden transfer report (JSON)")
    ap.add_argument("--analytic", action="store_true",
                    help="attribution-based split only; no timed runs "
                         "(works on CPU)")
    ap.add_argument("--model", default="llama3-8b")
    ap.add_argument("--layers", type=int,
                    default=int(os.environ.get("PROBE_LAYERS", "6")))
    ap.add_argument("--micro", type=int,
                    default=int(os.environ.get("PROBE_MICRO", "4")))
    ap.add_argument("--seq", type=int,
                    default=int(os.environ.get("PROBE_SEQ", "2048")))
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--steps", type=int,
                    default=int(os.environ.get("PROBE_STEPS", "5")))
    ap.add_argument("--overlap-depth", type=int, default=int(
        os.environ.get("DSTPU_OVERLAP_DEPTH", "0")))
    ap.add_argument("--fetch-gbps", type=float, default=None)
    return ap.parse_args(argv)


def analytic_report(args) -> dict:
    """Per-region exposed/hidden split from the attribution model."""
    import dataclasses as _dc

    import jax

    from deepspeed_tpu.models.zoo import get_model
    from deepspeed_tpu.observability.attribution import (
        _DEFAULT_FETCH_GBPS, attribute_step, split_exposed_hidden)
    from deepspeed_tpu.observability.roofline import (detect_hbm_gbps,
                                                      detect_peak_tflops)

    model = get_model(args.model, max_seq_len=args.seq)
    cfg = _dc.replace(model.config, num_layers=args.layers,
                      vocab_size=args.vocab)
    dev = jax.devices()[0]
    peak, hbm = detect_peak_tflops(dev), detect_hbm_gbps(dev)
    fetch = (args.fetch_gbps if args.fetch_gbps is not None
             else float(os.environ.get("DSTPU_FETCH_GBPS",
                                       _DEFAULT_FETCH_GBPS)))
    regions = attribute_step(cfg, args.micro, args.seq, fetch_gbps=fetch)
    split = split_exposed_hidden(
        regions, peak_tflops=peak, hbm_gbps=hbm, fetch_gbps=fetch,
        overlap_depth=args.overlap_depth, num_layers=cfg.num_layers)
    rows = [{"name": s["region"], "kind": s["kind"],
             "bytes": float(s["bytes"]),
             "total_ms": round(s["total_ms"], 3),
             "hidden_ms": round(s["hidden_ms"], 3),
             "exposed_ms": round(s["exposed_ms"], 3)} for s in split]
    transfers = [r for r in rows if r["kind"] != "compute"]
    tot = sum(r["total_ms"] for r in transfers)
    hid = sum(r["hidden_ms"] for r in transfers)
    return {
        "schema": SCHEMA,
        "mode": "analytic",
        "shape": {"model": args.model, "layers": args.layers,
                  "micro": args.micro, "seq": args.seq,
                  "vocab": args.vocab},
        "overlap_depth": args.overlap_depth,
        "fetch_gbps": fetch,
        "regions": rows,
        "totals": {
            "bytes": sum(r["bytes"] for r in transfers),
            "total_ms": round(tot, 3),
            "hidden_ms": round(hid, 3),
            "exposed_ms": round(tot - hid, 3),
            "hidden_frac": round(hid / tot, 4) if tot > 0 else 0.0,
        },
        "measured": None,
    }


def measure(args) -> float:
    import jax
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.zoo import get_model

    model = get_model(args.model, num_layers=args.layers,
                      vocab_size=args.vocab, max_seq_len=args.seq,
                      remat=True, remat_policy="nothing_saveable")
    config = {
        "train_micro_batch_size_per_chip": args.micro,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {
            "stage": 2,
            "offload_optimizer": {"device": "cpu",
                                  "grad_transfer_dtype": "bf16"},
            "offload_param": {"device": "cpu"},
        },
        "bf16": {"enabled": True},
        "steps_per_print": 10**6,
    }
    engine, *_ = dstpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    B = engine.micro_batch_size * engine.dp_world_size
    batch = {"input_ids": rng.integers(
        0, args.vocab, (B, args.seq + 1)).astype(np.int32)}

    def it():
        while True:
            yield batch

    data = it()
    # measure the DEVICE program only (grad_step), not the host optimizer:
    # the fetch-overlap question lives in the compiled fwd/bwd
    batches = engine._next_microbatches(
        data, engine.gradient_accumulation_steps)
    import jax.numpy as jnp

    scale = jnp.asarray(1.0, jnp.float32)
    grads, loss = engine._jit_grad_step(engine.params, batches, scale)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        grads, loss = engine._jit_grad_step(engine.params, batches, scale)
    jax.block_until_ready((grads, loss))
    return (time.perf_counter() - t0) / args.steps


def measured_report(args, argv) -> dict:
    env_a = dict(os.environ, _PROBE_MODE="run")
    env_b = dict(env_a, DSTPU_SERIALIZE_FETCH="1")

    def run(env):
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + list(argv or []),
            env=env, capture_output=True, text=True)
        for line in reversed(out.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)["step_s"]
        raise RuntimeError(f"probe run failed:\n{out.stdout}\n{out.stderr}")

    a = run(env_a)  # overlap free
    b = run(env_b)  # fetches serialized by data dependency
    doc = analytic_report(args)
    doc["mode"] = "measured"
    doc["measured"] = {
        "metric": ("offload_param per-layer-fetch overlap "
                   f"({args.model} geom)"),
        "steps": args.steps,
        "step_overlap_s": round(a, 4),
        "step_serialized_s": round(b, 4),
        "overlap_fraction": round(1.0 - a / b, 4) if b > 0 else None,
    }
    return doc


def main(argv=None):
    args = _args(argv)
    if os.environ.get("_PROBE_MODE") == "run":
        print(json.dumps({"step_s": measure(args)}))
        return 0
    if args.analytic:
        print(json.dumps(analytic_report(args), indent=2))
        return 0
    print(json.dumps(measured_report(args, argv if argv is not None
                                     else sys.argv[1:]), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
