"""Chaos harness: kill a training rank mid-run, restart it under the
elastic agent, and prove auto-resume reproduces the fault-free run.

The drill (docs/resilience.md):

1. **baseline** — one fault-free worker (tests/chaos_worker.py) trains
   N steps, checkpointing every K, and records per-step losses;
2. **chaos** — a fresh run dir, same worker, but ``DSTPU_CHAOS`` arms
   the in-process fault injector (default: SIGKILL at step 3, exactly a
   scheduler preemption with no grace). The ElasticAgent supervises it:
   the kill is observed as a worker failure, the group restarts, and the
   restarted worker auto-resumes from the latest *valid* manifest and
   replays the remaining batch stream;
3. **verdict** — the chaos run's final loss must be bit-identical to the
   baseline's. Not "close": identical. Anything else means resume
   changed the batch stream or the optimizer state and the run silently
   became a different run.

    python tools/chaos_run.py [--steps 5] [--kill-step 3]
                              [--signal SIGKILL|SIGTERM] [--keep]

Exit 0 on a bit-identical resume, 1 otherwise. ``make chaos`` runs this
on the 8-device CPU sim; no TPU needed.
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

WORKER = os.path.join(_REPO, "tests", "chaos_worker.py")


def _worker_env(run_dir: str, chaos: str = "") -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    env["DSTPU_FLIGHT_DIR"] = os.path.join(run_dir, "flight")
    if chaos:
        env["DSTPU_CHAOS"] = chaos
    else:
        env.pop("DSTPU_CHAOS", None)
    return env


def _final_loss(run_dir: str):
    path = os.path.join(run_dir, "losses.jsonl")
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    # a replayed step appears twice (pre-kill + post-resume); last wins
    by_step = {r["step"]: r["loss"] for r in rows}
    return by_step, max(by_step)


def run_baseline(run_dir: str, steps: int) -> None:
    rc = subprocess.call(
        [sys.executable, WORKER, run_dir, "--steps", str(steps)],
        env=_worker_env(run_dir))
    if rc != 0:
        raise SystemExit(f"baseline worker failed (rc={rc})")


def run_chaos(run_dir: str, steps: int, kill_step: int, sig: str) -> None:
    from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent

    chaos = f"kill_rank=0,kill_step={kill_step},kill_signal={sig}"

    def build_cmds(hosts, restart_count):
        return [[sys.executable, WORKER, run_dir, "--steps", str(steps)]]

    agent = ElasticAgent(
        build_cmds, lambda: ["localhost"], max_restarts=3,
        poll_interval=0.2,
        env=_worker_env(run_dir, chaos))
    rc = agent.run()
    if rc != 0:
        raise SystemExit(f"chaos group never finished cleanly (rc={rc})")
    print(f"chaos: agent restarted the group {agent.restart_count} "
          f"time(s); last failure kind={agent.last_failure_kind} "
          f"exit codes={agent.last_exit_codes}")
    if agent.restart_count == 0:
        raise SystemExit("chaos: fault never fired (0 restarts) — the "
                         "run proved nothing")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--kill-step", type=int, default=3)
    p.add_argument("--signal", default="SIGKILL",
                   choices=["SIGKILL", "SIGTERM"])
    p.add_argument("--keep", action="store_true",
                   help="keep the run dirs for inspection")
    args = p.parse_args()

    root = tempfile.mkdtemp(prefix="dstpu_chaos_")
    base_dir = os.path.join(root, "baseline")
    chaos_dir = os.path.join(root, "chaos")
    os.makedirs(base_dir)
    os.makedirs(chaos_dir)
    try:
        print(f"chaos: baseline run ({args.steps} steps) -> {base_dir}")
        run_baseline(base_dir, args.steps)
        print(f"chaos: fault run (kill step {args.kill_step} via "
              f"{args.signal}) -> {chaos_dir}")
        run_chaos(chaos_dir, args.steps, args.kill_step, args.signal)

        base, bstep = _final_loss(base_dir)
        got, gstep = _final_loss(chaos_dir)
        ok = bstep == gstep and base[bstep] == got[gstep]
        print(json.dumps({"kind": "chaos_verdict",
                          "baseline_final": base[bstep],
                          "chaos_final": got[gstep],
                          "steps": bstep,
                          "bit_identical": ok}))
        if not ok:
            print("chaos: FAIL — resumed run diverged from baseline",
                  file=sys.stderr)
            return 1
        print("chaos: OK — kill/restart/resume reproduced the "
              "fault-free run bit-for-bit")
        return 0
    finally:
        if args.keep:
            print(f"chaos: run dirs kept at {root}")
        else:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
