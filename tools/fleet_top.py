"""Fleet observability viewer: aggregate per-rank shards into one report.

Every worker of a run publishes atomic heartbeats + append-only step
shards into a shared run dir (DSTPU_RUN_DIR; see docs/observability.md).
This tool is the read side — it runs on any host that can see the run
dir and needs neither jax nor the training job's config:

  python tools/fleet_top.py RUN_DIR                # one-shot report
  python tools/fleet_top.py RUN_DIR --watch 5      # live top-style view
  python tools/fleet_top.py RUN_DIR --chrome-trace 0 --out trace.json
                                                   # Perfetto export
  python tools/fleet_top.py --demo                 # 2-process CPU demo

The report names the slowest rank per merged step, cross-rank skew, an
EWMA straggler score per rank, and dead hosts (stale heartbeats). The
chrome-trace export renders one rank's step shard + flight-recorder
dumps as a ``trace.json`` loadable in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from deepspeed_tpu.observability.fleet import (FleetAggregator, FleetPublisher,
                                               format_report)


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="fleet_top")
    p.add_argument("run_dir", nargs="?",
                   default=os.environ.get("DSTPU_RUN_DIR"),
                   help="shared run dir (default: $DSTPU_RUN_DIR)")
    p.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                   help="refresh the report every N seconds until Ctrl-C")
    p.add_argument("--stale-after", type=float, default=30.0,
                   help="heartbeat age (s) after which a rank counts dead")
    p.add_argument("--json", action="store_true",
                   help="emit the raw report dict as JSON")
    p.add_argument("--chrome-trace", type=int, default=None, metavar="RANK",
                   help="export RANK's steps + flight events as a "
                        "chrome://tracing / Perfetto trace and exit")
    p.add_argument("--out", default="trace.json",
                   help="output path for --chrome-trace")
    p.add_argument("--demo", action="store_true",
                   help="spawn a short 2-process CPU job into a temp run "
                        "dir and print the aggregated report")
    p.add_argument("--demo-worker", type=int, default=None,
                   help=argparse.SUPPRESS)  # internal: demo subprocess rank
    return p.parse_args(argv)


def _demo_worker(rank: int, run_dir: str) -> int:
    """Simulated training rank: publishes step shards + flight events.

    Rank 1 sleeps longer per step so the aggregated report has a real
    straggler to attribute. Pure host code — no jax."""
    from deepspeed_tpu.observability.flight_recorder import (
        get_flight_recorder, install_crash_handlers)

    fr = get_flight_recorder()
    fr.configure(rank=rank, run_dir=run_dir)
    install_crash_handlers()
    pub = FleetPublisher(run_dir, rank=rank)
    per_step = 0.01 if rank == 0 else 0.03  # rank 1 is the straggler
    for step in range(1, 13):
        t0 = time.time()
        fr.record("step_entry", step=step)
        time.sleep(per_step)
        fr.record("step_drain", step=step)
        pub.publish_step({
            "rank": rank, "step": step,
            "wall_ms": (time.time() - t0) * 1000.0,
            "loss": 2.0 / step, "timestamp": time.time(),
        })
    fr.dump("demo_exit", final_step=12)
    pub.close()
    return 0


def _run_demo() -> int:
    run_dir = tempfile.mkdtemp(prefix="dstpu_fleet_demo_")
    print(f"fleet demo: 2 CPU ranks publishing into {run_dir}", flush=True)
    procs = [
        subprocess.Popen([sys.executable, os.path.abspath(__file__),
                          "--demo-worker", str(r), run_dir])
        for r in (0, 1)
    ]
    rc = 0
    for p in procs:
        rc |= p.wait()
    report = FleetAggregator(run_dir).report()
    print(format_report(report))
    straggler = report.get("straggler")
    if straggler:
        print(f"\n=> rank {straggler['rank']} correctly flagged "
              f"(score {straggler['score']:.2f}); shards + flight dumps "
              f"kept in {run_dir}")
    return rc


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.demo_worker is not None:
        return _demo_worker(args.demo_worker, args.run_dir)
    if args.demo:
        return _run_demo()
    if not args.run_dir:
        print("fleet_top: error: no run dir (pass one or set DSTPU_RUN_DIR)",
              file=sys.stderr)
        return 2
    if args.chrome_trace is not None:
        from deepspeed_tpu.observability.chrome_trace import \
            export_rank_from_run_dir

        export_rank_from_run_dir(args.run_dir, args.chrome_trace, args.out)
        print(f"wrote rank {args.chrome_trace} trace to {args.out} "
              f"(open in Perfetto or chrome://tracing)")
        return 0

    agg = FleetAggregator(args.run_dir, stale_after_seconds=args.stale_after)
    while True:
        report = agg.report()
        if args.json:
            print(json.dumps(report, sort_keys=True))
        else:
            if args.watch:
                print("\x1b[2J\x1b[H", end="")  # clear screen, home cursor
            print(format_report(report), flush=True)
        if not args.watch:
            return 0
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
