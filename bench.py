"""Headline benchmark: training throughput on the available TPU chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``value`` is the median of k measured windows (default 3), with a
host-load sentinel: windows that started while the 1-minute loadavg
exceeded BENCH_LOAD_MAX are dropped when cleaner windows exist, and the
run resamples (up to BENCH_MAX_WINDOWS) while the kept spread exceeds
BENCH_SPREAD_TARGET. Per-window throughput + loadavg ship in the JSON
(``windows``/``load_avg``/``spread_pct``/``contended``) so a contended
capture is diagnosable from the artifact alone.

Default configuration is BASELINE.json's north-star class: Llama-3-8B
layer geometry (h=4096, ffn=14336, 32q/8kv GQA, RoPE, swiglu, RMSNorm)
under ZeRO-3 — depth cut to the 3 layers that fit one 16GB chip with
full fp32 Adam state resident (see docs/roofline.md for the breakdown
and the 8B projection). ``vs_baseline`` divides by the recorded number
in BASELINE.json's ``published`` dict.

Env knobs: BENCH_MODEL (zoo name; "gpt2-125m" restores the round-1
config), BENCH_SEQ, BENCH_MICRO, BENCH_STEPS, BENCH_LAYERS, BENCH_VOCAB,
BENCH_ZERO_STAGE, BENCH_REMAT_POLICY, BENCH_PEAK_TFLOPS (defaults to the
detected chip's bf16 peak), BENCH_WINDOWS / BENCH_MAX_WINDOWS /
BENCH_LOAD_MAX / BENCH_SPREAD_TARGET (measurement-window controls;
BENCH_WINDOWS=1 restores the single-sample behavior for slow capacity
probes), BENCH_PIPELINE_DEPTH / BENCH_PREFETCH_DEPTH (pipelined-loop
dispatch-ahead + input-prefetch depths; 0 restores the blocking loop —
see docs/performance.md). ``host_gap_ms`` in the JSON is the per-step
host time on the dispatch critical path, medianed over the kept windows.
"""

from __future__ import annotations

import json
import os
import statistics
import time


# peak tables + detection live in the observability package now, so the
# engine's per-step MFU and this benchmark's headline MFU come from one
# table and one formula (tools/device_step_bench.py imports them from
# here — keep the re-export)
from deepspeed_tpu.observability.roofline import (  # noqa: E402,F401
    PEAK_TFLOPS, detect_peak_tflops)


def main():
    if os.environ.get("BENCH_MODE") == "serve":
        # serving throughput instead of the training headline: v2 ragged
        # continuous batching + multi-step decode vs the naive v1 dense
        # path (tools/serve_bench.py; SERVE_* env knobs)
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        import serve_bench

        print(json.dumps(serve_bench.run()))
        return

    import jax
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.zoo import get_model

    n_chips = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"

    model_name = os.environ.get("BENCH_MODEL", "llama3-8b")
    llama_headline = model_name == "llama3-8b"
    seq = int(os.environ.get("BENCH_SEQ", 2048 if llama_headline else 1024))
    if not on_tpu:
        seq = int(os.environ.get("BENCH_SEQ", 128))
    # Measured on v5e-1 (see docs/roofline.md):
    #  - llama3-8b geometry: 3 layers + fp32 Adam state fill 16GB HBM;
    #    micro=8 with attn-out saved remat → 19.2k tok/s, MFU 0.450.
    #  - gpt2-125m: micro=224 with flash block-512 → ~75k tok/s, MFU 0.33.
    micro_default = 8 if llama_headline else 224
    micro = int(os.environ.get("BENCH_MICRO", micro_default if on_tpu else 1))
    gas = int(os.environ.get("BENCH_GAS", 1))
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 3))
    warmup = int(os.environ.get("BENCH_WARMUP", 3 if on_tpu else 1))

    # long-context mode (driver-capturable 128K+ claim, VERDICT r3 #2):
    # BENCH_SEQ >= 32768 flips the measured long-seq defaults — depth 1,
    # micro 1, tiled mlp/logits, full remat (docs/roofline.md 128K table)
    long_ctx = llama_headline and on_tpu and seq >= 32768
    if long_ctx:
        micro = int(os.environ.get("BENCH_MICRO", 1))
        steps = int(os.environ.get("BENCH_STEPS", 3))
        warmup = 1

    # remat costs ~30% extra FLOPs but is what bounds activation memory at
    # large micro-batches; tiled logits chunk the [B,S,V] fp32 logits+loss
    # (the HBM ceiling for small-vocab-heavy models like GPT-2)
    remat = bool(int(os.environ.get("BENCH_REMAT", "1")))
    tiled = int(os.environ.get("BENCH_TILED_LOGITS",
                               "64" if long_ctx else "8"))
    tiled_mlp = int(os.environ.get("BENCH_TILED_MLP",
                                   "16" if long_ctx else "0"))
    attn = os.environ.get("BENCH_ATTN", "auto")
    # gpt2: full remat (save only the residual stream) measures fastest —
    # saved matmul outputs at micro=224 would cost ~10GB HBM.
    # llama geometry: saving the attention output block is free at micro=8
    # and skips the flash-kernel recompute in the backward.
    policy = os.environ.get(
        "BENCH_REMAT_POLICY",
        "nothing_saveable" if long_ctx
        else ("save_attn_out" if llama_headline else "nothing_saveable"))
    overrides = dict(max_seq_len=seq, remat=remat, tiled_logits=tiled,
                     tiled_mlp=tiled_mlp, attn_impl=attn,
                     remat_policy=policy)
    if llama_headline:
        # depth that fits one 16GB chip with full fp32 Adam resident;
        # vocab cut so layer matmuls dominate FLOPs like the 32L model
        overrides["num_layers"] = int(os.environ.get(
            "BENCH_LAYERS", 1 if long_ctx else 3))
        overrides["vocab_size"] = int(os.environ.get("BENCH_VOCAB", 8192))
    if int(os.environ.get("BENCH_FPDT", "0")):
        # FPDT host-KV streaming (beyond-HBM sequence lengths): K/V tiles
        # live in pinned host memory, q chunks stream them back
        overrides["fpdt_host_kv"] = True
        overrides["attn_chunks"] = int(os.environ.get("BENCH_ATTN_CHUNKS",
                                                      "8"))
        if int(os.environ.get("BENCH_FPDT_RESIDUAL", "0")):
            # residual stream hosted too: no full-S device buffer at all
            overrides["fpdt_host_residual"] = True
    if not on_tpu:  # CPU smoke: shrink the model
        overrides.update(num_layers=2, hidden_size=256, num_heads=8,
                         vocab_size=2048)
        if llama_headline:
            overrides.update(num_kv_heads=4, ffn_size=512)
    model = get_model(model_name, **overrides)

    # zero stage + mesh topology decided ONCE, up front: the autotuner's
    # trial engines must run under the same mesh as the final engine or
    # the tuned settings are measured against a different program
    zero_stage_default = 3 if llama_headline else (1 if n_chips > 1 else 0)
    zero_stage = int(os.environ.get("BENCH_ZERO_STAGE", zero_stage_default))
    if int(os.environ.get("BENCH_OFFLOAD", "0")):
        zero_stage = 2 if n_chips == 1 else 1
    topology = ({"dp": 1, "fsdp": -1} if (n_chips > 1 or zero_stage == 3)
                else None)

    # BENCH_AUTOTUNE=1: let the autotuner pick micro batch + remat policy
    # (reference: the CLI launches Autotuner.tune() before real training,
    # launcher/runner.py:407). The chosen settings land in the JSON line.
    config_source = "measured-defaults"
    if int(os.environ.get("BENCH_AUTOTUNE", "0")) and on_tpu:
        from deepspeed_tpu.autotuning.autotuner import Autotuner

        def model_factory():
            return get_model(model_name, **overrides)

        vocab = model.config.vocab_size

        def batch_fn(global_batch):
            rng_ = np.random.default_rng(0)
            return {"input_ids": rng_.integers(
                0, vocab, (global_batch, seq + 1)).astype(np.int32)}

        space = {
            "micro_batch_sizes": [micro // 2, micro, micro + micro // 2],
            "zero_stages": [zero_stage],
            "remat": [True],
            "remat_policies": ["nothing_saveable", "save_attn_out"],
        }
        tuner = Autotuner(model_factory, {
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "adamw",
                          "params": {"lr": 1e-4, "weight_decay": 0.1}},
            "bf16": {"enabled": True}, "steps_per_print": 1_000_000,
        }, batch_fn, tuning_space=space, topology=topology)
        best = tuner.tune(top_k=4, measure_steps=3)
        if best is not None:
            micro = int(best["train_micro_batch_size_per_chip"])
            policy = best.get("_remat_policy", policy)
            overrides["remat_policy"] = policy
            model = get_model(model_name, **overrides)
            config_source = "autotuner"

    # pipelined loop: dispatch-ahead keeps K steps in flight so the host
    # input pull/stack/transfer overlaps device compute, and the engine
    # promotes the (repeatedly-passed) data iterator to a background
    # prefetching iterator (runtime/prefetch.py). Depth 0 restores the
    # blocking loop for A/B comparison (BENCH_PIPELINE_DEPTH=0).
    pipeline_depth = int(os.environ.get("BENCH_PIPELINE_DEPTH", "2"))
    prefetch_depth = int(os.environ.get("BENCH_PREFETCH_DEPTH", "2"))
    config = {
        "train_micro_batch_size_per_chip": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": zero_stage},
        "bf16": {"enabled": True},
        "performance": {"pipeline_depth": pipeline_depth,
                        "prefetch_depth": prefetch_depth},
        "steps_per_print": 1_000_000,
    }
    offload = int(os.environ.get("BENCH_OFFLOAD", "0"))
    if offload:
        # ZeRO-Offload mode: fp32 master + Adam state live in host RAM,
        # the chip keeps bf16 params only (capacity benchmark — the
        # reference's "13B on one GPU" claim class)
        config["zero_optimization"]["offload_optimizer"] = {
            "device": "cpu",
            "grad_transfer_dtype": os.environ.get("BENCH_GRAD_DTYPE",
                                                  "bf16")}
    if offload >= 2:
        # ZeRO-Infinity pairing: layer params stream from pinned host
        # memory one layer at a time (offload_param)
        config["zero_optimization"]["offload_param"] = {"device": "cpu"}
    if offload and int(os.environ.get("BENCH_ZENFLOW", "0")):
        # ZenFlow: top-k coordinates update on device every step, the
        # host master pass overlaps (importance-split offload — hides
        # most of the host optimizer cost the plain offload mode pays)
        config["zero_optimization"]["zenflow"] = {
            "topk_ratio": float(os.environ.get("BENCH_ZENFLOW_TOPK", "0.05")),
            "update_interval": int(os.environ.get("BENCH_ZENFLOW_UI", "4")),
            "overlap_step": True,
        }
    engine, _, _, _ = dstpu.initialize(model=model, config=config,
                                       topology=topology)

    rng = np.random.default_rng(0)
    B = engine.micro_batch_size * engine.dp_world_size
    batch = {"input_ids": rng.integers(
        0, model.config.vocab_size, (B, seq + 1)).astype(np.int32)}

    def it():
        while True:
            yield batch

    data = it()
    for _ in range(warmup):
        loss = engine.train_batch(data)
    engine.synchronize()  # drain the dispatch-ahead window before timing
    jax.block_until_ready(loss)

    # Median-of-k measurement with a host-contention sentinel. This repo
    # benches on a 1-core host the driver shares with other work; a single
    # 20-step sample has been observed 28% low purely from host load
    # (BENCH_r04 vs a fresh run at the same commit). Defense: measure k
    # independent windows, record the 1-minute loadavg at each window
    # start, drop windows that began under heavy load when clean ones
    # exist, resample while the spread is wide, and report the median
    # plus the full per-window evidence so an outlier is visible in the
    # artifact instead of silently becoming the headline.
    tokens_per_window = B * seq * steps * gas  # train_batch runs gas microbatches

    def loadavg():
        try:
            return os.getloadavg()[0]
        except OSError:
            return -1.0

    def measure_window():
        # loadavg is a 1-minute EMA, so the run's own compile/warmup burst
        # lingers into the first windows; min(start, end) reads through
        # that decaying tail, while genuine external contention persists
        # across the window and keeps both samples high
        load0 = loadavg()
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(data)
        engine.synchronize()  # window ends when every in-flight step lands
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        load = min(load0, loadavg()) if load0 >= 0 else load0
        # the engine's own per-step MFU over exactly this window's steps
        # (observability hub StepTrace rows) — same formula + peak table,
        # timed per step instead of per window
        hub = getattr(engine, "hub", None)
        emfu = hub.window_mfu(last_n=steps) if hub is not None else None
        # host time on the dispatch critical path per step (input pull +
        # stack + transfer + jit-call overhead) — the cost the pipelined
        # loop hides; a regression here shows up even when device math
        # still dominates the wall clock
        hgap = (hub.window_host_gap_ms(last_n=steps)
                if hub is not None else None)
        return tokens_per_window / dt / n_chips, load, loss, emfu, hgap

    # capacity-probe runs (BENCH_STEPS=1 on host-optimizer shapes where a
    # step takes minutes) default to one window; normal runs take three
    n_windows = max(1, int(os.environ.get(
        "BENCH_WINDOWS", 3 if (on_tpu and steps > 1) else 1)))
    max_windows = int(os.environ.get("BENCH_MAX_WINDOWS",
                                     max(n_windows + 2, 5)))
    load_max = float(os.environ.get("BENCH_LOAD_MAX", "2.0"))
    spread_target = float(os.environ.get("BENCH_SPREAD_TARGET", "0.05"))

    windows = []  # (tok/s/chip, loadavg, engine-window-mfu, host-gap-ms)
    for _ in range(n_windows):
        tps, load, loss, emfu, hgap = measure_window()
        windows.append((tps, load, emfu, hgap))
    # resample while spread is wide and budget remains — one contended
    # window out of three still skews the median less than it skews a
    # single-sample mean, and extra clean windows dilute it further.
    # With >=4 kept windows the single slowest value is trimmed before
    # the spread check: contention noise on this host is one-sided (it
    # only slows windows down), so the slowest window is the suspect one
    # and the fastest is never discarded. Without a trim, max-min never
    # shrinks and resampling could not converge.
    def kept_and_spread():
        clean = [w for w in windows if 0.0 <= w[1] <= load_max]
        kept = clean if clean else windows
        ordered = sorted(kept, key=lambda w: w[0])
        trimmed = 0
        if len(ordered) >= 4:
            ordered = ordered[1:]
            trimmed = 1
        vals = [w[0] for w in ordered]
        med = statistics.median(vals)
        spread = (max(vals) - min(vals)) / med if med > 0 else 0.0
        # engine MFU + host gap through the SAME window selection, so a
        # contended window dropped from the throughput median is dropped
        # from these medians too
        emfus = [w[2] for w in ordered if w[2] is not None]
        emfu_med = statistics.median(emfus) if emfus else None
        hgaps = [w[3] for w in ordered if w[3] is not None]
        hgap_med = statistics.median(hgaps) if hgaps else None
        return kept, med, spread, trimmed, emfu_med, hgap_med

    kept, med, spread, trimmed, engine_mfu, host_gap_ms = kept_and_spread()
    while (len(windows) < max_windows
           and (spread > spread_target or len(kept) < min(3, n_windows))):
        tps, load, loss, emfu, hgap = measure_window()
        windows.append((tps, load, emfu, hgap))
        kept, med, spread, trimmed, engine_mfu, host_gap_ms = \
            kept_and_spread()

    tok_per_sec_chip = med
    contended = len(kept) < len(windows) or any(
        w[1] > load_max for w in windows)
    flops_per_token = model.flops_per_token()
    peak = detect_peak_tflops(jax.devices()[0])
    mfu = tok_per_sec_chip * flops_per_token / (peak * 1e12)

    baseline = {}
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}) or {}
    except Exception:
        pass
    base_key = ("llama3_8b_geom_tokens_per_sec_per_chip" if llama_headline
                else "gpt2_125m_tokens_per_sec_per_chip")
    base_tps = baseline.get(base_key)
    vs_baseline = (tok_per_sec_chip / base_tps) if base_tps else 1.0

    desc = (f"{model_name}-geometry({model.config.num_layers}L)"
            if llama_headline else model_name)
    print(json.dumps({
        "metric": f"{desc} zero{zero_stage} train tokens/sec/chip "
                  f"(seq={seq}, micro={micro}, {'tpu' if on_tpu else 'cpu-sim'})",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "mfu": round(mfu, 4),
        "engine_mfu": (round(engine_mfu, 4)
                       if engine_mfu is not None else None),
        "host_gap_ms": (round(host_gap_ms, 3)
                        if host_gap_ms is not None else None),
        "pipeline_depth": pipeline_depth,
        "spread_pct": round(100.0 * spread, 2),
        "windows": [round(w[0], 1) for w in windows],
        "load_avg": [round(w[1], 2) for w in windows],
        "windows_kept": len(kept),
        "windows_used": len(kept) - trimmed,
        "trimmed_low": trimmed,
        "contended": contended,
        "config_source": config_source,
        "remat_policy": overrides.get("remat_policy", policy),
        "loss": round(float(loss), 4),
        "chips": n_chips,
    }))


if __name__ == "__main__":
    main()
