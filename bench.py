"""Headline benchmark: training throughput on the available TPU chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``value`` is the median of k measured windows (default 3), with a
host-load sentinel: windows that started while the 1-minute loadavg
exceeded BENCH_LOAD_MAX are dropped when cleaner windows exist, and the
run resamples (up to BENCH_MAX_WINDOWS) while the kept spread exceeds
BENCH_SPREAD_TARGET. Per-window throughput + loadavg ship in the JSON
(``windows``/``load_avg``/``spread_pct``/``contended``) so a contended
capture is diagnosable from the artifact alone.

Default configuration is BASELINE.json's north-star class: Llama-3-8B
layer geometry (h=4096, ffn=14336, 32q/8kv GQA, RoPE, swiglu, RMSNorm)
under ZeRO-3 — depth cut to the 3 layers that fit one 16GB chip with
full fp32 Adam state resident (see docs/roofline.md for the breakdown
and the 8B projection). ``vs_baseline`` divides by the recorded number
in BASELINE.json's ``published`` dict.

Default configuration since round 6 is the REAL shape (docs/roofline.md
"the real shape"): llama3-8b geometry at 8 layers + the true 131,072
vocab = 2.82B params, ZeRO-Infinity streamed (offload_param +
offload_optimizer) on one chip, measured as the device fwd+bwd program
(`BENCH_MEASURE=device_step` — the full step on a 1-core host is bound
by host Adam, not the chip; tools/device_step_bench.py rationale).
``BENCH_PROXY=1`` restores the round-5 3-layer / 8k-vocab
resident-param proxy. Autotuned real-shape defaults persist in
``docs/autotuned/real_shape.json`` (written by ``dstpu-autotune
--persist``) and are read back here; env knobs still win.

Env knobs: BENCH_MODEL (zoo name; "gpt2-125m" restores the round-1
config), BENCH_PROXY, BENCH_SEQ, BENCH_MICRO, BENCH_STEPS, BENCH_LAYERS,
BENCH_VOCAB, BENCH_ZERO_STAGE, BENCH_REMAT_POLICY, BENCH_PEAK_TFLOPS
(defaults to the detected chip's bf16 peak), BENCH_WINDOWS /
BENCH_MAX_WINDOWS / BENCH_LOAD_MAX / BENCH_SPREAD_TARGET
(measurement-window controls; BENCH_WINDOWS=1 restores the
single-sample behavior for slow capacity probes), BENCH_PIPELINE_DEPTH /
BENCH_PREFETCH_DEPTH (pipelined-loop dispatch-ahead + input-prefetch
depths; 0 restores the blocking loop — see docs/performance.md),
BENCH_PARAM_PREFETCH (ZeRO-Infinity layer-prefetch ring depth),
BENCH_OVERLAP_DEPTH (per-layer overlap engine stage depth — pin_stage
staging in runtime/param_stream.py; 0 restores the unstaged schedule
for A/B, see ``make bench-overlap``),
BENCH_FP8_MLP (opt-in fp8 MLP GEMMs), BENCH_MEASURE
(device_step | train_batch), BENCH_TUNED_DEFAULTS (tuned-config JSON
path). ``host_gap_ms`` in the JSON is the per-step host time on the
dispatch critical path, medianed over the kept windows.
"""

from __future__ import annotations

import json
import os
import statistics
import time


# peak tables + detection live in the observability package now, so the
# engine's per-step MFU and this benchmark's headline MFU come from one
# table and one formula (tools/device_step_bench.py imports them from
# here — keep the re-export)
from deepspeed_tpu.observability.roofline import (  # noqa: E402,F401
    PEAK_TFLOPS, detect_peak_tflops)

# the real shape (docs/roofline.md): llama3-8b geometry at the depth +
# true vocab that exercise ZeRO-Infinity streaming on one 16GB chip
REAL_LAYERS = 8
REAL_VOCAB = 131072


def read_tuned_defaults(path=None):
    """Autotuner-persisted real-shape config (dstpu-autotune --persist);
    {} when absent. Env knobs override every field it provides."""
    path = path or os.environ.get(
        "BENCH_TUNED_DEFAULTS",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "docs", "autotuned", "real_shape.json"))
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return {}


def resolve_bench_defaults(env=None, on_tpu=True, n_chips=1):
    """Resolve the benchmark's shape + perf knobs from env (pure —
    tier-1 tested against the real-shape contract).

    Returns a dict: model_name, real_shape, proxy, long_ctx, seq,
    layers, vocab (layers/vocab None off the llama headline), micro,
    remat_policy, tiled_logits, tiled_mlp, offload, zero_stage,
    param_prefetch_depth, overlap_depth, fp8_mlp, measure,
    config_source, tuned.
    """
    env = os.environ if env is None else env
    model_name = env.get("BENCH_MODEL", "llama3-8b")
    llama = model_name == "llama3-8b"
    proxy = bool(int(env.get("BENCH_PROXY", "0")))
    # BENCH_LONGCTX=1: the analytic long-context tier (256k+ tokens) —
    # planner + per-region attribution table, no compiled step (O(S²)
    # attention does not compile at 256k on the CPU sim)
    longctx_bench = bool(int(env.get("BENCH_LONGCTX", "0")))
    seq = int(env.get("BENCH_SEQ",
                      262144 if longctx_bench
                      else ((2048 if llama else 1024) if on_tpu else 128)))
    long_ctx = llama and on_tpu and seq >= 32768
    real = llama and not proxy and not long_ctx
    tuned = read_tuned_defaults() if real else {}

    layers = vocab = None
    if llama:
        layers = int(env.get("BENCH_LAYERS",
                             REAL_LAYERS if real else (1 if long_ctx
                                                       else 3)))
        vocab = int(env.get("BENCH_VOCAB",
                            REAL_VOCAB if real else 8192))
    micro_default = int(tuned.get("train_micro_batch_size_per_chip",
                                  4 if real else (8 if llama else 224)))
    if long_ctx:
        micro_default = 1
    micro = int(env.get("BENCH_MICRO", micro_default if on_tpu else 1))
    policy = env.get(
        "BENCH_REMAT_POLICY",
        tuned.get("remat_policy",
                  "nothing_saveable" if (long_ctx or real)
                  else ("save_attn_out" if llama
                        else "nothing_saveable")))
    tiled = int(env.get("BENCH_TILED_LOGITS",
                        tuned.get("tiled_logits",
                                  64 if long_ctx else 8)))
    tiled_mlp = int(env.get("BENCH_TILED_MLP", 16 if long_ctx else 0))
    attn_chunks = int(tuned.get("attn_chunks", 0)) if real else 0
    # the real shape exceeds HBM: ZeRO-Infinity streaming (offload_param
    # + host optimizer, bf16 grad transfer) is the default there
    offload = int(env.get("BENCH_OFFLOAD", "2" if (real and on_tpu)
                          else "0"))
    zero_default = 3 if llama else (1 if n_chips > 1 else 0)
    zero_stage = int(env.get("BENCH_ZERO_STAGE", zero_default))
    if offload:
        zero_stage = 2 if n_chips == 1 else 1
    ppd_env = env.get("BENCH_PARAM_PREFETCH")
    ppd_tuned = (tuned.get("performance") or {}).get(
        "param_prefetch_depth")
    param_prefetch = (int(ppd_env) if ppd_env is not None
                      else (int(ppd_tuned) if ppd_tuned is not None
                            else (4 if real else None)))
    # per-layer overlap engine (runtime/param_stream.py pin_stage): the
    # real shape pins the full depth-4 ring — each fetch may hide behind
    # 4 layer-stages of compute; 0 keeps the ring but drops the barriers
    # (the pre-round-7 schedule) for A/B runs
    od_env = env.get("BENCH_OVERLAP_DEPTH")
    od_tuned = (tuned.get("performance") or {}).get("overlap_depth")
    overlap_depth = (int(od_env) if od_env is not None
                     else (int(od_tuned) if od_tuned is not None
                           else (4 if real else None)))
    fp8_mlp = bool(int(env.get("BENCH_FP8_MLP", "0")))
    # ZeRO++ quantization mode (parse_quant_mode grammar: off |
    # qwz+qgz+hpz<k>): env > tuned file (the quant_modes autotuner axis
    # / tools/quant_sweep.py --persist write the same key) > off
    qm_env = env.get("BENCH_QUANT_MODE")
    quant_mode = (str(qm_env) if qm_env is not None
                  else str(tuned.get("quant_mode", "off")))
    # the full step at the real shape is host-Adam-bound on a 1-core
    # rig; the chip-side MFU question is answered by the device fwd+bwd
    # program (tools/device_step_bench.py) — that is the headline there
    measure = env.get("BENCH_MEASURE",
                      "device_step" if (real and on_tpu and offload >= 2)
                      else "train_batch")
    return {
        "model_name": model_name, "real_shape": real, "proxy": proxy,
        "long_ctx": long_ctx, "seq": seq, "layers": layers,
        "vocab": vocab, "micro": micro, "remat_policy": policy,
        "tiled_logits": tiled, "tiled_mlp": tiled_mlp,
        "attn_chunks": attn_chunks, "offload": offload,
        "zero_stage": zero_stage,
        "param_prefetch_depth": param_prefetch,
        "overlap_depth": overlap_depth, "fp8_mlp": fp8_mlp,
        "quant_mode": quant_mode,
        "measure": measure,
        "config_source": ("autotuned-file" if tuned
                          else "measured-defaults"),
        "longctx_bench": longctx_bench,
        "longctx_sp": int(env.get("BENCH_SP", "4")),
    }


def longctx_bench_report(env=None):
    """The BENCH_LONGCTX tier: plan + attribute a 256k–1M-token step.

    Runs the unified sequence-parallel planner
    (parallel/auto_sp.plan_sequence_parallel) on a SIMULATED sp degree
    (BENCH_SP — an int, no device mesh needed) and models the three
    long-context regions analytically
    (observability/attribution.attribute_longctx_step): a compiled step
    at 256k is O(S²) and infeasible on the CPU sim, and the closed forms
    are what the planner itself reasons with. Dims default to CPU-sim
    scale (hidden 256, 8q/4kv heads, 2 layers — override BENCH_HIDDEN /
    BENCH_HEADS / BENCH_KV_HEADS / BENCH_LAYERS for real-shape
    projections; docs/roofline.md round 8 records both). BENCH_HBM_GB
    sizes the planner's spill budget — default 0.25 so the CPU-sim dims
    exercise the host-KV spill mechanics a 16 GB chip hits at real dims.

    Returns (markdown_table, json_payload).
    """
    import jax

    from deepspeed_tpu.observability.attribution import (
        attribute_longctx_step, attribution_markdown,
        split_exposed_hidden)
    from deepspeed_tpu.observability.roofline import (detect_hbm_gbps,
                                                      detect_peak_tflops)
    from deepspeed_tpu.parallel.auto_sp import plan_sequence_parallel

    env = os.environ if env is None else env
    seq = int(env.get("BENCH_SEQ", "262144"))
    sp = int(env.get("BENCH_SP", "4"))
    micro = int(env.get("BENCH_MICRO", "1"))
    layers = int(env.get("BENCH_LAYERS", "2"))
    hidden = int(env.get("BENCH_HIDDEN", "256"))
    heads = int(env.get("BENCH_HEADS", "8"))
    kv_heads = int(env.get("BENCH_KV_HEADS", "4"))
    head_dim = hidden // heads
    budget_gb = float(env.get("BENCH_HBM_GB", "0.25"))

    plan = plan_sequence_parallel(
        seq, heads, kv_heads, sp, int(budget_gb * 2 ** 30),
        head_dim=head_dim, hidden_size=hidden, batch_size=micro,
        dtype_bytes=2)
    regions = attribute_longctx_step(
        seq_len=seq, hidden_size=hidden, num_heads=heads,
        num_kv_heads=kv_heads, head_dim=head_dim, num_layers=layers,
        batch_size=micro, sp=plan.sp_degree, strategy=plan.strategy,
        attn_chunks=plan.attn_chunks, fpdt_host_kv=plan.fpdt_host_kv,
        dtype_bytes=2)

    dev = jax.devices()[0]
    peak = float(env.get("BENCH_PEAK_TFLOPS", 0)) or detect_peak_tflops(dev)
    hbm = detect_hbm_gbps(dev)
    depth = plan.overlap_depth_hint
    table = attribution_markdown(
        regions, peak, hbm,
        title=(f"Long-context attribution — seq {seq:,} sp={plan.sp_degree}"
               f" ({plan.strategy}) chunks={plan.attn_chunks} "
               f"spill={plan.fpdt_host_kv}"),
        overlap_depth=depth, num_layers=layers)
    split = split_exposed_hidden(regions, peak_tflops=peak, hbm_gbps=hbm,
                                 overlap_depth=depth, num_layers=layers)
    exposed_ms = sum(s["exposed_ms"] for s in split)
    payload = {
        "metric": (f"longctx analytic step (seq={seq}, sp={plan.sp_degree}"
                   f"/{plan.strategy}, h={hidden}, {heads}q/{kv_heads}kv, "
                   f"{layers}L, cpu-sim dims)"),
        "value": round(exposed_ms, 2),
        "unit": "modeled exposed ms/step",
        "plan": {"strategy": plan.strategy, "sp_degree": plan.sp_degree,
                 "attn_chunks": plan.attn_chunks,
                 "fpdt_host_kv": plan.fpdt_host_kv,
                 "overlap_depth_hint": plan.overlap_depth_hint,
                 "reasons": list(plan.reasons)},
        "regions": [dict(s) for s in split],
        "hbm_budget_gb": budget_gb,
    }
    return table, payload


def overlap_report(model, step_ms, overlap_depth, streaming,
                   fetch_gbps=None):
    """(hidden_comm_frac, exposed_param_fetch_ms) for the JSON line.

    The param-stream bytes come from the model's abstract layer shapes
    (eval_shape — no compute); the compute window is the MEASURED step
    split across the 2L scheduling stages, so the split reflects this
    run's actual step time rather than the roofline model. (None, None)
    when the run doesn't stream params or the knob is off the table.
    """
    if not streaming or overlap_depth is None or not step_ms:
        return None, None
    try:
        import jax

        from deepspeed_tpu.models.transformer import init_params
        from deepspeed_tpu.observability.attribution import (
            _DEFAULT_FETCH_GBPS, _per_layer_shapes, _tree_bytes,
            overlap_split_ms)

        cfg = model.config
        params = jax.eval_shape(lambda k: init_params(cfg, k),
                                jax.random.PRNGKey(0))
        layer_bytes = _tree_bytes(_per_layer_shapes(params["layers"]))
        fetch = (fetch_gbps if fetch_gbps is not None
                 else float(os.environ.get("DSTPU_FETCH_GBPS",
                                           _DEFAULT_FETCH_GBPS)))
        transfer_ms = (layer_bytes * cfg.num_layers * 2  # fwd + bwd
                       / (fetch * 1e9) * 1e3)
        stages = 2 * max(int(cfg.num_layers), 1)
        split = overlap_split_ms(transfer_ms, float(step_ms) / stages,
                                 int(overlap_depth), stages)
        return (round(split["hidden_frac"], 4),
                round(split["exposed_ms"], 2))
    except Exception:
        return None, None


def main():
    if os.environ.get("BENCH_MODE") in ("serve", "serve_slo",
                                        "serve_fleet", "serve_quant",
                                        "serve_tier", "serve_procs",
                                        "chaos_fleet", "obs_fleet",
                                        "replay_fleet",
                                        "deploy_drill"):
        # serving benchmarks instead of the training headline
        # (tools/serve_bench.py): "serve" is the closed-loop v2-vs-v1
        # throughput comparison (SERVE_* env knobs); "serve_slo" is the
        # open-loop Poisson-arrival SLO harness — p50/p99 TTFT, goodput
        # under deadline, queue-depth timeline (SLO_* env knobs,
        # SLO_COMPARE=1 for the no-spec/no-prefix-cache baseline);
        # "serve_fleet" is the multi-replica router bench — unified vs
        # disaggregated prefill/decode arms over the same open-loop
        # workload, one JSON line per arm (FLEET_* env knobs);
        # "serve_quant" is the int8-KV capacity arm — concurrent
        # sessions per fixed HBM budget (int8 vs bf16 pool) plus the
        # raw-vs-int4 handoff wire bytes (QUANT_SERVE_* env knobs);
        # "serve_tier" is the host-memory KV tier arm — sessions held
        # per HBM GB (tiered vs HBM-only), warm-resume TTFT vs cold
        # re-prefill, and the distilled-drafter acceptance edge
        # (TIER_SERVE_* env knobs);
        # "serve_procs" is the cross-process fleet — worker subprocesses
        # behind the socket transport, routing A/B + chaos + disagg
        # arms over one diurnal/bursty schedule (PROCS_* env knobs);
        # "chaos_fleet" is the fault-matrix certification — every
        # transport fault family (drop/delay/dup/corrupt/partition)
        # plus kill/crash-loop/hedge arms over the same schedule, gated
        # on zero drops + bit-identical streams (CHAOS_FLEET_* knobs);
        # "obs_fleet" is the observability-plane certification — tracer
        # emit-point overhead vs disabled, and clock-sync offset
        # accuracy against a skewed-clock worker subprocess under the
        # clean/delay/dup net-fault arms (OBS_* env knobs);
        # "replay_fleet" is the fleet black-box certification — record
        # a chaos-fault arm into the append-only journal, re-drive a
        # fresh fleet from the journal alone and require bit-identical
        # token streams, bounded journal overhead, and a corrupted
        # journal to be named by uid + decode step (REPLAY_* env knobs);
        # "deploy_drill" is the zero-downtime operations certification —
        # a SIGKILL, a rolling weight swap (live sessions migrating out
        # warm, canary parity gating each rejoin), an autoscale swing,
        # and a corrupted-canary abort, all during the diurnal peak,
        # gated on zero drops + bit-identical streams (DRILL_* knobs)
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        import serve_bench

        if os.environ.get("BENCH_MODE") == "serve_fleet":
            for arm_result in serve_bench.run_fleet():
                print(json.dumps(arm_result))
        elif os.environ.get("BENCH_MODE") == "serve_slo":
            print(json.dumps(serve_bench.run_slo()))
        elif os.environ.get("BENCH_MODE") == "serve_quant":
            quant_payload = serve_bench.run_quant()
            print(json.dumps(quant_payload))
            if not quant_payload.get("ok", True):
                sys.exit(1)  # same fail-loud contract as BENCH_QUANT
        elif os.environ.get("BENCH_MODE") == "serve_tier":
            tier_payload = serve_bench.run_tier()
            print(json.dumps(tier_payload))
            if not tier_payload.get("ok", True):
                sys.exit(1)  # gates: sessions ratio, warm-resume TTFT,
                #             bit-identity, distilled-drafter edge
        elif os.environ.get("BENCH_MODE") == "serve_procs":
            procs_payload = serve_bench.run_procs()
            print(json.dumps(procs_payload))
            if not procs_payload.get("ok", True):
                sys.exit(1)  # gates: routing A/B, zero drops, wire ratio
        elif os.environ.get("BENCH_MODE") == "chaos_fleet":
            chaos_payload = serve_bench.run_chaos_fleet()
            print(json.dumps(chaos_payload))
            if not chaos_payload.get("ok", True):
                sys.exit(1)  # gates: zero drops, bit-identical, p99.9
        elif os.environ.get("BENCH_MODE") == "obs_fleet":
            obs_payload = serve_bench.run_obs_fleet()
            print(json.dumps(obs_payload))
            if not obs_payload.get("ok", True):
                sys.exit(1)  # gates: trace overhead, offset-in-bound
        elif os.environ.get("BENCH_MODE") == "replay_fleet":
            replay_payload = serve_bench.run_replay_fleet()
            print(json.dumps(replay_payload))
            if not replay_payload.get("ok", True):
                sys.exit(1)  # gates: bit-identical replay, journal
                #             overhead/bytes, corrupt-journal naming
        elif os.environ.get("BENCH_MODE") == "deploy_drill":
            drill_payload = serve_bench.run_deploy_drill()
            print(json.dumps(drill_payload))
            if not drill_payload.get("ok", True):
                sys.exit(1)  # gates: zero drops, bit-identical, warm
                #             migration, swap parity + abort path
        else:
            print(json.dumps(serve_bench.run()))
        return

    if int(os.environ.get("BENCH_LONGCTX", "0")):
        # long-context tier: planner + analytic per-region attribution
        # (attn / sp_comm / host_kv_stream, exposed vs hidden) — no
        # compiled step; see longctx_bench_report and make bench-longctx
        table, payload = longctx_bench_report()
        print(table)
        print(json.dumps(payload))
        return

    if int(os.environ.get("BENCH_KERNELS", "0")):
        # kernel tier win/loss (make bench-kernels): each Pallas kernel
        # vs its XLA fallback per shape bucket, block-geometry sweep,
        # measured rows recorded into the dispatch table
        # (docs/autotuned/kernel_table.json on TPU; scratch elsewhere).
        # Gates: kernel-vs-XLA numerics per bucket, and the recorded
        # table must provably steer multi_head_attention — losing
        # buckets route to XLA bit-identically. Fail-loud like
        # BENCH_QUANT. KERNEL_BENCH_* env knobs (tools/kernel_bench.py).
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from kernel_bench import run_kernel_bench

        table, payload, ok = run_kernel_bench()
        print(table)
        print(json.dumps(payload))
        if not ok:
            raise SystemExit(1)
        return

    if int(os.environ.get("BENCH_QUANT", "0")):
        # quantization acceptance gates (make bench-quant): per-region
        # SNR / max-rel-error on real params+grads, the bit-exact
        # off-switch, fail-loud exit on violation. CPU-safe — the
        # quantizer math is measured directly (observability/
        # quant_stats.py run_quant_bench); BENCH_QUANT_INJECT=
        # corrupt_scale demonstrates the nonzero exit.
        from deepspeed_tpu.observability.quant_stats import \
            run_quant_bench

        table, payload, ok = run_quant_bench()
        print(table)
        print(json.dumps(payload))
        if not ok:
            raise SystemExit(1)
        return

    import jax
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.zoo import get_model

    n_chips = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"

    # shape + perf knobs resolve in one place (resolve_bench_defaults —
    # tier-1 tested): real shape 8L + 131,072 vocab by default, the
    # round-5 3L/8k resident-param proxy behind BENCH_PROXY=1, tuned
    # defaults read back from docs/autotuned/real_shape.json
    knobs = resolve_bench_defaults(on_tpu=on_tpu, n_chips=n_chips)
    model_name = knobs["model_name"]
    llama_headline = model_name == "llama3-8b"
    real_shape = knobs["real_shape"]
    long_ctx = knobs["long_ctx"]
    seq = knobs["seq"]
    micro = knobs["micro"]
    policy = knobs["remat_policy"]
    device_step = knobs["measure"] == "device_step" and on_tpu
    gas = int(os.environ.get("BENCH_GAS", 1))
    steps = int(os.environ.get(
        "BENCH_STEPS",
        (3 if long_ctx else (10 if device_step else 20)) if on_tpu
        else 3))
    warmup = int(os.environ.get("BENCH_WARMUP", 3 if on_tpu else 1))
    if long_ctx:
        warmup = 1
    if device_step:
        warmup = int(os.environ.get("BENCH_WARMUP", 1))

    # remat costs ~30% extra FLOPs but is what bounds activation memory at
    # large micro-batches; tiled logits chunk the [B,S,V] fp32 logits+loss
    # (the HBM ceiling for small-vocab-heavy models like GPT-2)
    remat = bool(int(os.environ.get("BENCH_REMAT", "1")))
    tiled = knobs["tiled_logits"]
    tiled_mlp = knobs["tiled_mlp"]
    attn = os.environ.get("BENCH_ATTN", "auto")
    overrides = dict(max_seq_len=seq, remat=remat, tiled_logits=tiled,
                     tiled_mlp=tiled_mlp, attn_impl=attn,
                     remat_policy=policy)
    if llama_headline:
        overrides["num_layers"] = knobs["layers"]
        overrides["vocab_size"] = knobs["vocab"]
    if knobs["attn_chunks"]:
        overrides["attn_chunks"] = knobs["attn_chunks"]
    if int(os.environ.get("BENCH_FPDT", "0")):
        # FPDT host-KV streaming (beyond-HBM sequence lengths): K/V tiles
        # live in pinned host memory, q chunks stream them back
        overrides["fpdt_host_kv"] = True
        overrides["attn_chunks"] = int(os.environ.get("BENCH_ATTN_CHUNKS",
                                                      "8"))
        if int(os.environ.get("BENCH_FPDT_RESIDUAL", "0")):
            # residual stream hosted too: no full-S device buffer at all
            overrides["fpdt_host_residual"] = True
    if not on_tpu:  # CPU smoke: shrink the model
        overrides.update(num_layers=2, hidden_size=256, num_heads=8,
                         vocab_size=2048)
        if llama_headline:
            overrides.update(num_kv_heads=4, ffn_size=512)
    model = get_model(model_name, **overrides)

    # zero stage + mesh topology decided ONCE, up front: the autotuner's
    # trial engines must run under the same mesh as the final engine or
    # the tuned settings are measured against a different program
    zero_stage = knobs["zero_stage"]
    offload = knobs["offload"]
    topology = ({"dp": 1, "fsdp": -1} if (n_chips > 1 or zero_stage == 3)
                else None)

    # BENCH_AUTOTUNE=1: let the autotuner pick micro batch + remat policy
    # (reference: the CLI launches Autotuner.tune() before real training,
    # launcher/runner.py:407). The chosen settings land in the JSON line.
    config_source = knobs["config_source"]
    if int(os.environ.get("BENCH_AUTOTUNE", "0")) and on_tpu:
        from deepspeed_tpu.autotuning.autotuner import Autotuner

        def model_factory():
            return get_model(model_name, **overrides)

        vocab = model.config.vocab_size

        def batch_fn(global_batch):
            rng_ = np.random.default_rng(0)
            return {"input_ids": rng_.integers(
                0, vocab, (global_batch, seq + 1)).astype(np.int32)}

        space = {
            "micro_batch_sizes": [micro // 2, micro, micro + micro // 2],
            "zero_stages": [zero_stage],
            "remat": [True],
            "remat_policies": ["nothing_saveable", "save_attn_out"],
        }
        persist = None
        if real_shape:
            # the real-shape sweep: vocab-head tile x attention chunks x
            # layer-prefetch ring depth on top of micro x policy; winner
            # persists as the bench's future defaults
            space["tiled_logits"] = [4, 8, 16]
            space["attn_chunks"] = [None, 4]
            space["prefetch_depths"] = [2, 4]
            space["overlap_depths"] = [0, 2, 4]
            persist = os.environ.get(
                "BENCH_TUNED_DEFAULTS",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "docs", "autotuned", "real_shape.json"))
        tuner = Autotuner(model_factory, {
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "adamw",
                          "params": {"lr": 1e-4, "weight_decay": 0.1}},
            "bf16": {"enabled": True}, "steps_per_print": 1_000_000,
        }, batch_fn, tuning_space=space, topology=topology,
            persist_path=persist)
        best = tuner.tune(top_k=4, measure_steps=3)
        if best is not None:
            best = Autotuner.tuned_defaults(best)
            micro = int(best["train_micro_batch_size_per_chip"])
            policy = best.get("remat_policy", policy)
            overrides["remat_policy"] = policy
            if "tiled_logits" in best:
                overrides["tiled_logits"] = int(best["tiled_logits"])
            if best.get("attn_chunks"):
                overrides["attn_chunks"] = int(best["attn_chunks"])
            ppd_best = (best.get("performance") or {}).get(
                "param_prefetch_depth")
            if ppd_best is not None:
                knobs["param_prefetch_depth"] = int(ppd_best)
            od_best = (best.get("performance") or {}).get("overlap_depth")
            if od_best is not None:
                knobs["overlap_depth"] = int(od_best)
            model = get_model(model_name, **overrides)
            config_source = "autotuner"

    # pipelined loop: dispatch-ahead keeps K steps in flight so the host
    # input pull/stack/transfer overlaps device compute, and the engine
    # promotes the (repeatedly-passed) data iterator to a background
    # prefetching iterator (runtime/prefetch.py). Depth 0 restores the
    # blocking loop for A/B comparison (BENCH_PIPELINE_DEPTH=0).
    pipeline_depth = int(os.environ.get("BENCH_PIPELINE_DEPTH", "2"))
    prefetch_depth = int(os.environ.get("BENCH_PREFETCH_DEPTH", "2"))
    performance = {"pipeline_depth": pipeline_depth,
                   "prefetch_depth": prefetch_depth}
    if knobs["param_prefetch_depth"] is not None:
        # ZeRO-Infinity layer-prefetch ring depth (docs/performance.md);
        # 1 = plain double buffering, bit-identical to pre-ring behavior
        performance["param_prefetch_depth"] = knobs["param_prefetch_depth"]
    if knobs["fp8_mlp"]:
        performance["fp8_mlp"] = True
    if knobs["overlap_depth"] is not None:
        # per-layer overlap engine stage depth (docs/performance.md);
        # 0 = keep the ring, drop the pin_stage barriers (A/B baseline)
        performance["overlap_depth"] = knobs["overlap_depth"]
    config = {
        "train_micro_batch_size_per_chip": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": zero_stage},
        "bf16": {"enabled": True},
        "performance": performance,
        "steps_per_print": 1_000_000,
    }
    quant_mode = knobs.get("quant_mode", "off")
    if quant_mode != "off" and (n_chips > 1 or int(os.environ.get(
            "BENCH_QUANT_FORCE", "0"))):
        # ZeRO++ quantized collectives per the tuned/env quant_mode. On
        # a 1-chip rig the paths are inert (fsdp=1: nothing to gather
        # or reduce) and the flags only produce wiring warnings, so the
        # mode is applied when a real mesh exists (or forced for A/B).
        from deepspeed_tpu.autotuning.autotuner import parse_quant_mode

        config["zero_optimization"].update(parse_quant_mode(quant_mode))
    if offload:
        # ZeRO-Offload mode: fp32 master + Adam state live in host RAM,
        # the chip keeps bf16 params only (capacity benchmark — the
        # reference's "13B on one GPU" claim class)
        config["zero_optimization"]["offload_optimizer"] = {
            "device": "cpu",
            "grad_transfer_dtype": os.environ.get("BENCH_GRAD_DTYPE",
                                                  "bf16")}
    if offload >= 2:
        # ZeRO-Infinity pairing: layer params stream from pinned host
        # memory one layer at a time (offload_param)
        config["zero_optimization"]["offload_param"] = {"device": "cpu"}
    if offload and int(os.environ.get("BENCH_ZENFLOW", "0")):
        # ZenFlow: top-k coordinates update on device every step, the
        # host master pass overlaps (importance-split offload — hides
        # most of the host optimizer cost the plain offload mode pays)
        config["zero_optimization"]["zenflow"] = {
            "topk_ratio": float(os.environ.get("BENCH_ZENFLOW_TOPK", "0.05")),
            "update_interval": int(os.environ.get("BENCH_ZENFLOW_UI", "4")),
            "overlap_step": True,
        }
    engine, _, _, _ = dstpu.initialize(model=model, config=config,
                                       topology=topology)

    rng = np.random.default_rng(0)
    B = engine.micro_batch_size * engine.dp_world_size
    batch = {"input_ids": rng.integers(
        0, model.config.vocab_size, (B, seq + 1)).astype(np.int32)}

    def it():
        while True:
            yield batch

    data = it()
    batches = scale = None
    if device_step:
        # chip-side headline: time the compiled fwd+bwd program alone —
        # embedding, all layers with streamed host param fetches, the
        # 131k-vocab unembed+loss, full backward, ending at the grads
        # handed to the host optimizer tier. The FULL step at this shape
        # is bound by host Adam on a 1-core rig and answers a different
        # question (tools/device_step_bench.py rationale).
        import jax.numpy as jnp

        batches = engine._next_microbatches(
            iter(lambda: batch, None), engine.gradient_accumulation_steps)
        scale = jnp.asarray(1.0, jnp.float32)
        for _ in range(warmup):
            grads, loss = engine._jit_grad_step(engine.params, batches,
                                                scale)
            jax.block_until_ready(loss)
            del grads
    else:
        for _ in range(warmup):
            loss = engine.train_batch(data)
        engine.synchronize()  # drain the dispatch-ahead window first
        jax.block_until_ready(loss)

    # Median-of-k measurement with a host-contention sentinel. This repo
    # benches on a 1-core host the driver shares with other work; a single
    # 20-step sample has been observed 28% low purely from host load
    # (BENCH_r04 vs a fresh run at the same commit). Defense: measure k
    # independent windows, record the 1-minute loadavg at each window
    # start, drop windows that began under heavy load when clean ones
    # exist, resample while the spread is wide, and report the median
    # plus the full per-window evidence so an outlier is visible in the
    # artifact instead of silently becoming the headline.
    tokens_per_window = B * seq * steps * gas  # train_batch runs gas microbatches

    def loadavg():
        try:
            return os.getloadavg()[0]
        except OSError:
            return -1.0

    def measure_window():
        # loadavg is a 1-minute EMA, so the run's own compile/warmup burst
        # lingers into the first windows; min(start, end) reads through
        # that decaying tail, while genuine external contention persists
        # across the window and keeps both samples high
        load0 = loadavg()
        if device_step:
            t0 = time.perf_counter()
            for _ in range(steps):
                # free each step's grad tree before the next launch: two
                # live generations of 2.8B-param bf16 grads do not fit
                # alongside the streamed layers
                grads, loss = engine._jit_grad_step(engine.params,
                                                    batches, scale)
                jax.block_until_ready(loss)
                del grads
            dt = time.perf_counter() - t0
            load = min(load0, loadavg()) if load0 >= 0 else load0
            return tokens_per_window / dt / n_chips, load, loss, None, None
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(data)
        engine.synchronize()  # window ends when every in-flight step lands
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        load = min(load0, loadavg()) if load0 >= 0 else load0
        # the engine's own per-step MFU over exactly this window's steps
        # (observability hub StepTrace rows) — same formula + peak table,
        # timed per step instead of per window
        hub = getattr(engine, "hub", None)
        emfu = hub.window_mfu(last_n=steps) if hub is not None else None
        # host time on the dispatch critical path per step (input pull +
        # stack + transfer + jit-call overhead) — the cost the pipelined
        # loop hides; a regression here shows up even when device math
        # still dominates the wall clock
        hgap = (hub.window_host_gap_ms(last_n=steps)
                if hub is not None else None)
        return tokens_per_window / dt / n_chips, load, loss, emfu, hgap

    # capacity-probe runs (BENCH_STEPS=1 on host-optimizer shapes where a
    # step takes minutes) default to one window; normal runs take three
    n_windows = max(1, int(os.environ.get(
        "BENCH_WINDOWS", 3 if (on_tpu and steps > 1) else 1)))
    max_windows = int(os.environ.get("BENCH_MAX_WINDOWS",
                                     max(n_windows + 2, 5)))
    load_max = float(os.environ.get("BENCH_LOAD_MAX", "2.0"))
    spread_target = float(os.environ.get("BENCH_SPREAD_TARGET", "0.05"))

    windows = []  # (tok/s/chip, loadavg, engine-window-mfu, host-gap-ms)
    for _ in range(n_windows):
        tps, load, loss, emfu, hgap = measure_window()
        windows.append((tps, load, emfu, hgap))
    # resample while spread is wide and budget remains — one contended
    # window out of three still skews the median less than it skews a
    # single-sample mean, and extra clean windows dilute it further.
    # With >=4 kept windows the single slowest value is trimmed before
    # the spread check: contention noise on this host is one-sided (it
    # only slows windows down), so the slowest window is the suspect one
    # and the fastest is never discarded. Without a trim, max-min never
    # shrinks and resampling could not converge.
    def kept_and_spread():
        clean = [w for w in windows if 0.0 <= w[1] <= load_max]
        kept = clean if clean else windows
        ordered = sorted(kept, key=lambda w: w[0])
        trimmed = 0
        if len(ordered) >= 4:
            ordered = ordered[1:]
            trimmed = 1
        vals = [w[0] for w in ordered]
        med = statistics.median(vals)
        spread = (max(vals) - min(vals)) / med if med > 0 else 0.0
        # engine MFU + host gap through the SAME window selection, so a
        # contended window dropped from the throughput median is dropped
        # from these medians too
        emfus = [w[2] for w in ordered if w[2] is not None]
        emfu_med = statistics.median(emfus) if emfus else None
        hgaps = [w[3] for w in ordered if w[3] is not None]
        hgap_med = statistics.median(hgaps) if hgaps else None
        return kept, med, spread, trimmed, emfu_med, hgap_med

    kept, med, spread, trimmed, engine_mfu, host_gap_ms = kept_and_spread()
    while (len(windows) < max_windows
           and (spread > spread_target or len(kept) < min(3, n_windows))):
        tps, load, loss, emfu, hgap = measure_window()
        windows.append((tps, load, emfu, hgap))
        kept, med, spread, trimmed, engine_mfu, host_gap_ms = \
            kept_and_spread()

    tok_per_sec_chip = med
    contended = len(kept) < len(windows) or any(
        w[1] > load_max for w in windows)
    flops_per_token = model.flops_per_token()
    peak = detect_peak_tflops(jax.devices()[0])
    mfu = tok_per_sec_chip * flops_per_token / (peak * 1e12)

    baseline = {}
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}) or {}
    except Exception:
        pass
    base_key = ("llama3_8b_geom_tokens_per_sec_per_chip" if llama_headline
                else "gpt2_125m_tokens_per_sec_per_chip")
    base_tps = baseline.get(base_key)
    vs_baseline = (tok_per_sec_chip / base_tps) if base_tps else 1.0

    # overlap-engine accounting: how much of the param-stream traffic
    # the staged schedule hides behind this run's measured step, and the
    # exposed remainder (the round-7 headline delta — docs/roofline.md)
    step_ms = (B * seq * gas / (tok_per_sec_chip * n_chips) * 1e3
               if tok_per_sec_chip > 0 else None)
    hidden_comm_frac, exposed_param_fetch_ms = overlap_report(
        model, step_ms, knobs["overlap_depth"], offload >= 2)

    desc = (f"{model_name}-geometry({model.config.num_layers}L, "
            f"vocab {model.config.vocab_size})"
            if llama_headline else model_name)
    mode = ("device fwd+bwd" if device_step
            else f"zero{zero_stage} train")
    print(json.dumps({
        "metric": f"{desc} {mode} tokens/sec/chip "
                  f"(seq={seq}, micro={micro}, {'tpu' if on_tpu else 'cpu-sim'})",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "mfu": round(mfu, 4),
        "engine_mfu": (round(engine_mfu, 4)
                       if engine_mfu is not None else None),
        "host_gap_ms": (round(host_gap_ms, 3)
                        if host_gap_ms is not None else None),
        "pipeline_depth": pipeline_depth,
        "spread_pct": round(100.0 * spread, 2),
        "windows": [round(w[0], 1) for w in windows],
        "load_avg": [round(w[1], 2) for w in windows],
        "windows_kept": len(kept),
        "windows_used": len(kept) - trimmed,
        "trimmed_low": trimmed,
        "contended": contended,
        "config_source": config_source,
        "remat_policy": overrides.get("remat_policy", policy),
        "layers": model.config.num_layers,
        "vocab": model.config.vocab_size,
        "zero_stage": zero_stage,
        "offload": offload,
        "measure": "device_step" if device_step else "train_batch",
        "param_prefetch_depth": knobs["param_prefetch_depth"],
        "overlap_depth": knobs["overlap_depth"],
        "hidden_comm_frac": hidden_comm_frac,
        "exposed_param_fetch_ms": exposed_param_fetch_ms,
        "fp8_mlp": knobs["fp8_mlp"],
        "quant_mode": quant_mode,
        "loss": round(float(loss), 4),
        "chips": n_chips,
    }))


if __name__ == "__main__":
    main()
