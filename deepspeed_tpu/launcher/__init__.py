"""Launcher: head-node fan-out + per-host rendezvous + env report.

Reference: deepspeed/launcher/ (runner.py:436, launch.py:145,
multinode_runner.py) and bin/ds_report.
"""
