"""Head-node launcher: parse topology, fan out one process per host.

Reference: ``deepspeed`` CLI → launcher/runner.py:436 ``main()`` —
hostfile parse (:230), --include/--exclude filters (:310), base64
world-info (:401), multinode runner selection.

TPU re-design: the unit of launch is a *host* (each host owns its local
TPU chips and joins the job via ``jax.distributed.initialize``), not a
device — so `--num_gpus`-style fan-out becomes `--num_hosts`, the
rendezvous is the JAX coordinator (host 0), and on Cloud TPU pods the
platform already launches one worker per host, so `dstpu --tpu-pod` mode
simply execs the script with coordinator env derived from the metadata
server ordering.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

DEFAULT_COORDINATOR_PORT = 8476


# ---------------------------------------------------------------------------
# hostfile parsing + filters (reference runner.py:230,310)
# ---------------------------------------------------------------------------


def parse_hostfile(path_or_lines) -> "OrderedDict[str, int]":
    """``host slots=N`` per line → {host: slots}. Slots on TPU = chips per
    host (informational; launch is per host)."""
    if isinstance(path_or_lines, str):
        with open(path_or_lines) as f:
            lines = f.readlines()
    else:
        lines = list(path_or_lines)
    out: "OrderedDict[str, int]" = OrderedDict()
    for raw in lines:
        line = raw.split("#")[0].strip()
        if not line:
            continue
        parts = line.split()
        host = parts[0]
        slots = 1
        for tok in parts[1:]:
            if tok.startswith("slots="):
                slots = int(tok.split("=", 1)[1])
            else:
                raise ValueError(f"bad hostfile token {tok!r} in {raw!r}")
        if host in out:
            raise ValueError(f"duplicate host {host!r} in hostfile")
        out[host] = slots
    if not out:
        raise ValueError("hostfile is empty")
    return out


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              include: str = "",
                              exclude: str = "",
                              strict: bool = True) -> "OrderedDict[str, int]":
    """Filter hosts: ``host1@host2`` selects hosts; ``host1:0,2`` selects
    slots (reference runner.py:310 syntax). ``strict=False`` skips filter
    hosts missing from the pool instead of raising — elastic polling uses
    it, since a scaled-down hostfile legitimately drops filtered hosts."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")

    def parse_spec(spec: str) -> Dict[str, Optional[List[int]]]:
        out: Dict[str, Optional[List[int]]] = {}
        for part in spec.split("@"):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                host, slots = part.split(":", 1)
                out[host] = [int(s) for s in slots.split(",")]
            else:
                out[part] = None
        return out

    pool = OrderedDict(resource_pool)
    if include:
        sel = parse_spec(include)
        for host in sel:
            if host not in pool and strict:
                raise ValueError(f"--include host {host!r} not in hostfile")
        return OrderedDict(
            (h, len(sel[h]) if sel[h] is not None else pool[h])
            for h in pool if h in sel)
    if exclude:
        sel = parse_spec(exclude)
        for host in sel:
            if host not in pool and strict:
                raise ValueError(f"--exclude host {host!r} not in hostfile")
        sel = {h: v for h, v in sel.items() if h in pool}
        out = OrderedDict()
        for h, slots in pool.items():
            if h not in sel:
                out[h] = slots
            elif sel[h] is not None:  # exclude only some slots
                keep = slots - len(sel[h])
                if keep > 0:
                    out[h] = keep
        if not out:
            raise ValueError("--exclude removed every host")
        return out
    return pool


def encode_world_info(resource_pool: Dict[str, int]) -> str:
    """base64 world info passed to every node (reference runner.py:401)."""
    return base64.urlsafe_b64encode(
        json.dumps(resource_pool).encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, int]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


# ---------------------------------------------------------------------------
# multinode runners (reference launcher/multinode_runner.py)
# ---------------------------------------------------------------------------


class MultiNodeRunner:
    """Build the per-host command line. Subclasses cover transports."""

    name = "base"

    def __init__(self, args, world_info: str):
        self.args = args
        self.world_info = world_info

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, int]) -> List[str]:
        raise NotImplementedError

    @property
    def bind_args(self) -> List[str]:
        out = []
        if getattr(self.args, "bind_cores_to_rank", False):
            out.append("--bind_cores_to_rank")
        if getattr(self.args, "bind_core_list", None):
            out.append(f"--bind_core_list={self.args.bind_core_list}")
        return out

    @property
    def user_arguments(self) -> List[str]:
        return list(self.args.user_args or [])


class SSHRunner(MultiNodeRunner):
    """Plain ssh fan-out (the pdsh analog, multinode_runner.py:55): one ssh
    per host running launch.py with that host's process index."""

    name = "ssh"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("ssh") is not None

    def get_cmd(self, environment, active_resources) -> List[List[str]]:
        hosts = list(active_resources)
        coordinator = f"{hosts[0]}:{self.args.coordinator_port}"
        cmds = []
        exports = " ".join(
            f"export {k}={shlex.quote(v)};" for k, v in environment.items())
        for idx, host in enumerate(hosts):
            inner = (
                f"{exports} cd {shlex.quote(os.path.abspath(os.getcwd()))}; "
                f"{sys.executable} -m deepspeed_tpu.launcher.launch "
                f"--coordinator_address={coordinator} "
                f"--process_id={idx} --num_processes={len(hosts)} "
                f"--world_info={self.world_info} "
                + "".join(f"{shlex.quote(a)} " for a in self.bind_args)
                + f"{shlex.quote(self.args.user_script)} "
                + " ".join(map(shlex.quote, self.user_arguments))
            )
            cmds.append(["ssh", "-o", "StrictHostKeyChecking=no", host,
                         inner])
        return cmds


class GCERunner(MultiNodeRunner):
    """Cloud TPU pod: gcloud compute tpus tpu-vm ssh --worker=all runs the
    same command on every worker; process ids come from the TPU metadata
    (JAX does this automatically on TPU VMs)."""

    name = "gce"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("gcloud") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        if self.bind_args:
            logger.warning("gce launcher runs the script directly (no "
                           "dstpu-launch); --bind_cores_to_rank is ignored")
        exports = " ".join(
            f"export {k}={shlex.quote(v)};" for k, v in environment.items())
        inner = (f"{exports} {sys.executable} "
                 f"{shlex.quote(self.args.user_script)} "
                 + " ".join(map(shlex.quote, self.user_arguments)))
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
                self.args.tpu_name, f"--zone={self.args.tpu_zone}",
                "--worker=all", f"--command={inner}"]


class SlurmRunner(MultiNodeRunner):
    """srun fan-out (multinode_runner.py:260 analog)."""

    name = "slurm"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("srun") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        hosts = list(active_resources)
        cmd = ["srun", f"--nodes={len(hosts)}", "--ntasks-per-node=1",
               f"--nodelist={','.join(hosts)}"]
        for k, v in environment.items():
            cmd.append(f"--export=ALL,{k}={v}")
        cmd += [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                "--slurm_managed",
                f"--coordinator_address={hosts[0]}:{self.args.coordinator_port}",
                f"--num_processes={len(hosts)}",
                f"--world_info={self.world_info}",
                *self.bind_args,
                self.args.user_script] + self.user_arguments
        return cmd


class MPIRunner(MultiNodeRunner):
    """mpirun fan-out (OpenMPI analog, multinode_runner.py:126): ranks map
    to hosts; launch.py reads OMPI env for its process id."""

    name = "mpi"

    def backend_exists(self) -> bool:
        from shutil import which

        return which("mpirun") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        hosts = list(active_resources)
        cmd = ["mpirun", "-np", str(len(hosts)),
               "--host", ",".join(hosts)]
        for k, v in environment.items():
            cmd += ["-x", f"{k}={v}"]
        cmd += [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                "--mpi_managed",
                f"--coordinator_address={hosts[0]}:{self.args.coordinator_port}",
                f"--num_processes={len(hosts)}",
                f"--world_info={self.world_info}",
                *self.bind_args,
                self.args.user_script] + self.user_arguments
        return cmd


RUNNERS = {r.name: r for r in
           (SSHRunner, GCERunner, SlurmRunner, MPIRunner)}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dstpu",
        description="deepspeed_tpu launcher (reference: deepspeed CLI, "
                    "launcher/runner.py:436)")
    p.add_argument("-H", "--hostfile", default=None,
                   help="host slots=N per line; default: localhost only")
    p.add_argument("-i", "--include", default="",
                   help="host[:slot,...]@host2 inclusion filter")
    p.add_argument("-e", "--exclude", default="",
                   help="exclusion filter, same syntax")
    p.add_argument("--launcher", default="ssh", choices=sorted(RUNNERS),
                   help="multinode transport")
    p.add_argument("--coordinator_port", type=int,
                   default=DEFAULT_COORDINATOR_PORT)
    p.add_argument("--tpu_name", default=os.environ.get("TPU_NAME", ""))
    p.add_argument("--tpu_zone", default=os.environ.get("TPU_ZONE", ""))
    p.add_argument("--dry_run", action="store_true",
                   help="print the per-host commands, do not execute")
    p.add_argument("--run_dir", default=None,
                   help="shared fleet-observability run dir (exported as "
                        "DSTPU_RUN_DIR to every worker; see "
                        "docs/observability.md). Multi-host launches "
                        "default to ./dstpu_runs/<timestamp> so per-rank "
                        "heartbeat/step shards and flight-recorder dumps "
                        "land somewhere aggregable for free; pass "
                        "--run_dir '' to disable")
    p.add_argument("--bind_cores_to_rank", action="store_true",
                   help="pin each worker's host threads to its NUMA core "
                        "slice (forwarded to dstpu-launch)")
    p.add_argument("--bind_core_list", default=None,
                   help="restrict binding to these cores, '0-15,32-47'")
    p.add_argument("--elastic_training", action="store_true",
                   help="supervise workers with the elastic agent: restart "
                        "on failure/membership change (reference "
                        "runner.py:88-102)")
    p.add_argument("--min_elastic_nodes", type=int, default=1)
    p.add_argument("--max_elastic_nodes", type=int, default=64)
    p.add_argument("--max_restarts", type=int, default=100)
    p.add_argument("--restart_backoff_s", type=float, default=1.0,
                   help="base backoff before restarting a group that died "
                        "of a transient comm failure (exit 75, see "
                        "docs/resilience.md); grows exponentially with "
                        "the restart count")
    p.add_argument("user_script", nargs="?", default=None)
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def resolve_launch_run_dir(args, multi_host: bool) -> Optional[str]:
    """Pick the DSTPU_RUN_DIR exported to every worker.

    Precedence: explicit --run_dir ('' disables) > inherited env >
    auto timestamped dir for multi-host launches. Single-host runs get
    no implicit dir — they pay zero shard I/O unless asked.
    """
    if args.run_dir is not None:
        return os.path.abspath(args.run_dir) if args.run_dir else None
    inherited = os.environ.get("DSTPU_RUN_DIR")
    if inherited:
        return inherited
    if multi_host:
        import time

        return os.path.abspath(
            os.path.join("dstpu_runs", time.strftime("%Y%m%d-%H%M%S")))
    return None


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.user_script is None:
        print("dstpu: error: user_script is required (see dstpu --help)",
              file=sys.stderr)
        return 2

    if args.hostfile:
        pool = parse_hostfile(args.hostfile)
    else:
        pool = OrderedDict(localhost=1)
    active = parse_inclusion_exclusion(pool, args.include, args.exclude)
    world_info = encode_world_info(dict(active))

    if args.elastic_training and not args.hostfile:
        raise RuntimeError("--elastic_training requires --hostfile")

    single_host = not args.elastic_training and \
        len(active) == 1 and next(iter(active)) == "localhost"
    run_dir = resolve_launch_run_dir(args, multi_host=not single_host)
    if run_dir:
        logger.info(f"fleet observability run dir: {run_dir}")

    if single_host:
        # single-host: exec in place, no ssh (reference runner does the
        # same for single-node jobs)
        cmd = [sys.executable, args.user_script] + list(args.user_args or [])
        if args.dry_run:
            print(shlex.join(cmd))
            return 0
        if run_dir:
            os.environ["DSTPU_RUN_DIR"] = run_dir
        if args.bind_cores_to_rank or args.bind_core_list:
            # bind in the parent; the child inherits affinity + OMP env
            from deepspeed_tpu.utils.numa import bind_current_process

            cores = bind_current_process(0, 1, args.bind_core_list)
            logger.info(f"bound to cores {cores}")
        return subprocess.call(cmd)

    env = {"DSTPU_WORLD_INFO": world_info}
    if run_dir:
        env["DSTPU_RUN_DIR"] = run_dir
    runner = RUNNERS[args.launcher](args, world_info)
    if not args.dry_run and not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher!r} not found")

    if args.elastic_training:
        from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent

        def filtered_pool() -> "OrderedDict[str, int]":
            # re-read + re-filter every round so scale-up/down respects
            # --include/--exclude just like the initial launch; non-strict
            # so a scaled-down hostfile missing a filter host is fine
            return parse_inclusion_exclusion(
                parse_hostfile(args.hostfile), args.include, args.exclude,
                strict=False)

        def membership():
            # raises on a mid-rewrite hostfile; the agent keeps the last
            # known membership across such transients
            return list(filtered_pool())

        def build_cmds(hosts, restart_count):
            try:
                slots = filtered_pool()
            except (OSError, ValueError):
                # hostfile mid-rewrite: fall back to the membership list
                # (slots are informational on TPU; launch is per host)
                slots = {}
            pool = OrderedDict((h, slots.get(h, 1)) for h in hosts)
            wi = encode_world_info(dict(pool))
            r = RUNNERS[args.launcher](args, wi)
            # exported on the remote side too (ssh builds exports from
            # this dict; local-process env alone never crosses ssh)
            renv = {
                "DSTPU_WORLD_INFO": wi,
                "DSTPU_ELASTIC_RESTART_COUNT": str(restart_count),
                "DSTPU_ELASTIC_WORLD": ",".join(hosts),
            }
            if run_dir:
                renv["DSTPU_RUN_DIR"] = run_dir
            cmds = r.get_cmd(renv, pool)
            return [cmds] if isinstance(cmds[0], str) else cmds

        if args.dry_run:
            for c in build_cmds(membership() or list(active), 0):
                print(shlex.join(c))
            return 0
        agent = ElasticAgent(
            build_cmds, membership,
            min_nodes=args.min_elastic_nodes,
            max_nodes=args.max_elastic_nodes,
            max_restarts=args.max_restarts,
            restart_backoff_s=args.restart_backoff_s)
        return agent.run()
    cmds = runner.get_cmd(env, active)
    if isinstance(cmds[0], str):
        cmds = [cmds]  # single fan-out command (gce/slurm/mpi)
    if args.dry_run:
        for c in cmds:
            print(shlex.join(c))
        return 0
    procs = [subprocess.Popen(c) for c in cmds]
    rc = 0
    try:
        for p in procs:
            rc |= p.wait()
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        raise
    return rc


if __name__ == "__main__":
    sys.exit(main())
