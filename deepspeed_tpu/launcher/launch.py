"""Per-host launcher: join the JAX distributed rendezvous and exec the
user script.

Reference: launcher/launch.py:145 spawns one process per CUDA device with
RANK/LOCAL_RANK/WORLD_SIZE env and a torch rendezvous. On TPU one process
per *host* owns all local chips, and the rendezvous is
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)`` — so this script resolves its process id (from the CLI,
SLURM, or MPI env), initializes the JAX distributed runtime, then runs
the user script in-process (signal handling kills the child process tree
on SIGTERM like launch.py:131).
"""

from __future__ import annotations

import argparse
import os
import runpy
import signal
import sys

from deepspeed_tpu.utils.logging import logger


def _resolve_process_id(args) -> int:
    if args.process_id is not None:
        return args.process_id
    if args.slurm_managed:
        return int(os.environ["SLURM_PROCID"])
    if args.mpi_managed:
        for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK"):
            if var in os.environ:
                return int(os.environ[var])
        raise RuntimeError("MPI-managed launch but no MPI rank env found")
    raise RuntimeError("need --process_id (or --slurm_managed/--mpi_managed)")


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="dstpu-launch")
    p.add_argument("--coordinator_address", required=True)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--num_processes", type=int, required=True)
    p.add_argument("--world_info", default="")
    p.add_argument("--slurm_managed", action="store_true")
    p.add_argument("--mpi_managed", action="store_true")
    p.add_argument("--module", action="store_true",
                   help="run user_script as a module (python -m)")
    p.add_argument("--bind_cores_to_rank", action="store_true",
                   help="pin host threads to this rank's NUMA core slice "
                        "(reference launch.py --bind_cores_to_rank)")
    p.add_argument("--bind_core_list", default=None,
                   help="restrict binding to these cores, '0-15,32-47'")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    process_id = _resolve_process_id(args)

    # expose reference-compatible env to the user script
    os.environ["RANK"] = str(process_id)
    os.environ["WORLD_SIZE"] = str(args.num_processes)
    os.environ["LOCAL_RANK"] = "0"  # one process per host on TPU
    if args.world_info:
        os.environ["DSTPU_WORLD_INFO"] = args.world_info

    if args.bind_cores_to_rank or args.bind_core_list:
        from deepspeed_tpu.utils.numa import bind_current_process

        # one process per host: local slice index 0 of 1, so binding here
        # mainly restricts to --bind_core_list and sets OMP_NUM_THREADS
        cores = bind_current_process(0, 1, args.bind_core_list)
        logger.info(f"bound process to cores {cores}")

    import jax

    if args.num_processes > 1:
        logger.info(
            f"joining rendezvous at {args.coordinator_address} as "
            f"{process_id}/{args.num_processes}")
        jax.distributed.initialize(
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes,
            process_id=process_id)

    # forward SIGTERM to a clean interpreter exit so atexit/finalizers run
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    # arm the crash flight recorder before user code runs: a worker that
    # dies during import/compile still leaves a dump in the shared run dir
    try:
        from deepspeed_tpu.observability.flight_recorder import (
            get_flight_recorder, install_crash_handlers)

        get_flight_recorder().configure(
            rank=process_id, run_dir=os.environ.get("DSTPU_RUN_DIR"))
        install_crash_handlers()
    except Exception as e:  # observability must never block the launch
        logger.warning(f"flight recorder unavailable: {e}")

    sys.argv = [args.user_script] + list(args.user_args or [])
    if args.module:
        runpy.run_module(args.user_script, run_name="__main__")
    else:
        runpy.run_path(args.user_script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
