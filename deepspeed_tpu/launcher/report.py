"""Environment/compat report CLI (reference: bin/ds_report →
deepspeed/env_report.py): prints versions, device inventory, op-registry
compatibility, and mesh defaults."""

from __future__ import annotations

import os
import platform
import sys


def _row(name, value, width=34):
    return f"{name:.<{width}} {value}"


def main(argv=None) -> int:
    lines = ["-" * 60, "deepspeed_tpu environment report", "-" * 60]

    from deepspeed_tpu.version import __version__

    lines.append(_row("deepspeed_tpu version", __version__))
    lines.append(_row("python", platform.python_version()))
    lines.append(_row("platform", platform.platform()))

    try:
        import jax
        import jaxlib

        lines.append(_row("jax version", jax.__version__))
        lines.append(_row("jaxlib version", jaxlib.__version__))
        try:
            devs = jax.devices()
            lines.append(_row("default backend", jax.default_backend()))
            lines.append(_row("device count", str(len(devs))))
            kinds = sorted({d.device_kind for d in devs})
            lines.append(_row("device kinds", ", ".join(kinds)))
            lines.append(_row("process count", str(jax.process_count())))
        except Exception as e:  # no accelerator: still report
            lines.append(_row("devices", f"unavailable ({e})"))
    except ImportError as e:
        lines.append(_row("jax", f"NOT INSTALLED ({e})"))

    for mod in ("flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = __import__(mod)
            for part in mod.split(".")[1:]:
                m = getattr(m, part)
            lines.append(_row(mod, getattr(m, "__version__", "?")))
        except ImportError:
            lines.append(_row(mod, "not installed"))

    # op registry compat (reference: ds_report op compatibility table)
    lines.append("-" * 60)
    lines.append("op compatibility")
    lines.append("-" * 60)
    try:
        from deepspeed_tpu.ops.registry import all_ops

        for name, op in sorted(all_ops().items()):
            ok, why = op.is_compatible()
            if ok:
                status = f"OK ({why})" if why else "OK"
            else:
                status = f"NO ({why})"
            lines.append(_row(name, status))
    except ImportError:
        lines.append("op registry not available")

    env_flags = {k: v for k, v in os.environ.items()
                 if k.startswith(("JAX_", "XLA_", "LIBTPU", "DSTPU_"))}
    if env_flags:
        lines.append("-" * 60)
        lines.append("relevant environment")
        lines.append("-" * 60)
        for k in sorted(env_flags):
            lines.append(_row(k, env_flags[k]))

    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
