"""Aux fleet CLIs: ``dstpu-ssh`` and ``dstpu-nvme-tune``.

Reference: ``bin/ds_ssh`` (run a command on every hostfile host) and
``bin/ds_nvme_tune`` (sweep AIO knobs on the NVMe scratch volume and
persist the winning configuration for the swap stack to pick up).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List

from deepspeed_tpu.launcher.runner import parse_hostfile

DEFAULT_HOSTFILE = "/job/hostfile"
TUNE_OUTPUT = os.path.expanduser("~/.dstpu_nvme_config.json")


# ---------------------------------------------------------------------------
# dstpu-ssh
# ---------------------------------------------------------------------------

def ssh_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-ssh",
        description="run a shell command on every host in the hostfile "
                    "(reference bin/ds_ssh)")
    ap.add_argument("-H", "--hostfile", default=DEFAULT_HOSTFILE)
    ap.add_argument("--sequential", action="store_true",
                    help="one host at a time instead of parallel fan-out")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run on each host")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    cmd = " ".join(args.command)
    try:
        hosts: List[str] = list(parse_hostfile(args.hostfile))
    except (OSError, ValueError) as e:
        print(f"dstpu-ssh: cannot read hostfile {args.hostfile}: {e}",
              file=sys.stderr)
        return 2

    def launch(host):
        return subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host, cmd])

    rc = 0
    if args.sequential:
        for h in hosts:
            rc |= launch(h).wait()
    else:
        procs = [launch(h) for h in hosts]
        for p in procs:
            rc |= p.wait()
    return rc


# ---------------------------------------------------------------------------
# dstpu-nvme-tune
# ---------------------------------------------------------------------------

def nvme_tune_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu-nvme-tune",
        description="sweep AIO block size / queue depth on an NVMe scratch "
                    "dir and save the fastest config (reference "
                    "bin/ds_nvme_tune); the swap stack reads the saved "
                    "config via deepspeed_tpu.runtime.swap_tensor")
    ap.add_argument("nvme_dir", help="directory on the NVMe volume to tune")
    ap.add_argument("--size-mb", type=int, default=256)
    ap.add_argument("--block-mults", type=int, nargs="+",
                    default=[1, 2, 4, 8, 16])
    ap.add_argument("--queue-depths", type=int, nargs="+",
                    default=[4, 8, 16, 32, 64])
    ap.add_argument("-o", "--output", default=TUNE_OUTPUT,
                    help=f"where to save the best config "
                         f"(default {TUNE_OUTPUT})")
    args = ap.parse_args(argv)

    from deepspeed_tpu.launcher.bench_cli import bench_io
    from deepspeed_tpu.ops.native.aio import (DEFAULT_BLOCK_SIZE,
                                              DEFAULT_THREADS)

    scratch = os.path.join(args.nvme_dir, ".dstpu_nvme_tune.scratch")
    try:  # a previous interrupted sweep may have left its scratch behind
        os.unlink(scratch)
    except OSError:
        pass
    try:
        results = bench_io(scratch, args.size_mb, args.block_mults,
                           args.queue_depths, read=True, write=True)
    finally:
        try:  # ADVICE r1: never leave the sweep's scratch on the NVMe
            os.unlink(scratch)
        except OSError:
            pass
    best = {}
    for op in ("read", "write"):
        rows = [r for r in results if r["op"] == op]
        if rows:
            best[op] = max(rows, key=lambda r: r["gbps"])
    # single config serving both directions: highest min(read,write) speed.
    # bench_io sweeps multiple backends — key on backend too, or rows from
    # the second backend overwrite the first and the pick is meaningless
    by_key = {}
    for r in results:
        key = (r["block_kb"], r["queue_depth"], r.get("backend", "auto"))
        by_key.setdefault(key, {})[r["op"]] = r
    combined = [(min(v[o]["gbps"] for o in v), k) for k, v in by_key.items()]
    (block_kb, queue_depth, backend) = max(combined)[1]
    config = {
        "aio": {
            "block_size": block_kb * 1024,
            "queue_depth": queue_depth,
            "backend": backend,
            # the sweep varies block size / queue depth only; keep the
            # library default rather than writing an unmeasured value
            "thread_count": DEFAULT_THREADS,
        },
        "best_read": best.get("read"),
        "best_write": best.get("write"),
        "nvme_dir": os.path.abspath(args.nvme_dir),
        "default_block_size": DEFAULT_BLOCK_SIZE,
    }
    with open(args.output, "w") as f:
        json.dump(config, f, indent=2)
    print(json.dumps({"saved": args.output, "aio": config["aio"]}))
    return 0


if __name__ == "__main__":
    sys.exit(ssh_main())
