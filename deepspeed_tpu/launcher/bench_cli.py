"""Aux benchmark CLIs.

Reference: ``bin/ds_bench`` (communication benchmark sweep, backed by
DeepSpeedExamples' comm suite) and ``bin/ds_io`` / ``bin/ds_nvme_tune``
(DeepNVMe async-I/O throughput sweep, deepspeed/nvme/).

  * ``dstpu-bench``: collective bandwidth sweep (all_reduce /
    all_gather / reduce_scatter / all_to_all) over a mesh axis, sizes
    swept in powers of two; reports algorithmic bus bandwidth the same
    way the reference's comm benchmarks do.
  * ``dstpu-io``: file read/write throughput through the native AIO
    handle (block size × queue-depth sweep — the ds_nvme_tune
    parameter space).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List
from deepspeed_tpu.utils import jaxcompat


# ---------------------------------------------------------------------------
# dstpu-bench: collective sweep
# ---------------------------------------------------------------------------

def _bus_bandwidth(op: str, nbytes: int, world: int, dt: float) -> float:
    """Algorithmic bus bandwidth in GB/s (reference comms convention:
    ring all-reduce moves 2(n-1)/n of the data, gather/scatter (n-1)/n)."""
    if world <= 1:
        return nbytes / dt / 1e9
    if op == "all_reduce":
        factor = 2 * (world - 1) / world
    elif op in ("all_gather", "reduce_scatter", "all_to_all"):
        factor = (world - 1) / world
    else:
        factor = 1.0
    return nbytes * factor / dt / 1e9


def bench_collectives(axis: str = "dp", sizes_mb: List[float] = (1, 4, 16, 64),
                      ops: List[str] = ("all_reduce", "all_gather",
                                        "reduce_scatter", "all_to_all"),
                      iters: int = 10, out=print) -> List[dict]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu import comm
    from deepspeed_tpu.parallel import topology as topo

    sizes = {axis: -1}
    if axis != "dp":
        sizes["dp"] = 1  # TopologyConfig defaults dp=-1; only one free axis
    mesh = topo._GLOBAL_MESH or topo.build_mesh(topo.TopologyConfig(**sizes))
    world = mesh.shape[axis]
    results = []
    for op in ops:
        for mb in sizes_mb:
            n = int(mb * 1e6 / 4)
            # per-shard count (n/world) must itself divide by world for the
            # all_to_all reshape; round to a world*world multiple
            unit = world * world
            n = max(unit, (n // unit) * unit)
            x = jnp.ones((n,), jnp.float32)

            def body(x):
                if op == "all_reduce":
                    return comm.all_reduce(x, axis)
                if op == "all_gather":
                    return comm.all_gather(x, axis)
                if op == "reduce_scatter":
                    return comm.reduce_scatter(x, axis)
                return comm.all_to_all(x.reshape(world, -1), axis,
                                       split_dim=0, concat_dim=1)

            fn = jax.jit(jaxcompat.shard_map(body, mesh=mesh, in_specs=P(axis),
                                       out_specs=P(axis), check_vma=False))
            r = fn(x)
            jax.block_until_ready(r)  # compile + warm
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(x)
            jax.block_until_ready(r)
            dt = (time.perf_counter() - t0) / iters
            nbytes = x.size * 4 // world  # per-shard payload
            # collective buffer size S per the nccl-tests convention the
            # reference benchmarks follow: all_reduce/reduce_scatter/
            # all_to_all use the per-rank buffer, all_gather the aggregate
            S = nbytes * world if op == "all_gather" else nbytes
            bw = _bus_bandwidth(op, S, world, dt)
            rec = {"op": op, "axis": axis, "world": world,
                   "size_mb": round(S / 1e6, 2),
                   "time_ms": round(dt * 1e3, 3),
                   "busbw_gbps": round(bw, 2)}
            results.append(rec)
            out(json.dumps(rec))
    return results


def bench_main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dstpu-bench",
        description="collective bandwidth sweep (reference bin/ds_bench)")
    ap.add_argument("--axis", default="dp")
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 4, 16, 64])
    ap.add_argument("--ops", nargs="+",
                    default=["all_reduce", "all_gather", "reduce_scatter",
                             "all_to_all"])
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args(argv)
    bench_collectives(args.axis, args.sizes_mb, args.ops, args.iters)
    return 0


# ---------------------------------------------------------------------------
# dstpu-io: AIO throughput sweep
# ---------------------------------------------------------------------------

def bench_io(path: str, size_mb: int = 256, block_sizes=(1, 8, 16),
             queue_depths=(4, 16, 32), read: bool = True,
             write: bool = True, backends=("threads", "auto"),
             out=print) -> List[dict]:
    import numpy as np

    from deepspeed_tpu.ops.native.aio import (AsyncIOHandle,
                                              DEFAULT_BLOCK_SIZE)

    if not read and not write:
        raise ValueError("nothing to do: enable read and/or write")
    if read and not write and not os.path.exists(path):
        raise FileNotFoundError(
            f"read-only sweep needs an existing file at {path}")
    if write and os.path.exists(path):
        raise FileExistsError(
            f"refusing to overwrite existing file {path} — the write sweep "
            "clobbers and deletes its scratch file; pass a fresh path")
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    if read and not write:
        # size from the user's file; never delete it
        data = np.empty(os.path.getsize(path), dtype=np.uint8)
        size_mb = data.nbytes // (1024 * 1024)
    else:
        data = np.random.default_rng(0).integers(
            0, 255, size_mb * 1024 * 1024, dtype=np.uint8)
    results = []
    for backend in backends:
      for bs_mult in block_sizes:
        for qd in queue_depths:
            # pin every knob: a stale tuned config must not parameterize
            # the benchmark that tuned configs are derived from
            from deepspeed_tpu.ops.native.aio import DEFAULT_THREADS

            handle = AsyncIOHandle(block_size=bs_mult * DEFAULT_BLOCK_SIZE,
                                   queue_depth=qd,
                                   num_threads=DEFAULT_THREADS,
                                   backend=backend)
            if write:
                t0 = time.perf_counter()
                handle.pwrite(data, path)
                dt = time.perf_counter() - t0
                rec = {"op": "write", "size_mb": size_mb,
                       "backend": handle.backend,
                       "block_kb": bs_mult * DEFAULT_BLOCK_SIZE // 1024,
                       "queue_depth": qd,
                       "gbps": round(data.nbytes / dt / 1e9, 3)}
                results.append(rec)
                out(json.dumps(rec))
            if read:
                buf = np.empty_like(data)
                t0 = time.perf_counter()
                handle.pread(buf, path)
                dt = time.perf_counter() - t0
                rec = {"op": "read", "size_mb": size_mb,
                       "backend": handle.backend,
                       "block_kb": bs_mult * DEFAULT_BLOCK_SIZE // 1024,
                       "queue_depth": qd,
                       "gbps": round(data.nbytes / dt / 1e9, 3)}
                results.append(rec)
                out(json.dumps(rec))
            handle.close()
    if write:  # only delete scratch files this sweep created
        try:
            os.unlink(path)
        except OSError:
            pass
    return results


def io_main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dstpu-io",
        description="async file I/O throughput sweep (reference bin/ds_io "
                    "+ ds_nvme_tune)")
    ap.add_argument("path", help="scratch file on the device to test")
    ap.add_argument("--size-mb", type=int, default=256)
    ap.add_argument("--block-mults", type=int, nargs="+", default=[1, 8, 16])
    ap.add_argument("--queue-depths", type=int, nargs="+",
                    default=[4, 16, 32])
    ap.add_argument("--read-only", action="store_true")
    ap.add_argument("--write-only", action="store_true")
    args = ap.parse_args(argv)
    if args.read_only and args.write_only:
        ap.error("--read-only and --write-only are mutually exclusive")
    bench_io(args.path, args.size_mb, args.block_mults, args.queue_depths,
             read=not args.write_only, write=not args.read_only)
    return 0


if __name__ == "__main__":
    sys.exit(bench_main())
