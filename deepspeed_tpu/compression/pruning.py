"""Pruning mask construction.

Reference: ``deepspeed/compression/basic_layer.py`` pruning paths +
``helper.py`` — unstructured (sparse), row, channel, and attention-head
pruning, each by L1 magnitude or top-k ratio. Masks are boolean arrays
shaped like (or broadcastable onto) the weight; training applies them
every step (projected SGD), ``redundancy_clean`` bakes them in.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def sparse_pruning_mask(w: np.ndarray, dense_ratio: float,
                        method: str = "l1") -> np.ndarray:
    """Unstructured mask keeping ``dense_ratio`` of entries (reference
    sparse_pruning; method l1 == topk by |w|)."""
    w = np.asarray(w)
    k = int(np.ceil(dense_ratio * w.size))
    if k >= w.size:
        return np.ones_like(w, dtype=bool)
    if method not in ("l1", "topk"):
        raise ValueError(f"unknown sparse pruning method '{method}'")
    thresh = np.partition(np.abs(w).ravel(), w.size - k)[w.size - k]
    return np.abs(w) >= thresh


def row_pruning_mask(w: np.ndarray, dense_ratio: float) -> np.ndarray:
    """Keep the highest-L1 rows (output neurons; reference row_pruning).
    w: [in, out] — rows scored along the input dim, mask broadcasts
    [1, out]."""
    w = np.asarray(w)
    scores = np.abs(w).sum(axis=0)
    k = max(1, int(np.ceil(dense_ratio * scores.size)))
    keep = np.argsort(scores)[-k:]
    mask = np.zeros((1, scores.size), dtype=bool)
    mask[0, keep] = True
    return mask


def channel_pruning_mask(w: np.ndarray, dense_ratio: float) -> np.ndarray:
    """Keep the highest-L1 input channels (reference channel_pruning).
    w: [in, out] — mask broadcasts [in, 1]."""
    w = np.asarray(w)
    scores = np.abs(w).sum(axis=1)
    k = max(1, int(np.ceil(dense_ratio * scores.size)))
    keep = np.argsort(scores)[-k:]
    mask = np.zeros((scores.size, 1), dtype=bool)
    mask[keep, 0] = True
    return mask


def head_pruning_mask(w_o: np.ndarray, num_heads: int,
                      dense_ratio: float) -> Tuple[np.ndarray, np.ndarray]:
    """Attention-head mask from the output projection's magnitude
    (reference head_pruning scores the attention output matrix).

    w_o: [num_heads * head_dim, hidden] (our attention 'wo' layout,
    flattened heads leading). Returns (head_keep [num_heads] bool,
    mask broadcastable onto w_o).
    """
    w_o = np.asarray(w_o)
    hd = w_o.shape[0] // num_heads
    scores = np.abs(w_o.reshape(num_heads, hd, -1)).sum(axis=(1, 2))
    k = max(1, int(np.ceil(dense_ratio * num_heads)))
    keep_ids = np.argsort(scores)[-k:]
    head_keep = np.zeros(num_heads, dtype=bool)
    head_keep[keep_ids] = True
    mask = np.repeat(head_keep, hd)[:, None]
    return head_keep, np.broadcast_to(mask, w_o.shape).copy()
