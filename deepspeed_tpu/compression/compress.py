"""Compression orchestration: config → masks/quantizers → schedule.

Reference: ``deepspeed/compression/compress.py:100`` (``init_compression``
walks the model and swaps layers per config group), ``scheduler.py``
(``CompressionScheduler`` enables each technique at its
``schedule_offset``), and ``redundancy_clean`` (bake compression in).

TPU-native: models are functional and parameters are pytrees, so
"layer swap" becomes *param-tree transforms*: each config group matches
parameter paths by regex and contributes a pruning mask and/or a QAT
fake-quant spec. Training applies masks as projected gradient descent
(params re-masked after each step — numerically identical to the
reference's mask-in-forward once converged, and it keeps the compiled
train step untouched); ``redundancy_clean`` applies masks + quantization
permanently to produce the final compressed params.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.compression.pruning import (channel_pruning_mask,
                                               head_pruning_mask,
                                               row_pruning_mask,
                                               sparse_pruning_mask)
from deepspeed_tpu.compression.quantization import fake_quantize
from deepspeed_tpu.utils.logging import log_dist, logger

SEP = "."


def _flatten(tree, prefix="") -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{SEP}{k}" if prefix else str(k)))
    else:
        out[prefix] = tree
    return out


def _set_path(tree, path: str, value):
    keys = path.split(SEP)
    node = tree
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def _copy_tree(tree):
    if isinstance(tree, dict):
        return {k: _copy_tree(v) for k, v in tree.items()}
    return tree


@dataclasses.dataclass
class _QuantSpec:
    bits: int            # target bits
    symmetric: bool
    schedule_offset: int
    start_bits: int = 0  # 0 = no staging (jump straight to target)
    period: int = 0      # steps between bit halvings (reference
    #                      quantization_period staged annealing)

    def active_bits(self, step: int) -> Optional[int]:
        """Bit width in effect at ``step`` (None = not yet quantizing).
        Staged schedule (reference compression/basic_layer.py QuantAct /
        scheduler): start_bits at schedule_offset, halving every
        ``period`` steps until target ``bits``."""
        if step < self.schedule_offset:
            return None
        if not self.start_bits or not self.period:
            return self.bits
        halvings = (step - self.schedule_offset) // self.period
        return max(self.bits, self.start_bits >> halvings)


@dataclasses.dataclass
class _MaskSpec:
    mask: np.ndarray
    schedule_offset: int


@dataclasses.dataclass
class CompressionState:
    masks: Dict[str, _MaskSpec] = dataclasses.field(default_factory=dict)
    quant: Dict[str, _QuantSpec] = dataclasses.field(default_factory=dict)
    layer_reduction: Optional[List[int]] = None


_TECHNIQUES = ("weight_quantization", "sparse_pruning", "row_pruning",
               "channel_pruning", "head_pruning")


def _iter_groups(block: Dict[str, Any]):
    shared = block.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return
    offset = int(shared.get("schedule_offset", 0))
    for gname, group in (block.get("different_groups") or {}).items():
        params = group.get("params", {})
        modules = group.get("modules", ["*"])
        yield gname, offset, params, modules, shared


def _match(path: str, patterns: List[str]) -> bool:
    for p in patterns:
        if p == "*" or re.search(p.replace("*", ".*"), path):
            return True
    return False


def init_compression(params, compression_config: Dict[str, Any],
                     num_heads: Optional[int] = None) -> CompressionState:
    """Build masks/quant specs from the ``compression_training`` block
    (reference init_compression compress.py:100).

    ``params``: the engine's (or model's) parameter pytree.
    ``num_heads``: needed by head_pruning (reference reads it from the
    client config the same way).
    """
    cfg = compression_config.get("compression_training",
                                 compression_config) or {}
    flat = _flatten(params)
    state = CompressionState()

    for gname, offset, p, modules, shared in _iter_groups(
            cfg.get("weight_quantization", {})):
        bits = int(p.get("target_bits", p.get("start_bits", 8)))
        start = int(p.get("start_bits", 0))
        period = int(p.get("quantization_period", 0))
        sym = str(p.get("quantization_type", "symmetric")) == "symmetric"
        for path in flat:
            if _match(path, modules):
                state.quant[path] = _QuantSpec(
                    bits, sym, offset, start_bits=start, period=period)

    prune_builders: Dict[str, Callable] = {
        "sparse_pruning": lambda w, p: sparse_pruning_mask(
            w, float(p.get("dense_ratio", 0.5)),
            method=str(p.get("method", "l1"))),
        "row_pruning": lambda w, p: row_pruning_mask(
            w, float(p.get("dense_ratio", 0.5))),
        "channel_pruning": lambda w, p: channel_pruning_mask(
            w, float(p.get("dense_ratio", 0.5))),
    }
    for tech, builder in prune_builders.items():
        for gname, offset, p, modules, shared in _iter_groups(
                cfg.get(tech, {})):
            for path, w in flat.items():
                if not _match(path, modules):
                    continue
                arr = np.asarray(w)
                if arr.ndim < 2:
                    continue  # structured pruning needs matrices
                # stacked-layer params [L, in, out]: mask per layer
                if arr.ndim == 3:
                    mask = np.stack([builder(arr[i], p)
                                     for i in range(arr.shape[0])])
                else:
                    mask = builder(arr, p)
                mask = np.broadcast_to(mask, arr.shape).copy()
                prev = state.masks.get(path)
                if prev is not None:
                    mask &= prev.mask
                state.masks[path] = _MaskSpec(mask, offset)

    for gname, offset, p, modules, shared in _iter_groups(
            cfg.get("head_pruning", {})):
        nh = int(shared.get("num_heads", num_heads or 0))
        if nh <= 0:
            raise ValueError("head_pruning needs num_heads (shared_parameters"
                             ".num_heads or init_compression(num_heads=..))")
        ratio = float(p.get("dense_ratio", 0.5))
        for path, w in flat.items():
            if not _match(path, modules):
                continue
            arr = np.asarray(w)
            if arr.ndim == 3:
                masks = []
                for i in range(arr.shape[0]):
                    _, m = head_pruning_mask(arr[i], nh, ratio)
                    masks.append(m)
                mask = np.stack(masks)
            elif arr.ndim == 2:
                _, mask = head_pruning_mask(arr, nh, ratio)
            else:
                continue
            prev = state.masks.get(path)
            if prev is not None:
                mask = mask & prev.mask
            state.masks[path] = _MaskSpec(np.asarray(mask), offset)

    lr = cfg.get("layer_reduction", {})
    if lr.get("enabled", False):
        keep = lr.get("keep_layers")
        if keep is None:
            n = int(lr["keep_number_layer"])
            total = int(lr.get("total_layers", n))
            # evenly spaced teacher layers (reference teacher_layer default)
            keep = sorted(set(np.linspace(0, total - 1, n).astype(int)
                              .tolist()))
        state.layer_reduction = [int(i) for i in keep]

    log_dist(
        f"compression: {len(state.masks)} masked tensors, "
        f"{len(state.quant)} quantized tensors, layer_reduction="
        f"{state.layer_reduction}", ranks=[0])
    return state


def apply_masks(params, state: CompressionState, step: int = 10**12):
    """Project params onto the masks active at ``step`` (projected-SGD
    re-masking; called after each optimizer step)."""
    import jax

    if not any(step >= m.schedule_offset for m in state.masks.values()):
        return params  # nothing active: skip the tree copy
    flat = _flatten(params)
    new = _copy_tree(params)
    for path, spec in state.masks.items():
        if step < spec.schedule_offset:
            continue
        w = flat[path]
        masked = jax.numpy.where(spec.mask, w, 0).astype(w.dtype)
        if hasattr(w, "sharding"):
            masked = jax.device_put(masked, w.sharding)
        _set_path(new, path, masked)
    return new


def apply_quantization(params, state: CompressionState,
                       step: int = 10**12):
    """QAT-by-projection at the bit width the staged schedule dictates
    for ``step`` (reference: the compressed forward of basic_layer.py;
    here compression is a projection after the optimizer step, so the
    next forward computes with quantized weights while fp32 masters keep
    full precision)."""
    import jax

    active = {p: q.active_bits(step) for p, q in state.quant.items()}
    if not any(b is not None and b < 16 for b in active.values()):
        return params  # nothing active at this step: skip the tree copy
    flat = _flatten(params)
    new = _copy_tree(params)
    for path, q in state.quant.items():
        bits = active[path]
        if bits is None or bits >= 16:
            continue
        w = flat[path]
        if getattr(w, "ndim", 0) < 2:
            continue
        fq = fake_quantize(jax.numpy.asarray(w), bits=bits,
                           symmetric=q.symmetric).astype(w.dtype)
        if hasattr(w, "sharding"):
            fq = jax.device_put(fq, w.sharding)
        _set_path(new, path, fq)
    return new


def redundancy_clean(params, state: CompressionState):
    """Bake compression in (reference redundancy_clean): apply all masks,
    fake-quantize QAT tensors, and drop reduced layers permanently."""
    import jax

    new = apply_masks(params, state)
    flat = _flatten(new)
    for path, q in state.quant.items():
        w = flat[path]
        if getattr(w, "ndim", 0) < 2:
            continue
        _set_path(new, path, fake_quantize(
            jax.numpy.asarray(w), bits=q.bits,
            symmetric=q.symmetric).astype(w.dtype))
    if state.layer_reduction is not None:
        keep = np.asarray(state.layer_reduction)

        def cut(x):
            return x[keep] if getattr(x, "ndim", 0) >= 1 else x

        if isinstance(new, dict) and "layers" in new:
            new["layers"] = jax.tree.map(cut, new["layers"])
        else:
            logger.warning("layer_reduction: no 'layers' subtree found")
    return new


class CompressionScheduler:
    """Applies compression during training (reference
    compression/scheduler.py): call ``step(engine)`` after each optimizer
    step (or attach via ``engine.register_post_step_hook``)."""

    def __init__(self, state: CompressionState):
        self.state = state

    def step(self, engine):
        if not self.state.masks and not self.state.quant:
            return
        params = apply_masks(engine.params, self.state,
                             step=engine.global_steps)
        params = apply_quantization(params, self.state,
                                    step=engine.global_steps)
        engine.params = params

    def attach(self, engine):
        engine.register_post_step_hook(lambda e: self.step(e))
        return self
