"""Quantization-aware-training primitives.

Reference: ``deepspeed/compression/basic_layer.py`` (``Embedding_Compress``,
``LinearLayer_Compress`` quantization paths) + ``utils.py`` — symmetric /
asymmetric fake quantization with a straight-through estimator, applied to
weights (QAT) and activations during the forward pass.

TPU-native: fake-quant is a pure function fused by XLA into the
surrounding matmul; the STE is ``x + stop_gradient(q(x) - x)`` — identical
gradients to the reference's autograd-function STE, no custom kernels
needed until real int8 execution (ops/pallas/quantization.py covers that).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _quantize_symmetric(x, bits: int, axis: Optional[int]):
    qmax = 2.0 ** (bits - 1) - 1.0
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax)
    return q * scale


def _quantize_asymmetric(x, bits: int, axis: Optional[int]):
    qmax = 2.0 ** bits - 1.0
    lo = jnp.min(x, axis=axis, keepdims=axis is not None)
    hi = jnp.max(x, axis=axis, keepdims=axis is not None)
    scale = jnp.where(hi > lo, (hi - lo) / qmax, 1.0)
    q = jnp.clip(jnp.round((x - lo) / scale), 0, qmax)
    return q * scale + lo


def fake_quantize(x, bits: int = 8, symmetric: bool = True,
                  axis: Optional[int] = None, enabled=True):
    """Quantize-dequantize with straight-through gradient.

    ``axis``: per-channel scales along that axis (None = per-tensor).
    ``enabled`` may be a traced boolean (schedule offset inside jit).
    """
    q = (_quantize_symmetric(x, bits, axis) if symmetric
         else _quantize_asymmetric(x, bits, axis))
    out = x + jax.lax.stop_gradient(q - x)  # STE
    return jnp.where(enabled, out, x) if not isinstance(enabled, bool) \
        else (out if enabled else x)


def quantize_activation(x, bits: int = 8, symmetric: bool = False,
                        range_calibration: str = "dynamic"):
    """Activation fake-quant (reference activation_quantization block;
    dynamic = per-batch min/max, the reference's default)."""
    del range_calibration  # static calibration would carry running stats
    return fake_quantize(x, bits=bits, symmetric=symmetric, axis=None)
