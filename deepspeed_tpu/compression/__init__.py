"""Compression subsystem (reference: deepspeed/compression/)."""

from deepspeed_tpu.compression.compress import (  # noqa: F401
    CompressionScheduler,
    init_compression,
    redundancy_clean,
)
from deepspeed_tpu.compression.distillation import (  # noqa: F401
    DistillationConfig,
    StudentTeacherModel,
    init_distillation,
    kd_loss,
    student_from_teacher,
)
from deepspeed_tpu.compression.quantization import (  # noqa: F401
    fake_quantize,
    quantize_activation,
)
from deepspeed_tpu.compression.pruning import (  # noqa: F401
    channel_pruning_mask,
    head_pruning_mask,
    row_pruning_mask,
    sparse_pruning_mask,
)
