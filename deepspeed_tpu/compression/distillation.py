"""Knowledge distillation for structural compression.

Reference: ``deepspeed/compression/compress.py:100`` — ``init_compression
(model, config, teacher_model=...)`` pairs layer-reduction students with
a teacher, and the compression examples train the student on a soft
KL term against the teacher's logits plus the hard-label CE (the
DistilBERT/TinyBERT recipe the reference's layer_reduction tutorial
follows).

TPU-native shape: the teacher forward runs inside the same jitted loss
under ``stop_gradient`` (no separate serving pass, XLA overlaps both
networks), and the student is born from the teacher by slicing the
stacked layer axis — ``layer_reduction.keep_layers`` indexes [L, ...]
arrays directly instead of rewriting a module graph.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass(frozen=True)
class DistillationConfig:
    """Reference knobs (compression examples' kd settings)."""

    temperature: float = 2.0
    alpha_kd: float = 0.5      # soft-target KL weight
    alpha_ce: float = 0.5      # hard-label CE weight
    alpha_hidden: float = 0.0  # optional last-hidden MSE weight


def kd_loss(student_logits: jax.Array, teacher_logits: jax.Array,
            temperature: float) -> jax.Array:
    """Temperature-scaled KL(teacher || student), mean over tokens,
    scaled by T^2 (gradient magnitude invariant in T — Hinton et al.)."""
    t = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / temperature)
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / temperature)
    return (jnp.exp(t) * (t - s)).sum(-1).mean() * temperature ** 2


def student_from_teacher(teacher_model, teacher_params,
                         keep_layers: Sequence[int]):
    """Build a layer-reduced student initialized from the teacher
    (reference layer_reduction: student layer i <- teacher layer
    keep_layers[i]; embeddings/final norm copied).

    Returns (student_model, student_params).
    """
    import dataclasses as _dc

    keep = [int(i) for i in keep_layers]
    cfg = teacher_model.config
    if any(i < 0 or i >= cfg.num_layers for i in keep):
        raise ValueError(f"keep_layers {keep} out of range for "
                         f"{cfg.num_layers}-layer teacher")
    student_cfg = _dc.replace(cfg, num_layers=len(keep))
    student_model = type(teacher_model)(student_cfg)

    idx = jnp.asarray(keep)
    sp = {k: v for k, v in teacher_params.items()}
    sp["layers"] = jax.tree.map(lambda a: a[idx], teacher_params["layers"])
    log_dist(f"distillation: student keeps teacher layers {keep}",
             ranks=[0])
    return student_model, sp


class StudentTeacherModel:
    """Model-protocol wrapper: trains the student against hard labels +
    the teacher's soft targets. The teacher's params live on the object
    (never part of the optimized tree) and its forward runs under
    stop_gradient inside the same compiled step."""

    def __init__(self, student, teacher, teacher_params,
                 config: Optional[DistillationConfig] = None):
        self.student = student
        self.teacher = teacher
        self.teacher_params = teacher_params
        self.kd = config or DistillationConfig()
        self.config = student.config  # engine reads model.config

    def init(self, rng):
        return self.student.init(rng)

    def logical_axes(self):
        return self.student.logical_axes()

    def apply(self, params, tokens, positions=None):
        return self.student.apply(params, tokens, positions)

    def loss(self, params, batch) -> Any:
        kd = self.kd
        tokens = batch["input_ids"]
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
        s_logits = self.student.apply(params, inputs)
        t_logits = lax.stop_gradient(
            self.teacher.apply(self.teacher_params, inputs))

        logz = jax.nn.logsumexp(s_logits, axis=-1)
        gold = jnp.take_along_axis(s_logits, labels[..., None],
                                   axis=-1)[..., 0]
        ce = (logz - gold).mean()
        soft = kd_loss(s_logits, t_logits, kd.temperature)
        total = kd.alpha_ce * ce + kd.alpha_kd * soft
        aux: Dict[str, jax.Array] = {
            "lm_loss": ce, "kd_loss": soft,
            "ntokens": jnp.asarray(labels.size, jnp.float32)}
        if kd.alpha_hidden:
            # last-hidden MSE needs matching widths (same hidden_size)
            from deepspeed_tpu.models import transformer as tfm

            sh = tfm.apply_hidden(self.student.config, params, inputs)
            th = lax.stop_gradient(tfm.apply_hidden(
                self.teacher.config, self.teacher_params, inputs))
            hid = jnp.mean((sh.astype(jnp.float32)
                            - th.astype(jnp.float32)) ** 2)
            total = total + kd.alpha_hidden * hid
            aux["hidden_loss"] = hid
        aux["loss"] = total
        return total, aux

    def flops_per_token(self):
        # student + teacher forward both run per step
        return (self.student.flops_per_token()
                + self.teacher.flops_per_token() / 3)

    def num_params(self):
        return self.student.num_params()


def init_distillation(teacher_model, teacher_params,
                      compression_config: Dict[str, Any],
                      kd_config: Optional[DistillationConfig] = None):
    """Reference-parity entry: layer_reduction block + teacher →
    (StudentTeacherModel, student_params) ready for dstpu.initialize
    (the reference's init_compression(model, cfg, teacher_model=...)).
    """
    cfg = compression_config.get("compression_training",
                                 compression_config) or {}
    lr = cfg.get("layer_reduction", {})
    if not lr.get("enabled", False):
        raise ValueError("init_distillation needs an enabled "
                         "layer_reduction block (keep_layers or "
                         "keep_number_layer)")
    keep = lr.get("keep_layers")
    if keep is None:
        import numpy as np

        n = int(lr["keep_number_layer"])
        total = int(lr.get("total_layers",
                           teacher_model.config.num_layers))
        keep = sorted(set(np.linspace(0, total - 1, n).astype(int)
                          .tolist()))
    student, sparams = student_from_teacher(teacher_model, teacher_params,
                                            keep)
    wrapper = StudentTeacherModel(student, teacher_model, teacher_params,
                                  kd_config)
    return wrapper, sparams
