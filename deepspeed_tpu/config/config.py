"""The framework config tree.

TPU-native analog of the reference's ``DeepSpeedConfig``
(reference: deepspeed/runtime/config.py:676) and its nested sub-configs.
A single JSON file / dict configures the whole engine. Key parity points:

  - batch-size triple solver: ``train_batch_size`` =
    ``train_micro_batch_size_per_chip`` × ``gradient_accumulation_steps`` ×
    data-parallel world size (reference ``_configure_train_batch_size``
    runtime/config.py:971);
  - ``"auto"`` values resolved by the engine or autotuner;
  - deprecated-key aliasing (e.g. ``train_micro_batch_size_per_gpu``).

TPU-first deltas: fp16 loss-scaling exists for parity but bf16 is the
default compute dtype; ZeRO stages map to sharding declarations instead of
runtime partitioning (see runtime/zero.py); parallel topology (dp/fsdp/
tp/sp/ep/pp) is part of the config because on TPU it compiles into the
program rather than being wired at runtime.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from deepspeed_tpu.config.config_utils import (
    AUTO,
    ConfigModel,
    is_auto,
    register_config_model,
)
from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# sub-configs
# ---------------------------------------------------------------------------


@register_config_model
@dataclass
class OptimizerConfig(ConfigModel):
    """Reference: ``optimizer`` block (runtime/config.py:90-127)."""

    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)


@register_config_model
@dataclass
class SchedulerConfig(ConfigModel):
    """Reference: ``scheduler`` block → runtime/lr_schedules.py."""

    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


@register_config_model
@dataclass
class BF16Config(ConfigModel):
    """Reference: ``bf16`` block (runtime/config.py:157). Default on TPU."""

    enabled: bool = True


@register_config_model
@dataclass
class FP16Config(ConfigModel):
    """Reference: ``fp16`` block with dynamic loss scaling
    (runtime/fp16/loss_scaler.py:187). Rarely wanted on TPU (bf16-native),
    kept for API parity and for accelerators without bf16."""

    enabled: bool = False
    loss_scale: float = 0.0  # 0 = dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0


@register_config_model
@dataclass
class OffloadParamConfig(ConfigModel):
    """Reference: DeepSpeedZeroOffloadParamConfig (runtime/zero/offload_config.py:21)."""

    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    pin_memory: bool = False


@register_config_model
@dataclass
class OffloadOptimizerConfig(ConfigModel):
    """Reference: DeepSpeedZeroOffloadOptimizerConfig (runtime/zero/offload_config.py:52)."""

    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    # device->host gradient transfer dtype: "fp32" (exact) or "bf16"
    # (halves transfer volume; native bf16-grad optimizer kernels)
    grad_transfer_dtype: str = "fp32"
    ratio: float = 1.0

    def validate(self) -> None:
        if self.device not in ("none", "cpu", "nvme"):
            raise ValueError(
                f"offload_optimizer.device must be none|cpu|nvme, "
                f"got {self.device!r}")
        if self.device == "nvme" and not self.nvme_path:
            raise ValueError(
                "offload_optimizer.device='nvme' requires nvme_path "
                "(otherwise state would silently stay in host RAM)")
        if self.grad_transfer_dtype not in ("fp32", "bf16"):
            raise ValueError(
                f"offload_optimizer.grad_transfer_dtype must be fp32|bf16, "
                f"got {self.grad_transfer_dtype!r}")


@register_config_model
@dataclass
class ZenFlowBlockConfig(ConfigModel):
    """Reference: ZenFlowConfig (runtime/zenflow/zenflow_config.py) —
    importance-split offloaded optimization: top-k coordinates update on
    device every step, the rest in an overlapped host pass."""

    topk_ratio: float = 0.01
    update_interval: int = 4
    select_interval: int = 16
    overlap_step: bool = True
    # host-optimizer worker parallelism (reference SuperOffload runs a
    # CPU optimizer worker process, superoffload_utils.py:165; threads
    # suffice here — the native optimizer releases the GIL)
    workers: int = 1

    def validate(self) -> None:
        if self.workers < 1:
            raise ValueError(f"zenflow.workers must be >= 1, got "
                             f"{self.workers}")
        if not 0.0 < self.topk_ratio <= 1.0:
            raise ValueError(
                f"zenflow.topk_ratio must be in (0, 1], got "
                f"{self.topk_ratio}")
        if self.update_interval < 1 or self.select_interval < 1:
            raise ValueError("zenflow intervals must be >= 1")


@register_config_model
@dataclass
class ZeroConfig(ConfigModel):
    """Reference: DeepSpeedZeroConfig (runtime/zero/config.py:90).

    On TPU the stages are declarative sharding choices (runtime/zero.py):
      0: replicate params/grads/opt-state over dp;
      1: shard optimizer state over dp;
      2: + reduce-scatter grads (grads land sharded);
      3: + shard parameters over dp (XLA all-gathers on use).
    """

    stage: int = 0
    # bucket knobs kept for parity; on TPU XLA handles bucketing, but they
    # bound host-side flattening in the offload path.
    reduce_bucket_size: int = 500_000_000
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    round_robin_gradients: bool = False
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    # ZenFlow (stall-free offload): requires offload_optimizer.device=cpu
    zenflow: Optional[ZenFlowBlockConfig] = None
    sub_group_size: int = 1_000_000_000
    # ZeRO++ (reference docs/_tutorials/zeropp.md): hierarchical partitioning
    # and quantized collectives.
    zero_hpz_partition_size: int = 1  # 1 = off; >1 = shard within ICI slice
    zero_quantized_weights: bool = False  # qwZ: int8 all-gather of params
    zero_quantized_gradients: bool = False  # qgZ: quantized grad reduce
    # qar: EQuARX-style quantized all-reduce of gradients (quantize →
    # int8 reduce-scatter with fp32 accumulation → int8 all-gather →
    # dequant; ops/pallas/quantization.py quantized_all_reduce). Replaces
    # the stage-1/2 gradient reduce; mutually exclusive with qgZ (both
    # own the same wire).
    zero_quantized_allreduce: bool = False
    # MiCS (runtime/zero/mics.py): sub-world shard groups.
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    log_trace_cache_warnings: bool = False
    model_persistence_threshold: int = 0  # params below stay replicated
    param_persistence_threshold: int = 0

    def validate(self) -> None:
        if self.stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        if self.zero_hpz_partition_size < 1:
            raise ValueError("zero_hpz_partition_size must be >= 1")
        if self.zero_quantized_allreduce and self.zero_quantized_gradients:
            raise ValueError(
                "zero_quantized_allreduce (qar) and "
                "zero_quantized_gradients (qgZ) both own the gradient "
                "wire — enable at most one")


@register_config_model
@dataclass
class TensorParallelConfig(ConfigModel):
    """Reference: ``tensor_parallel`` block (runtime/tensor_parallel/config.py,
    autotp_size engine.py:624)."""

    autotp_size: int = 1
    tp_grain_size: int = 1

    @property
    def size(self) -> int:
        return max(1, self.autotp_size)


@register_config_model
@dataclass
class SequenceParallelConfig(ConfigModel):
    """Ulysses-style sequence parallelism (reference: deepspeed/sequence/layer.py:351,
    runtime/sequence_parallel/ulysses_sp.py). ``mode='ring'`` adds the
    ring-attention option the reference lacks (SURVEY §5: head-count < chips)."""

    size: int = 1
    mode: str = "ulysses"  # ulysses | ring
    tiled_mlp: bool = False
    tiled_logits: bool = False
    tile_size: int = 0  # 0 = auto
    # unified long-context planner (parallel/auto_sp.py
    # plan_sequence_parallel): when the mesh has an sp axis the engine
    # composes strategy/chunking/host-KV-spill onto the model config at
    # init — conservatively, never overriding explicit model settings.
    # False opts out.
    auto_plan: bool = True
    # per-chip activation HBM budget (GiB) the planner sizes chunking
    # and host-KV spill against; None plans without spill pressure.
    hbm_budget_gb: Optional[float] = None


@register_config_model
@dataclass
class MoEConfig(ConfigModel):
    """Expert parallelism defaults used by our model zoo (reference MoE layer
    args: deepspeed/moe/layer.py:17)."""

    enabled: bool = False
    ep_size: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    top_k: int = 2
    drop_tokens: bool = True
    use_rts: bool = False
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.0
    # expert execution engine: "auto" | "grouped" (dropless grouped-GEMM,
    # reference GroupedExperts moe/ep_experts.py:136) | "einsum" (capacity)
    impl: str = "auto"


@register_config_model
@dataclass
class PipelineConfig(ConfigModel):
    """Reference: PipelineModule/PipelineEngine (runtime/pipe/). On TPU the
    1F1B interpreter becomes a collective-permute microbatch pipeline
    (parallel/pipeline.py)."""

    stages: int = 1
    partition_method: str = "uniform"  # uniform | parameters
    activation_checkpoint_interval: int = 0
    # microbatches per pipeline pass (default 2*stages; more amortizes
    # the bubble) and the 1F1B-depth window: microbatches are run in
    # waves of `window` (default 2*stages) with per-wave remat, so live
    # stage-boundary activations stay O(window) no matter how large
    # `microbatches` grows (the role of TrainSchedule's bounded
    # in-flight depth, reference pipe/schedule.py:189)
    microbatches: int = 0  # 0 = auto
    window: int = 0  # 0 = auto (2*stages)
    # "waves": waves of `window` microbatches with per-wave remat —
    #   activation memory O(window + stages) however large `microbatches`
    #   grows, at the cost of one extra forward per wave (the reference
    #   1F1B TrainSchedule's bounded depth, pipe/schedule.py:189).
    # "save_boundaries": one un-rematted pass — the scan saves exactly
    #   the per-step stage-boundary activations (O(microbatches+stages)
    #   of them), no wave recompute: pipeline flops match the no-pp
    #   model within the bubble. Scale batch via gradient accumulation
    #   instead of microbatches in this mode.
    schedule: str = "waves"


@register_config_model
@dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """Reference: runtime/activation_checkpointing/checkpointing.py:1029.
    On TPU this selects the jax.checkpoint (remat) policy applied to the
    scanned layer stack."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native knob: which remat policy to use for the layer scan.
    # nothing_saveable | dots_saveable | dots_with_no_batch_dims_saveable
    # | offload_dots_host | none
    policy: str = "nothing_saveable"


@register_config_model
@dataclass
class CommsLoggerConfig(ConfigModel):
    """Reference: comms_logger block (utils/comms_logging.py:67)."""

    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = field(default_factory=list)


@register_config_model
@dataclass
class MonitorBackendConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"
    team: Optional[str] = None
    project: Optional[str] = None
    group: Optional[str] = None


@register_config_model
@dataclass
class MonitorConfig(ConfigModel):
    """Reference: deepspeed/monitor/config.py; MonitorMaster (monitor/monitor.py:30)."""

    tensorboard: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    csv_monitor: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    wandb: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    comet: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)
    jsonl: MonitorBackendConfig = field(default_factory=MonitorBackendConfig)


@register_config_model
@dataclass
class FlopsProfilerConfig(ConfigModel):
    """Reference: deepspeed/profiling/config.py. On TPU we read XLA's
    ``Compiled.cost_analysis()`` instead of monkey-patching ops."""

    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@register_config_model
@dataclass
class WatchdogConfig(ConfigModel):
    """Stall watchdog (observability/watchdog.py): a step exceeding
    ``max(factor * rolling_mean_step_time, min_seconds)`` triggers a
    report with Python stacks and device memory stats. Env overrides:
    DSTPU_WATCHDOG=0, DSTPU_WATCHDOG_FACTOR, DSTPU_WATCHDOG_MIN_S."""

    enabled: bool = True
    factor: float = 8.0
    min_seconds: float = 30.0


@register_config_model
@dataclass
class RequestTraceConfig(ConfigModel):
    """Per-request serving traces (observability/request_trace.py;
    docs/serving.md "Request tracing & SLO attribution").

    Every request the serving engine touches records a typed span
    timeline; at FINISH a tail-based sampler keeps every SLO violator
    (TTFT > ``slo_deadline_ms``) plus a ``sample_rate`` random slice of
    the healthy rest in a ``ring_size``-bounded ring. ``slo_deadline_ms``
    null means no deadline: only the random slice is kept. Env
    overrides: DSTPU_REQUEST_TRACE=0 (disable),
    DSTPU_REQ_TRACE_SAMPLE, DSTPU_REQ_TRACE_RING,
    DSTPU_REQ_TRACE_SLO_MS."""

    enabled: bool = True
    sample_rate: float = 0.05
    ring_size: int = 4096
    slo_deadline_ms: Optional[float] = None

    def validate(self) -> None:
        if not 0.0 <= self.sample_rate <= 1.0:
            raise ValueError(
                f"observability.request_trace.sample_rate must be in "
                f"[0, 1], got {self.sample_rate}")
        if self.ring_size < 1:
            raise ValueError(
                f"observability.request_trace.ring_size must be >= 1, "
                f"got {self.ring_size}")
        if self.slo_deadline_ms is not None and self.slo_deadline_ms <= 0:
            raise ValueError(
                f"observability.request_trace.slo_deadline_ms must be "
                f"> 0 (or null), got {self.slo_deadline_ms}")


@register_config_model
@dataclass
class ClockSyncConfig(ConfigModel):
    """Per-channel fleet clock sync (observability/clocksync.py;
    docs/observability.md "Fleet tracing & clock sync").

    The supervisor attaches one NTP-style offset estimator to every
    worker channel: ``rounds`` ping/pong exchanges at spawn, a re-ping
    whenever the newest sample is older than ``resync_seconds``. The
    estimate is the median offset of the ``k`` lowest-RTT samples in a
    ``window``-bounded history; ``min_samples`` round trips gate
    ``synced`` (before that — and always with ``enabled=false`` — every
    consumer passes raw timestamps through, bit-exact with the
    pre-clocksync localhost behavior)."""

    enabled: bool = True
    rounds: int = 8
    resync_seconds: float = 5.0
    k: int = 5
    window: int = 32
    min_samples: int = 3

    def validate(self) -> None:
        if self.rounds < 1:
            raise ValueError(
                f"observability.clock_sync.rounds must be >= 1, got "
                f"{self.rounds}")
        if self.resync_seconds <= 0:
            raise ValueError(
                f"observability.clock_sync.resync_seconds must be > 0, "
                f"got {self.resync_seconds}")
        if not 1 <= self.k <= self.window:
            raise ValueError(
                f"observability.clock_sync needs 1 <= k <= window, got "
                f"k={self.k} window={self.window}")
        if self.min_samples < 1:
            raise ValueError(
                f"observability.clock_sync.min_samples must be >= 1, "
                f"got {self.min_samples}")


@register_config_model
@dataclass
class BurnRateConfig(ConfigModel):
    """SLO burn-rate alerting (observability/burn_rate.py;
    docs/observability.md "Burn-rate alerts").

    The SRE multi-window shape: with ``slo_target`` 0.999 the error
    budget is 0.1%, and the alert fires when BOTH the fast window
    (``fast_window_seconds`` at >= ``fast_burn`` x budget-neutral
    spend) and the slow window agree — fast catches the cliff, slow
    suppresses self-healing blips. ``deadline_ms`` is the per-request
    SLO deadline on ``objective`` (``ttft`` or ``e2e``); null leaves
    alerting off even when enabled. A firing alert clears after
    ``clear_checks`` consecutive clean evaluations; ``min_events``
    observations must sit in the fast window before the first fire."""

    enabled: bool = False
    deadline_ms: Optional[float] = None
    slo_target: float = 0.999
    fast_window_seconds: float = 60.0
    fast_burn: float = 14.4
    slow_window_seconds: float = 600.0
    slow_burn: float = 6.0
    clear_checks: int = 3
    min_events: int = 10
    objective: str = "ttft"

    def validate(self) -> None:
        if not 0.0 < self.slo_target < 1.0:
            raise ValueError(
                f"burn_rate.slo_target must be in (0, 1), got "
                f"{self.slo_target}")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"burn_rate.deadline_ms must be > 0 (or null), got "
                f"{self.deadline_ms}")
        if not 0 < self.fast_window_seconds <= self.slow_window_seconds:
            raise ValueError(
                f"burn_rate needs 0 < fast_window_seconds <= "
                f"slow_window_seconds, got ({self.fast_window_seconds}, "
                f"{self.slow_window_seconds})")
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError(
                f"burn_rate burn thresholds must be > 0, got "
                f"({self.fast_burn}, {self.slow_burn})")
        if self.clear_checks < 1 or self.min_events < 1:
            raise ValueError(
                f"burn_rate.clear_checks and min_events must be >= 1, "
                f"got ({self.clear_checks}, {self.min_events})")
        if self.objective not in ("ttft", "e2e"):
            raise ValueError(
                f"burn_rate.objective must be ttft|e2e, got "
                f"{self.objective!r}")


@register_config_model
@dataclass
class PerformanceConfig(ConfigModel):
    """Pipelined training loop (docs/performance.md).

    ``pipeline_depth`` is the number of dispatched-but-unresolved
    ``train_batch`` steps the engine may keep in flight before blocking
    (dispatch-ahead): 0 = fully synchronous — the debugging default,
    where every per-step host read happens inside its own step. The
    ``DSTPU_DISPATCH_AHEAD`` env var overrides it. ``prefetch_depth``
    bounds the background input-prefetch buffer
    (runtime/prefetch.py PrefetchingIterator); 0 disables prefetch, and
    multi-process runs fall back to synchronous input assembly
    regardless.

    ``param_prefetch_depth`` sets the depth of the ZeRO-Infinity layer
    prefetch ring (runtime/param_stream.py streamed_layers_prefetch):
    K layers of host→device fetches ride in flight ahead of the compute
    when ``offload_param`` streams the layer stack. None (default)
    keeps the model's own default (2, or the DSTPU_PREFETCH_DEPTH env);
    1 reproduces plain double-buffering bit-for-bit. HBM cost is K
    fp32 layers.

    ``fp8_mlp`` routes the MLP-block matmuls through fp8 (e4m3 operands,
    fp32 accumulation, straight-through gradients — ops/fp_quantizer.py
    fp8_matmul_ste). Opt-in: off by default for exact parity; on v5p+
    the MXU runs fp8 at 2x the bf16 rate.

    ``overlap_depth`` arms the per-layer overlap engine
    (runtime/param_stream.py pin_stage): the K newest in-flight
    transfers — h2d layer fetches on the ZeRO-Infinity path, fsdp
    all-gathers on the stage-3 resident path, plus the backward grad
    streams — are barrier-pinned into the issuing layer's scheduling
    stage, so each transfer provably overlaps that layer's compute.
    0 disables (today's program, bit-for-bit); None keeps the model/env
    default (DSTPU_OVERLAP_DEPTH). Identity on values at any depth."""

    pipeline_depth: int = 0
    prefetch_depth: int = 2
    param_prefetch_depth: Optional[int] = None
    fp8_mlp: bool = False
    overlap_depth: Optional[int] = None

    def validate(self) -> None:
        if self.pipeline_depth < 0:
            raise ValueError(
                f"performance.pipeline_depth must be >= 0, got "
                f"{self.pipeline_depth}")
        if self.prefetch_depth < 0:
            raise ValueError(
                f"performance.prefetch_depth must be >= 0, got "
                f"{self.prefetch_depth}")
        if self.param_prefetch_depth is not None \
                and self.param_prefetch_depth < 1:
            raise ValueError(
                f"performance.param_prefetch_depth must be >= 1, got "
                f"{self.param_prefetch_depth}")
        if self.overlap_depth is not None and self.overlap_depth < 0:
            raise ValueError(
                f"performance.overlap_depth must be >= 0, got "
                f"{self.overlap_depth}")


@register_config_model
@dataclass
class JournalConfig(ConfigModel):
    """Fleet black-box journal (observability/journal.py): append-only
    CRC-framed capture of admissions, routing/preemption/failover
    decisions with their inputs, chaos injections, and per-request
    emitted-token checksum chains — enough to re-drive the run
    bit-identically with tools/replay.py. Off by default: the journal
    is a forensic artifact, not ambient telemetry. ``dir`` is where
    ``<run>.journal`` files land (gitignored, like ``dstpu_flight/``);
    ``max_mb`` caps one journal file — past it records are dropped
    (counted, plus one TRUNCATED marker) rather than failing the
    run."""

    enabled: bool = False
    dir: str = "dstpu_journal"
    max_mb: float = 64.0

    def validate(self) -> None:
        if self.max_mb <= 0:
            raise ValueError(
                f"observability.journal.max_mb must be > 0, got "
                f"{self.max_mb}")
        if not self.dir:
            raise ValueError("observability.journal.dir must be set")


@register_config_model
@dataclass
class ObservabilityConfig(ConfigModel):
    """Unified observability hub (observability/hub.py). Per-step
    StepTrace rows (wall time, loss, tokens/s, MFU, comm deltas,
    compile events) flow to the in-process hub always; ``jsonl_path`` /
    ``prometheus_path`` additionally stream them to disk
    (DSTPU_METRICS_JSONL / DSTPU_METRICS_PROM env override).
    ``xla_cost_analysis`` opts into the lazily-computed roofline from
    the compiled step's cost analysis (env: DSTPU_ROOFLINE=1) — it
    costs one extra lower+compile, so it is off by default.

    Fleet layer (observability/fleet.py): ``run_dir`` (env override
    DSTPU_RUN_DIR — the launcher sets it for multi-process runs) points
    every rank at one shared directory where it publishes heartbeat +
    step-summary shards every ``publish_every_steps`` steps; a rank
    whose heartbeat is older than ``stale_after_seconds`` is reported
    dead by the aggregator. No run dir → no shard I/O. The crash flight
    recorder keeps a ring of ``flight_events`` structured events
    (0 disables) dumped on crash/SIGTERM/watchdog fire.
    ``request_trace`` configures the per-request serving flight paths
    (tail-sampled span timelines + SLO attribution; see
    RequestTraceConfig). ``quant_stats`` opts into the ZeRO++
    quantization-error telemetry (observability/quant_stats.py):
    ``quant.*`` hub metrics — per-region SNR dB, max relative error,
    wire-vs-logical bytes — sampled at engine init when qwZ/qgZ run
    (env override DSTPU_QUANT_STATS=1); off by default because the
    init-time sample quantizes a capped slice of the real params."""

    enabled: bool = True
    quant_stats: bool = False
    jsonl_path: Optional[str] = None
    prometheus_path: Optional[str] = None
    prometheus_every_steps: int = 10
    step_history: int = 512
    xla_cost_analysis: bool = False
    run_dir: Optional[str] = None
    publish_every_steps: int = 1
    stale_after_seconds: float = 30.0
    flight_events: int = 4096
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    request_trace: RequestTraceConfig = field(
        default_factory=RequestTraceConfig)
    clock_sync: ClockSyncConfig = field(default_factory=ClockSyncConfig)
    journal: JournalConfig = field(default_factory=JournalConfig)

    def validate(self) -> None:
        self.request_trace.validate()
        self.clock_sync.validate()
        self.journal.validate()
        if self.flight_events < 0:
            raise ValueError(
                f"observability.flight_events must be >= 0, got "
                f"{self.flight_events}")
        if self.publish_every_steps < 1:
            raise ValueError(
                f"observability.publish_every_steps must be >= 1, got "
                f"{self.publish_every_steps}")


@register_config_model
@dataclass
class SparseAttentionConfig(ConfigModel):
    """Reference: ``sparse_attention`` block (runtime/config.py:250-410):
    dense | fixed | variable | bigbird | bslongformer modes. Maps onto the
    Pallas block-sparse layouts (ops/pallas/blocksparse_attention.py)."""

    mode: str = "fixed"
    block: int = 128
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    local_window_blocks: Any = field(default_factory=lambda: [4])
    global_block_indices: Any = field(default_factory=lambda: [0])
    attention: str = "unidirectional"  # unidirectional (causal) | bidirectional

    def validate(self) -> None:
        if self.mode not in ("dense", "fixed", "variable", "bigbird",
                             "bslongformer"):
            raise ValueError(
                f"sparse_attention.mode must be dense|fixed|variable|"
                f"bigbird|bslongformer, got {self.mode!r}")
        if self.attention not in ("unidirectional", "bidirectional"):
            raise ValueError(
                f"sparse_attention.attention must be unidirectional|"
                f"bidirectional, got {self.attention!r}")


@register_config_model
@dataclass
class CheckpointConfig(ConfigModel):
    """Reference: checkpoint block (runtime/config.py:439-471)."""

    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    async_save: bool = False


@register_config_model
@dataclass
class ResilienceConfig(ConfigModel):
    """Fault tolerance (deepspeed_tpu/resilience/, docs/resilience.md).

    Preemption: ``preemption_guard`` installs a SIGTERM listener on the
    engine (first signal drains in-flight steps and forces an emergency
    checkpoint at the next GAS boundary within
    ``preemption_save_deadline_s``; a second signal escalates to
    immediate shutdown). The emergency save lands in
    ``emergency_save_dir``, defaulting to the directory of the last
    explicit ``save_checkpoint`` call.

    Checkpoint manifests: ``manifest`` writes an atomic per-tag manifest
    (topology, per-file checksums, data cursor) at publish and validates
    it at load, falling back to the previous good tag on corruption;
    ``manifest_checksums`` controls the (streaming crc32) content
    verification at load — size/presence checks always run.

    Collective health: ``init_timeout_s`` bounds ``init_distributed``;
    ``collective_timeout_s`` bounds the process-level control-plane ops
    (barrier, cross-process asserts, heartbeat I/O). ``None`` (default)
    leaves an op unbounded — zero behavior change until the block opts
    in. On deadline, ops retry up to ``max_retries`` times with
    exponential backoff (``backoff_base_s`` doubling to
    ``backoff_max_s``, ±``jitter``) and then raise ``CommTimeoutError``
    (worker exit code 75) carrying the flight-ring tail."""

    enabled: bool = True
    preemption_guard: bool = True
    preemption_save_deadline_s: float = 60.0
    emergency_save_dir: Optional[str] = None
    manifest: bool = True
    manifest_checksums: bool = True
    init_timeout_s: Optional[float] = None
    collective_timeout_s: Optional[float] = None
    max_retries: int = 2
    backoff_base_s: float = 1.0
    backoff_max_s: float = 30.0
    jitter: float = 0.25

    def validate(self) -> None:
        if self.preemption_save_deadline_s <= 0:
            raise ValueError(
                f"resilience.preemption_save_deadline_s must be > 0, got "
                f"{self.preemption_save_deadline_s}")
        if self.max_retries < 0:
            raise ValueError(
                f"resilience.max_retries must be >= 0, got "
                f"{self.max_retries}")
        for name in ("backoff_base_s", "backoff_max_s", "jitter"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"resilience.{name} must be >= 0, got "
                    f"{getattr(self, name)}")
        for name in ("init_timeout_s", "collective_timeout_s"):
            val = getattr(self, name)
            if val is not None and val <= 0:
                raise ValueError(
                    f"resilience.{name} must be > 0 (or null for "
                    f"unbounded), got {val}")


@register_config_model
@dataclass
class RouterConfig(ConfigModel):
    """Serving-fleet router knobs (serving/router.py, docs/serving.md
    "Multi-replica fleet").

    ``replicas`` sizes the in-process fleet the harness builds; ``mode``
    is ``"unified"`` (every replica prefills and decodes) or
    ``"disagg"`` (``prefill_replicas`` of the fleet prefill only, the
    rest decode only, with the KV-block handoff in between).
    ``affinity_blocks`` is the prefix-hash session-affinity window in KV
    blocks (0 disables affinity routing); ``stale_after_seconds`` is the
    heartbeat staleness that declares a replica dead and triggers
    failover. The ``autoscale_*``/``queue_*``/``slo_miss_high``/
    ``hysteresis_rounds`` knobs parameterize the desired-replica-count
    signal (serving/autoscale.py) — metrics only in-process; the
    cross-process supervisor (serving/supervisor.py) is the controller
    that acts on it.

    ``routing`` picks the placement policy behind the affinity check:
    ``least_loaded`` (live load report) or ``predictive`` (lowest
    predicted TTFT from the queue-depth x service-EWMA + prefill-rate
    model). ``transport`` selects how a process fleet connects its
    replicas — ``inproc`` (threads, no processes), ``socket``
    (localhost TCP, the primary), or ``file`` (spool-dir frames, the
    socketless fallback; docs/serving.md degraded-mode matrix) — with
    ``max_frame_mb`` bounding the frame size. The dial-with-backoff
    schedule is a resilience ``RetryPolicy`` built by
    :meth:`connect_retry_policy` from ``connect_retries`` /
    ``connect_backoff_seconds`` / ``connect_backoff_max_seconds``
    (the first two predate the policy and stay as aliases).

    Health state machine (docs/serving.md "Replica health"):
    ``health_mode`` is ``state_machine`` (healthy → suspect → dead with
    hysteresis) or ``legacy`` (the single stale-heartbeat flip,
    bit-exact pre-PR-15 routing). ``suspect_after_seconds`` is the
    heartbeat age that demotes to suspect (0 = half of
    ``stale_after_seconds``); ``transport_error_dead`` consecutive
    channel errors declare dead; ``health_recover_checks`` consecutive
    clean checks promote suspect back to healthy.

    Hedged requests: after ``hedge_ttft_factor`` x the predicted TTFT
    (floored at ``hedge_min_seconds``) with no first token, the router
    resubmits to a second replica and keeps whichever stream emits
    first — greedy decode makes the winner bit-identical either way.

    Crash-loop containment (serving/supervisor.py): a lineage crashing
    more than ``max_restarts_per_window`` times inside
    ``restart_window_seconds`` is quarantined instead of restarted;
    ``min_healthy`` is the floor below which drains are refused.

    Live session migration (docs/serving.md "Zero-downtime
    operations"): with ``migrate_sessions`` (the default), drains,
    rolling weight swaps, and migration-backed scale-down move every
    in-flight decode session off the leaving replica *warm* — KV
    blocks + generated tokens + spec EWMA over the quantized wire,
    zero re-prefill — degrading to host-tier page-out then legacy
    fold-and-recompute, never an error. ``migrate_hedges`` extends
    migrate-first to hedge promotion (off keeps the duplicate-stream
    hedge race bit-exact); ``migrate_wire`` overrides the session wire
    codec (empty = the engine's ``handoff_wire``; else raw/int8/int4/
    fp8)."""

    replicas: int = 2
    mode: str = "unified"
    prefill_replicas: int = 1
    affinity_blocks: int = 2
    stale_after_seconds: float = 5.0
    autoscale_min: int = 1
    autoscale_max: int = 8
    queue_high: float = 4.0
    queue_low: float = 0.5
    slo_miss_high: float = 0.1
    hysteresis_rounds: int = 3
    routing: str = "least_loaded"
    transport: str = "inproc"
    max_frame_mb: int = 64
    connect_retries: int = 40
    connect_backoff_seconds: float = 0.05
    connect_backoff_max_seconds: float = 1.0
    health_mode: str = "state_machine"
    suspect_after_seconds: float = 0.0  # 0 => stale_after_seconds / 2
    transport_error_dead: int = 3
    health_recover_checks: int = 2
    hedge_enabled: bool = False
    hedge_ttft_factor: float = 3.0
    hedge_min_seconds: float = 0.25
    max_restarts_per_window: int = 3
    restart_window_seconds: float = 30.0
    min_healthy: int = 1
    migrate_sessions: bool = True
    migrate_hedges: bool = False
    migrate_wire: str = ""  # "" => the engine's handoff_wire
    burn_rate: BurnRateConfig = field(default_factory=BurnRateConfig)

    def connect_retry_policy(self):
        """The transport dial schedule as a resilience
        :class:`RetryPolicy` — jitter 0 so reconnect timing stays
        deterministic under the chaos gates."""
        from deepspeed_tpu.resilience.policy import RetryPolicy

        return RetryPolicy(
            max_retries=max(0, self.connect_retries - 1),
            backoff_base_s=self.connect_backoff_seconds,
            backoff_max_s=self.connect_backoff_max_seconds,
            jitter=0.0)

    def validate(self) -> None:
        if self.mode not in ("unified", "disagg"):
            raise ValueError(
                f"serving.router.mode must be 'unified' or 'disagg', "
                f"got {self.mode!r}")
        if self.replicas < 1:
            raise ValueError(
                f"serving.router.replicas must be >= 1, got "
                f"{self.replicas}")
        if self.mode == "disagg" and not (
                1 <= self.prefill_replicas < self.replicas):
            raise ValueError(
                f"serving.router.prefill_replicas must leave at least "
                f"one decode replica (1 <= prefill_replicas < replicas),"
                f" got {self.prefill_replicas} of {self.replicas}")
        if self.affinity_blocks < 0:
            raise ValueError(
                f"serving.router.affinity_blocks must be >= 0, got "
                f"{self.affinity_blocks}")
        if self.stale_after_seconds <= 0:
            raise ValueError(
                f"serving.router.stale_after_seconds must be > 0, got "
                f"{self.stale_after_seconds}")
        if not 1 <= self.autoscale_min <= self.autoscale_max:
            raise ValueError(
                f"serving.router needs 1 <= autoscale_min <= "
                f"autoscale_max, got ({self.autoscale_min}, "
                f"{self.autoscale_max})")
        if self.hysteresis_rounds < 1:
            raise ValueError(
                f"serving.router.hysteresis_rounds must be >= 1, got "
                f"{self.hysteresis_rounds}")
        if self.routing not in ("least_loaded", "predictive"):
            raise ValueError(
                f"serving.router.routing must be least_loaded|"
                f"predictive, got {self.routing!r}")
        if self.transport not in ("inproc", "socket", "file"):
            raise ValueError(
                f"serving.router.transport must be inproc|socket|file, "
                f"got {self.transport!r}")
        if self.max_frame_mb < 1:
            raise ValueError(
                f"serving.router.max_frame_mb must be >= 1, got "
                f"{self.max_frame_mb}")
        if self.connect_retries < 1 or self.connect_backoff_seconds <= 0:
            raise ValueError(
                f"serving.router needs connect_retries >= 1 and "
                f"connect_backoff_seconds > 0, got "
                f"({self.connect_retries}, "
                f"{self.connect_backoff_seconds})")
        if self.connect_backoff_max_seconds < self.connect_backoff_seconds:
            raise ValueError(
                f"serving.router.connect_backoff_max_seconds must be >= "
                f"connect_backoff_seconds, got "
                f"{self.connect_backoff_max_seconds}")
        if self.health_mode not in ("state_machine", "legacy"):
            raise ValueError(
                f"serving.router.health_mode must be state_machine|"
                f"legacy, got {self.health_mode!r}")
        if self.suspect_after_seconds < 0:
            raise ValueError(
                f"serving.router.suspect_after_seconds must be >= 0 "
                f"(0 = stale_after_seconds/2), got "
                f"{self.suspect_after_seconds}")
        if self.transport_error_dead < 1 or self.health_recover_checks < 1:
            raise ValueError(
                f"serving.router needs transport_error_dead >= 1 and "
                f"health_recover_checks >= 1, got "
                f"({self.transport_error_dead}, "
                f"{self.health_recover_checks})")
        if self.hedge_ttft_factor <= 0 or self.hedge_min_seconds < 0:
            raise ValueError(
                f"serving.router needs hedge_ttft_factor > 0 and "
                f"hedge_min_seconds >= 0, got "
                f"({self.hedge_ttft_factor}, {self.hedge_min_seconds})")
        if self.max_restarts_per_window < 1 \
                or self.restart_window_seconds <= 0:
            raise ValueError(
                f"serving.router needs max_restarts_per_window >= 1 and "
                f"restart_window_seconds > 0, got "
                f"({self.max_restarts_per_window}, "
                f"{self.restart_window_seconds})")
        if self.min_healthy < 1:
            raise ValueError(
                f"serving.router.min_healthy must be >= 1, got "
                f"{self.min_healthy}")
        if self.migrate_wire not in ("", "auto", "raw", "int8", "int4",
                                     "fp8"):
            raise ValueError(
                f"serving.router.migrate_wire must be empty (engine "
                f"default) or one of auto/raw/int8/int4/fp8, got "
                f"{self.migrate_wire!r}")
        self.burn_rate.validate()


@register_config_model
@dataclass
class ServingConfig(ConfigModel):
    """Serving-engine knobs (inference/engine_v2.py, docs/serving.md).

    Admission: since PR 8, ``InferenceEngineV2.put()`` NEVER raises on a
    full KV pool — the pre-PR-8 contract (put() raised ``RuntimeError``
    when ``can_schedule`` failed) is retired. Requests wait in a FIFO
    queue and admit as blocks free up; ``max_queue_depth`` (default
    unbounded) restores fail-fast backpressure for callers that want an
    error instead of queueing. ``can_schedule()`` remains as an advisory
    capacity probe.

    ``prefix_cache`` shares full KV blocks across requests whose prompt
    prefixes match by content hash (repeated system prompts prefill
    once); ``spec_decode`` enables model-free prompt-lookup speculative
    decoding — ``spec_k`` drafted tokens per sequence verified in one
    ragged forward, n-gram match length up to ``spec_ngram``. Greedy
    output is token-identical with speculation on or off.
    ``decode_steps`` is the steady-state multi-token decode burst length
    (1 restores strict per-token SplitFuse admission).

    ``kv_quant_bits`` stores KV-cache blocks as quantized payloads with
    one fp32 scale per head_dim vector: 8 keeps int8 storage, 4 packs
    two nibbles per byte (~1.9x more sessions at head_dim 128; decode
    SNR gated in ``make serve-quant``), "fp8" stores e4m3 floats (same
    2x footprint as int8 with format-native dynamic range). None keeps
    today's bf16 pool bit-exactly — the quantized pytree never enters
    the traced program. ``handoff_wire`` picks the disaggregated-prefill
    KV handoff codec: "auto" ships the pool's native format, "raw"
    forces full precision, "int8"/"int4"/"fp8" quantize bf16 pools for
    the wire (int4 packs two values per byte, fp8 ships native e4m3
    payloads + per-vector scales with no bf16 round-trip; both
    converted pool-native on install).

    ``host_kv_tier`` attaches a ``host_tier_mb``-byte host-memory tier
    below the HBM pool (ragged/kv_tier.py): KV pressure PAGES cold
    prefix chains and preempted sessions out in pool-native format
    instead of discarding them, and returning sessions warm-resume
    decode without re-prefill. Off keeps the HBM-only engine
    bit-exactly. ``spec_adaptive_k`` makes the speculative draft length
    per-request adaptive (acceptance-EWMA x batch-occupancy controller,
    ``spec_accept_alpha`` smoothing); off is the fixed-``spec_k``
    legacy path, and greedy output stays token-identical either way."""

    max_queue_depth: Optional[int] = None
    prefix_cache: bool = True
    spec_decode: bool = False
    spec_k: int = 4
    spec_ngram: int = 3
    decode_steps: int = 8
    kv_quant_bits: Optional[Any] = None
    handoff_wire: str = "auto"
    host_kv_tier: bool = False
    host_tier_mb: int = 256
    spec_adaptive_k: bool = False
    spec_accept_alpha: float = 0.25
    router: RouterConfig = field(default_factory=RouterConfig)

    def validate(self) -> None:
        if self.max_queue_depth is not None and self.max_queue_depth < 1:
            raise ValueError(
                f"serving.max_queue_depth must be >= 1 (or null for "
                f"unbounded), got {self.max_queue_depth}")
        for name, lo in (("spec_k", 1), ("spec_ngram", 1),
                         ("decode_steps", 1), ("host_tier_mb", 1)):
            if getattr(self, name) < lo:
                raise ValueError(
                    f"serving.{name} must be >= {lo}, got "
                    f"{getattr(self, name)}")
        if self.kv_quant_bits not in (None, 4, 8, "fp8"):
            raise ValueError(
                f"serving.kv_quant_bits must be null, 4, 8 or \"fp8\", "
                f"got {self.kv_quant_bits}")
        if self.handoff_wire not in ("auto", "raw", "int8", "int4",
                                     "fp8"):
            raise ValueError(
                f"serving.handoff_wire must be one of auto/raw/int8/"
                f"int4/fp8, got {self.handoff_wire!r}")
        if not (0.0 < self.spec_accept_alpha <= 1.0):
            raise ValueError(
                f"serving.spec_accept_alpha must be in (0, 1], got "
                f"{self.spec_accept_alpha}")
        self.router.validate()


@register_config_model
@dataclass
class CompileConfig(ConfigModel):
    """Reference: deepspeed/compile/config.py. On TPU everything is compiled;
    these knobs tune donation/remat instead."""

    enabled: bool = True
    donate_params: bool = True
    scan_layers: bool = True


@register_config_model
@dataclass
class KernelsConfig(ConfigModel):
    """Pallas kernel geometry + dispatch policy (docs/kernels.md).

    Block sizes were hardcoded in the kernels; they are config knobs
    and autotuner axes now (kernel-geometry axis family — candidates
    are shape-legal divisors only, ``autotuning/autotuner.py``). 0
    means "auto": the kernel's seq-derived default for flash, the
    measured v5e tiles for the grouped matmul, one page per compute
    block for paged attention.

    ``dispatch`` picks how ``ops/attention.py`` chooses flash vs XLA:
    "auto" consults the per-(kernel, shape-bucket) win/loss table
    (``ops/kernel_table.py``; measured by ``make bench-kernels``) with
    the legacy seq-length heuristic covering unmeasured buckets;
    "heuristic" ignores the table (pre-round-14 behavior).
    ``table_path`` overrides the table location (None → the
    ``DSTPU_KERNEL_TABLE`` env var, then
    ``docs/autotuned/kernel_table.json``)."""

    flash_block_q: int = 0  # 0 = auto (1024 at seq>=8k else min(512, S))
    flash_block_k: int = 0
    pages_per_compute_block: int = 1  # KV pages folded per paged-attn grid step
    gmm_block_m: int = 512
    gmm_block_n: int = 1024
    gmm_block_k: int = 512
    blocksparse_block: int = 0  # 0 = follow sparse_attention.block
    dispatch: str = "auto"  # auto (win/loss table) | heuristic
    table_path: Optional[str] = None

    def validate(self) -> None:
        for name in ("flash_block_q", "flash_block_k", "gmm_block_m",
                     "gmm_block_n", "gmm_block_k", "blocksparse_block"):
            v = getattr(self, name)
            if v < 0 or (v and v & (v - 1)):
                raise ValueError(
                    f"kernels.{name} must be 0 (auto) or a power of "
                    f"two, got {v}")
        if self.pages_per_compute_block < 1:
            raise ValueError(
                f"kernels.pages_per_compute_block must be >= 1, got "
                f"{self.pages_per_compute_block}")
        if self.dispatch not in ("auto", "heuristic"):
            raise ValueError(
                f"kernels.dispatch must be auto|heuristic, got "
                f"{self.dispatch!r}")


@register_config_model
@dataclass
class DataEfficiencyConfig(ConfigModel):
    """Reference: runtime/data_pipeline/config.py (curriculum etc.)."""

    enabled: bool = False
    seed: int = 1234
    curriculum_metrics: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# top-level config
# ---------------------------------------------------------------------------

_TOP_LEVEL_DEPRECATED = {
    "train_micro_batch_size_per_gpu": "train_micro_batch_size_per_chip",
}


@register_config_model
@dataclass
class Config(ConfigModel):
    """Top-level typed config (reference: DeepSpeedConfig runtime/config.py:676).

    Build with :func:`load_config` / ``Config.from_dict``; the batch triple is
    solved against the data-parallel world size by :meth:`resolve_batch_size`.
    """

    _deprecated_keys = _TOP_LEVEL_DEPRECATED

    # batch triple (any subset; solver fills the rest)
    train_batch_size: Any = None
    train_micro_batch_size_per_chip: Any = None
    gradient_accumulation_steps: Any = None

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    communication_data_type: Optional[str] = None
    seed: int = 42

    # dtype blocks
    bf16: BF16Config = field(default_factory=BF16Config)
    fp16: FP16Config = field(default_factory=FP16Config)

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    zero_optimization: ZeroConfig = field(default_factory=ZeroConfig)
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    sequence_parallel: SequenceParallelConfig = field(default_factory=SequenceParallelConfig)
    moe: MoEConfig = field(default_factory=MoEConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    activation_checkpointing: ActivationCheckpointingConfig = field(
        default_factory=ActivationCheckpointingConfig
    )
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    monitor: MonitorConfig = field(default_factory=MonitorConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    observability: ObservabilityConfig = field(default_factory=ObservabilityConfig)
    performance: PerformanceConfig = field(default_factory=PerformanceConfig)
    sparse_attention: Optional[SparseAttentionConfig] = None
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    compile: CompileConfig = field(default_factory=CompileConfig)
    # raw elasticity block: consumed by deepspeed_tpu/elasticity/ (the
    # launcher and compute_elastic_config take the dict form); kept
    # unparsed here so it survives into checkpoint metadata, where the
    # resharded-restore path re-checks the batch math for the new world
    elasticity: Optional[Dict[str, Any]] = None
    data_efficiency: DataEfficiencyConfig = field(default_factory=DataEfficiencyConfig)
    kernels: KernelsConfig = field(default_factory=KernelsConfig)

    # monitor blocks may also appear top-level in reference configs
    tensorboard: Optional[MonitorBackendConfig] = None
    csv_monitor: Optional[MonitorBackendConfig] = None
    wandb: Optional[MonitorBackendConfig] = None
    comet: Optional[MonitorBackendConfig] = None

    def __post_init__(self):
        # a JSON null for a block means "defaults", not "no block"
        defaultable = {
            "bf16": BF16Config, "fp16": FP16Config, "zero_optimization": ZeroConfig,
            "tensor_parallel": TensorParallelConfig,
            "sequence_parallel": SequenceParallelConfig, "moe": MoEConfig,
            "pipeline": PipelineConfig, "monitor": MonitorConfig,
            "activation_checkpointing": ActivationCheckpointingConfig,
            "comms_logger": CommsLoggerConfig, "flops_profiler": FlopsProfilerConfig,
            "observability": ObservabilityConfig,
            "performance": PerformanceConfig,
            "checkpoint": CheckpointConfig, "serving": ServingConfig,
            "resilience": ResilienceConfig, "compile": CompileConfig,
            "data_efficiency": DataEfficiencyConfig,
            "kernels": KernelsConfig,
        }
        # sparse_attention stays None unless configured (Optional block:
        # "not present" must be distinguishable from "defaults")
        for name, klass in defaultable.items():
            if getattr(self, name) is None:
                setattr(self, name, klass())
        # hoist top-level monitor blocks into .monitor (reference accepts both)
        for name in ("tensorboard", "csv_monitor", "wandb", "comet"):
            blk = getattr(self, name)
            if blk is not None:
                setattr(self.monitor, name, blk)

    # -- dtypes ------------------------------------------------------------
    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.fp16.enabled:
            return jnp.float16
        if self.bf16.enabled:
            return jnp.bfloat16
        return jnp.float32

    @property
    def loss_scaling_enabled(self) -> bool:
        return self.fp16.enabled

    def validate(self) -> None:
        if self.fp16.enabled and self.bf16 is not None and self.bf16.enabled:
            # reference errors on both; bf16 defaults on, so fp16 wins if
            # explicitly requested.
            self.bf16.enabled = False
        if self.gradient_clipping < 0:
            raise ValueError("gradient_clipping must be >= 0")

    # -- batch triple solver ----------------------------------------------
    def resolve_batch_size(self, dp_world_size: int) -> None:
        """Solve train_batch = micro × GAS × dp (reference
        runtime/config.py:971 ``_configure_train_batch_size``)."""
        tb = None if is_auto(self.train_batch_size) else self.train_batch_size
        mb = (
            None
            if is_auto(self.train_micro_batch_size_per_chip)
            else self.train_micro_batch_size_per_chip
        )
        gas = (
            None
            if is_auto(self.gradient_accumulation_steps)
            else self.gradient_accumulation_steps
        )

        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"Inconsistent batch config: train_batch_size={tb} != "
                    f"micro({mb}) * gas({gas}) * dp({dp_world_size})"
                )
        elif tb is not None and mb is not None:
            if tb % (mb * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size={tb} not divisible by micro*dp="
                    f"{mb * dp_world_size}"
                )
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size={tb} not divisible by gas*dp="
                    f"{gas * dp_world_size}"
                )
            mb = tb // (gas * dp_world_size)
        elif mb is not None:
            gas = gas if gas is not None else 1
            tb = mb * gas * dp_world_size
        elif tb is not None:
            mb = max(1, tb // dp_world_size)
            gas = tb // (mb * dp_world_size)
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"train_batch_size={tb} not divisible by dp={dp_world_size}"
                )
        elif gas is not None:
            mb = 1
            tb = mb * gas * dp_world_size
        else:
            mb, gas = 1, 1
            tb = dp_world_size

        self.train_batch_size = int(tb)
        self.train_micro_batch_size_per_chip = int(mb)
        self.gradient_accumulation_steps = int(gas)
        if self.gradient_accumulation_steps < 1:
            raise ValueError("gradient_accumulation_steps must be >= 1")


def load_config(config: str | Dict[str, Any] | Config | None) -> Config:
    """Accept a path to JSON, a dict, an existing Config, or None."""
    if config is None:
        return Config.from_dict({})
    if isinstance(config, Config):
        return config
    if isinstance(config, str):
        if not os.path.exists(config):
            raise FileNotFoundError(f"config file not found: {config}")
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError(f"config must be a path, dict, or Config; got {type(config)}")
    return Config.from_dict(config)
