"""Typed config-tree machinery.

TPU-native analog of the reference's pydantic-style ``DeepSpeedConfigModel``
(reference: deepspeed/runtime/config_utils.py) without a pydantic dependency:
dataclass-backed models with unknown-key warnings, deprecated-field aliasing,
and an ``"auto"`` sentinel resolved later by the engine/autotuner.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Type, TypeVar, get_args, get_origin, Union

from deepspeed_tpu.utils.logging import logger

AUTO = "auto"

T = TypeVar("T", bound="ConfigModel")


def is_auto(value: Any) -> bool:
    return isinstance(value, str) and value.lower() == AUTO


@dataclasses.dataclass
class ConfigModel:
    """Base class for all config sub-models.

    Subclasses are plain dataclasses. ``from_dict`` performs:
      - deprecated-key aliasing via the class attr ``_deprecated_keys``
        ({old_key: new_key}), warning on use (parity with the reference's
        ``deprecated`` field metadata, config_utils.py);
      - recursion into nested ConfigModel fields;
      - unknown-key warnings (the reference errors or warns depending on
        model; we warn and ignore to stay permissive);
      - light type coercion (int/float/bool from JSON strings).
    """

    @classmethod
    def from_dict(cls: Type[T], data: Dict[str, Any] | None) -> T:
        data = dict(data or {})
        deprecated = getattr(cls, "_deprecated_keys", {})
        for old, new in deprecated.items():
            if old in data:
                logger.warning(
                    f"Config key '{old}' is deprecated; use '{new}' instead."
                )
                data.setdefault(new, data.pop(old))

        field_map = {f.name: f for f in dataclasses.fields(cls) if f.init}
        kwargs: Dict[str, Any] = {}
        for key, value in data.items():
            if key not in field_map:
                logger.warning(f"{cls.__name__}: ignoring unknown config key '{key}'")
                continue
            kwargs[key] = _coerce(field_map[key].type, value, f"{cls.__name__}.{key}")
        obj = cls(**kwargs)
        obj.validate()
        return obj

    def validate(self) -> None:
        """Override for cross-field checks. Raise ValueError on bad configs."""

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):
            if f.name.startswith("_"):
                continue
            v = getattr(self, f.name)
            out[f.name] = v.to_dict() if isinstance(v, ConfigModel) else v
        return out

    def __repr__(self) -> str:  # compact, hide internals
        body = ", ".join(
            f"{f.name}={getattr(self, f.name)!r}"
            for f in dataclasses.fields(self)
            if not f.name.startswith("_")
        )
        return f"{self.__class__.__name__}({body})"


def _unwrap_optional(tp):
    if get_origin(tp) is Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _coerce(tp, value, where: str):
    """Best-effort coercion of JSON-ish values to the declared field type."""
    if isinstance(tp, str):
        # string annotations (from __future__ annotations) — look up lazily
        tp = _resolve_annotation(tp)
        if tp is None:
            return value
    tp = _unwrap_optional(tp)
    if value is None or is_auto(value):
        return value
    if isinstance(tp, type) and issubclass(tp, ConfigModel):
        if isinstance(tp, type) and isinstance(value, tp):
            return value
        if not isinstance(value, dict):
            raise ValueError(f"{where}: expected a dict, got {type(value).__name__}")
        return tp.from_dict(value)
    if tp is bool and isinstance(value, str):
        return value.lower() in ("true", "1", "yes", "on")
    if tp in (int, float) and isinstance(value, (str, int, float, bool)):
        try:
            return tp(value)
        except (TypeError, ValueError):
            raise ValueError(f"{where}: cannot convert {value!r} to {tp.__name__}")
    return value


_ANNOTATION_REGISTRY: Dict[str, type] = {}


def register_config_model(cls):
    """Class decorator: make a ConfigModel resolvable from string annotations."""
    _ANNOTATION_REGISTRY[cls.__name__] = cls
    return cls


def _resolve_annotation(name: str):
    name = name.strip()
    for prefix in ("Optional[", "typing.Optional["):
        if name.startswith(prefix) and name.endswith("]"):
            name = name[len(prefix):-1].strip()
    if name in _ANNOTATION_REGISTRY:
        return _ANNOTATION_REGISTRY[name]
    return {"int": int, "float": float, "bool": bool, "str": str}.get(name)


def get_scalar_param(config_dict: Dict[str, Any], key: str, default):
    """Reference-parity helper (deepspeed/runtime/config_utils.py)."""
    return config_dict.get(key, default)
