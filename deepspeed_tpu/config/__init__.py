from deepspeed_tpu.config.config import Config, load_config  # noqa: F401
from deepspeed_tpu.config.config_utils import AUTO, ConfigModel, is_auto  # noqa: F401
