"""Autotuner: search micro-batch / ZeRO stage / remat for best throughput.

Reference: ``deepspeed/autotuning/autotuner.py:42`` (``Autotuner``, ``.tune()``
:404) — before real training, enumerate a config space (ZeRO stage ×
micro-batch × offload), run short profiling experiments through a
scheduler, measure throughput, and emit the best config.

TPU-native twist: the expensive part of the reference's flow — launching a
real experiment per candidate just to discover OOM — is replaced by XLA's
compile-time ``memory_analysis()``: every candidate is *lowered and
compiled* (fast, no step execution) and candidates whose compiled peak
memory exceeds the per-chip HBM budget are pruned before any is timed.
Only the surviving top candidates are actually run (``measure_steps``
timed steps each). This is the "model-based tuning" mode of the reference
(``tune_space`` model, autotuner.py:523) with the compiler as the model.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

METRIC_THROUGHPUT = "throughput"  # samples/sec (reference autotuning_metric)
METRIC_LATENCY = "latency"


def parse_quant_mode(mode: str) -> Dict[str, Any]:
    """Decode a ZeRO++ quantization-mode label into the
    ``zero_optimization`` keys it stands for.

    Grammar: ``"off"`` or ``"+"``-joined tokens from {``qwz``, ``qgz``,
    ``qar``, ``hpz<k>``} — e.g. ``"qwz+qgz+hpz8"`` or ``"qar"``. ``qar``
    (EQuARX-style quantized all-reduce) and ``qgz`` are mutually
    exclusive: both own the gradient wire (ZeroConfig.validate rejects
    the pair, and so does this parser). This is the shared vocabulary
    of the ``quant_modes`` tuning axis, ``tools/quant_sweep.py`` rows,
    and the ``quant_mode`` key bench.py reads back from the persisted
    real-shape defaults."""
    out = {"zero_quantized_weights": False,
           "zero_quantized_gradients": False,
           "zero_quantized_allreduce": False,
           "zero_hpz_partition_size": 1}
    mode = str(mode).strip().lower()
    if mode in ("off", "", "none"):
        return out
    for tok in mode.split("+"):
        tok = tok.strip()
        if tok == "qwz":
            out["zero_quantized_weights"] = True
        elif tok == "qgz":
            out["zero_quantized_gradients"] = True
        elif tok == "qar":
            out["zero_quantized_allreduce"] = True
        elif tok.startswith("hpz"):
            try:
                out["zero_hpz_partition_size"] = int(tok[3:])
            except ValueError:
                raise ValueError(f"bad hpz token {tok!r} in quant mode "
                                 f"{mode!r} (want e.g. hpz8)") from None
        else:
            raise ValueError(f"unknown quant-mode token {tok!r} in "
                             f"{mode!r} (grammar: off | "
                             f"qwz+[qgz|qar]+hpz<k>)")
    if out["zero_quantized_gradients"] and out["zero_quantized_allreduce"]:
        raise ValueError(f"quant mode {mode!r} combines qgz and qar — "
                         f"both own the gradient wire, pick one")
    return out


def format_quant_mode(qwz: bool, qgz: bool, hpz: int = 1,
                      qar: bool = False) -> str:
    """Inverse of :func:`parse_quant_mode`."""
    toks = (([] if not qwz else ["qwz"]) + ([] if not qgz else ["qgz"])
            + ([] if not qar else ["qar"]))
    if int(hpz) > 1:
        toks.append(f"hpz{int(hpz)}")
    return "+".join(toks) or "off"


def parse_blocks(label: str, n: int) -> List[int]:
    """Parse an ``x``-joined block-geometry label (``"512x512"``,
    ``"512x1024x512"``) into ``n`` ints, validating each is a positive
    power of two. Shared by the ``flash_blocks`` / ``gmm_tiles`` tuning
    axes and their CLI flags."""
    parts = str(label).lower().split("x")
    if len(parts) != n:
        raise ValueError(f"block label {label!r}: want {n} 'x'-joined "
                         f"ints (e.g. {'x'.join(['512'] * n)})")
    vals = []
    for p in parts:
        v = int(p)
        if v <= 0 or v & (v - 1):
            raise ValueError(f"block label {label!r}: {v} is not a "
                             f"positive power of two")
        vals.append(v)
    return vals


def legal_flash_blocks(seq: int, lo: int = 128,
                       hi: int = 1024) -> List[str]:
    """Shape-legal flash block candidates for a sequence length: square
    power-of-two blocks that tile ``seq`` exactly (the kernel clamps
    others, so off-divisor candidates would silently measure a
    different geometry). The ``--flash-blocks auto`` axis family."""
    out = []
    b = lo
    while b <= min(hi, seq):
        if seq % b == 0:
            out.append(f"{b}x{b}")
        b *= 2
    return out or [f"{min(lo, seq)}x{min(lo, seq)}"]


@dataclasses.dataclass
class AutotunerResult:
    config: Dict[str, Any]
    metric_value: float  # samples/sec (or -sec for latency)
    peak_bytes: int
    compiled_ok: bool
    ran: bool
    error: Optional[str] = None

    def to_dict(self):
        return dataclasses.asdict(self)


class Autotuner:
    """Search over engine configs for a model.

    Args:
      model_factory: () -> model (fresh model per trial; engines own state)
      base_config:   dict config every trial starts from
      batch_fn:      (global_batch_size) -> batch dict for one micro step
      tuning_space:  {"micro_batch_sizes": [...], "zero_stages": [...],
                      "remat": [...], "remat_policies": [...],
                      "tiled_logits": [...], "attn_chunks": [...],
                      "prefetch_depths": [...], "overlap_depths": [...],
                      "sp_modes": [...]}
                      — the last five are model-config axes for the
                      real-shape sweep (vocab-head tile count, FPDT
                      query chunks, the ZeRO-Infinity layer-prefetch
                      ring depth, and the overlap-engine stage depth);
                      None in any of them keeps the model's own setting
      hbm_budget_bytes: prune candidates whose compiled peak exceeds this
                      (default: detected device memory, else 16 GiB)
      topology:      mesh topology dict forwarded to every trial engine —
                      must match the final run's topology or the tuned
                      settings are measured under a different mesh
      persist_path:  write the winning config (model knobs surfaced as
                      top-level keys) as JSON here after tune() — the
                      bench reads it back as its real-shape defaults
    """

    STATIC_OVERSHOOT = 1.2  # static peak estimate vs allocator reality

    def __init__(self, model_factory: Callable[[], Any],
                 base_config: Dict[str, Any],
                 batch_fn: Callable[[int], Dict[str, np.ndarray]],
                 tuning_space: Optional[Dict[str, Sequence]] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 results_dir: Optional[str] = None,
                 topology: Optional[Dict[str, int]] = None,
                 persist_path: Optional[str] = None):
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        self.batch_fn = batch_fn
        space = dict(tuning_space or {})
        self.micro_batch_sizes = list(space.get("micro_batch_sizes",
                                                [1, 2, 4, 8]))
        self.zero_stages = list(space.get("zero_stages", [1, 2, 3]))
        self.remat = list(space.get("remat", [False]))
        # named remat policies (activation_checkpointing registry);
        # None = keep the model's own policy
        self.remat_policies = list(space.get("remat_policies", [None]))
        # real-shape model axes (ISSUE 4): vocab-head tile count ×
        # FPDT attention chunks × layer-prefetch ring depth. None in a
        # list = keep the model's own value for that axis.
        self.tiled_logits = list(space.get("tiled_logits", [None]))
        self.attn_chunks = list(space.get("attn_chunks", [None]))
        self.prefetch_depths = list(space.get("prefetch_depths", [None]))
        # overlap-engine depth (ISSUE 6): pin_stage barrier staging of
        # the K newest in-flight transfers per layer. None = model/env
        # default; 0 = today's unstaged schedule
        self.overlap_depths = list(space.get("overlap_depths", [None]))
        # sp strategy (ISSUE 7 planner): 'ulysses' | 'ring' candidates
        # for models running sequence-parallel; None = keep the model's
        # own sp_mode (or whatever the planner composed at init)
        self.sp_modes = list(space.get("sp_modes", [None]))
        # ZeRO++ quantization modes (ISSUE 11): parse_quant_mode labels
        # ("off", "qwz+qgz+hpz8", ...) expanded into zero_optimization
        # keys per candidate; None = keep the base config's flags
        self.quant_modes = list(space.get("quant_modes", [None]))
        # serving KV-quant axes (ISSUE 12): KV-pool storage bits (0 =
        # bf16 pool) × disagg handoff wire codec. These ride into
        # cfg["serving"] so serving benches / engines built from the
        # winning config pick them up; the train-step probe ignores them
        self.kv_quant_bits = list(space.get("kv_quant_bits", [None]))
        self.handoff_wires = list(space.get("handoff_wires", [None]))
        # kernel-geometry axis family (ISSUE 14): flash block_q x block_k
        # ("512x512" labels, shape-legal divisors only — see
        # legal_flash_blocks), grouped-matmul m x n x k tiles, and the
        # paged-attention pages-per-compute-block fan-in. They ride as
        # real cfg["kernels"] keys (the engine consumes that block
        # directly, so trials genuinely run the geometry) and the winner
        # persists to docs/autotuned/ with the rest of the config
        self.flash_blocks = list(space.get("flash_blocks", [None]))
        self.gmm_tiles = list(space.get("gmm_tiles", [None]))
        self.pages_per_block = list(space.get("pages_per_block", [None]))
        self.hbm_budget = hbm_budget_bytes or self._detect_hbm()
        self.results_dir = results_dir
        self.persist_path = persist_path
        self.topology = dict(topology) if topology else None
        self.results: List[AutotunerResult] = []

    @staticmethod
    def _detect_hbm() -> int:
        import jax

        try:
            stats = jax.local_devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:
            pass
        # memory_stats() is unavailable on some backends (axon tunnel):
        # assume a 16GB-class chip
        return int(15.75 * 1024**3)

    # -- candidate enumeration (reference tune_space) -------------------
    def candidates(self) -> List[Dict[str, Any]]:
        out = []
        for (mb, stage, remat, policy, tl, ac, pd, od, sm, qm, kvb,
             hw, fb, gt, pb) in itertools.product(
                self.micro_batch_sizes, self.zero_stages, self.remat,
                self.remat_policies, self.tiled_logits, self.attn_chunks,
                self.prefetch_depths, self.overlap_depths, self.sp_modes,
                self.quant_modes, self.kv_quant_bits, self.handoff_wires,
                self.flash_blocks, self.gmm_tiles, self.pages_per_block):
            cfg = json.loads(json.dumps(self.base_config))  # deep copy
            cfg["train_micro_batch_size_per_chip"] = int(mb)
            cfg.pop("train_batch_size", None)  # re-derived from micro×gas×dp
            cfg.setdefault("zero_optimization", {})["stage"] = int(stage)
            # a named policy implies remat; record what actually runs
            cfg["_remat"] = bool(remat or policy)
            if policy is not None:
                cfg["_remat_policy"] = str(policy)
            # model-config axes ride as private keys _build_engine pops
            if tl is not None:
                cfg["_tiled_logits"] = int(tl)
            if ac is not None:
                cfg["_attn_chunks"] = int(ac)
            if pd is not None:
                cfg["_prefetch_depth"] = int(pd)
            if od is not None:
                cfg["_overlap_depth"] = int(od)
            if sm is not None:
                cfg["_sp_mode"] = str(sm)
            if qm is not None:
                # expand the label into real zero_optimization keys so
                # the trial engine actually runs the mode; keep the
                # label as a private key for tuned_defaults/persist
                cfg["zero_optimization"].update(parse_quant_mode(qm))
                cfg["_quant_mode"] = str(qm)
            if kvb is not None:
                # 0 = explicit bf16 pool (vs None = keep base config)
                cfg.setdefault("serving", {})["kv_quant_bits"] = (
                    None if int(kvb) == 0 else int(kvb))
            if hw is not None:
                cfg.setdefault("serving", {})["handoff_wire"] = str(hw)
            if fb is not None:
                bq, bk = parse_blocks(fb, 2)
                kcfg = cfg.setdefault("kernels", {})
                kcfg["flash_block_q"], kcfg["flash_block_k"] = bq, bk
            if gt is not None:
                bm, bn, bkk = parse_blocks(gt, 3)
                kcfg = cfg.setdefault("kernels", {})
                kcfg["gmm_block_m"] = bm
                kcfg["gmm_block_n"] = bn
                kcfg["gmm_block_k"] = bkk
            if pb is not None:
                cfg.setdefault("kernels", {})[
                    "pages_per_compute_block"] = int(pb)
            out.append(cfg)
        return out

    # -- compile-probe one candidate ------------------------------------
    def _build_engine(self, cfg: Dict[str, Any]):
        import deepspeed_tpu as dstpu

        cfg = dict(cfg)
        remat = cfg.pop("_remat", False)
        policy = cfg.pop("_remat_policy", None)
        cfg.pop("_quant_mode", None)  # label only; flags already applied
        model_axes = {name: cfg.pop(key)
                      for key, name in (("_tiled_logits", "tiled_logits"),
                                        ("_attn_chunks", "attn_chunks"),
                                        ("_prefetch_depth",
                                         "prefetch_depth"),
                                        ("_overlap_depth",
                                         "overlap_depth"),
                                        ("_sp_mode", "sp_mode"))
                      if key in cfg}
        model = self.model_factory()
        if hasattr(model, "config") and hasattr(model.config, "remat"):
            # set BOTH ways: models default remat=True, so a remat=False
            # candidate must actually disable it or the sweep is a no-op
            import dataclasses as _dc

            updates = {"remat": bool(remat)}
            if policy is not None:
                updates["remat_policy"] = policy
            updates.update({k: v for k, v in model_axes.items()
                            if hasattr(model.config, k)})
            model.config = _dc.replace(model.config, **updates)
        engine, *_ = dstpu.initialize(model=model, config=cfg,
                                      topology=self.topology)
        return engine

    @staticmethod
    def _release(engine) -> None:
        """Drop a trial engine's device state NOW: the next trial (and
        the final real run) must not OOM against a dead trial's params/
        optimizer arrays waiting for GC."""
        for attr in ("params", "opt_state", "loss_scale_state",
                     "step_count", "_zeropp_state", "_onebit_state"):
            if hasattr(engine, attr):
                setattr(engine, attr, None)
        import gc

        gc.collect()

    def _probe(self, cfg: Dict[str, Any]) -> AutotunerResult:
        """Lower + compile the train step; read compiled peak memory."""
        try:
            engine = self._build_engine(cfg)
        except Exception as e:  # bad mesh/batch combos are legal to prune
            return AutotunerResult(cfg, 0.0, 0, False, False, str(e)[:300])
        try:
            from deepspeed_tpu.profiling.flops_profiler import \
                profile_compiled

            gas = engine.gradient_accumulation_steps
            batch = self._stacked_batch(engine, gas)
            cost = profile_compiled(
                engine._jit_train_step, engine.params, engine.opt_state,
                engine.loss_scale_state, engine.step_count, batch)
            peak = int(cost.get("peak_bytes", 0))
            # XLA's static temp accounting over-reports vs the real
            # allocator by ~10-15% on fused train steps (measured: a
            # 17.7GB-static step runs in 15.75GB HBM) — candidates
            # within the tolerance stay measurable; runtime OOM prunes
            # for real during measurement
            ok = peak <= self.hbm_budget * self.STATIC_OVERSHOOT or peak == 0
            return AutotunerResult(cfg, 0.0, peak, ok, False,
                                   None if ok else "exceeds HBM budget")
        except Exception as e:
            return AutotunerResult(cfg, 0.0, 0, False, False, str(e)[:300])
        finally:
            self._release(engine)

    def _stacked_batch(self, engine, gas: int):
        import jax

        one = self.batch_fn(engine.micro_batch_size * engine.dp_world_size)
        stacked = jax.tree.map(
            lambda x: np.stack([np.asarray(x)] * gas), one)
        return engine.shard_batch(stacked, leading_dims=2)

    # -- measured run ----------------------------------------------------
    def _measure(self, cfg: Dict[str, Any], steps: int) -> AutotunerResult:
        engine = None
        try:
            engine = self._build_engine(cfg)
            gas = engine.gradient_accumulation_steps

            def it():
                while True:
                    yield self.batch_fn(
                        engine.micro_batch_size * engine.dp_world_size)

            data = it()
            engine.train_batch(data)  # warmup + compile
            t0 = time.time()
            for _ in range(steps):
                loss = engine.train_batch(data)
            float(loss)  # block on the last step's result
            dt = time.time() - t0
            samples = steps * engine.train_batch_size
            return AutotunerResult(cfg, samples / dt, 0, True, True)
        except Exception as e:
            return AutotunerResult(cfg, 0.0, 0, False, False, str(e)[:300])
        finally:
            if engine is not None:
                self._release(engine)

    # -- main entry (reference .tune autotuner.py:404) -------------------
    def tune(self, metric: str = METRIC_THROUGHPUT, top_k: int = 3,
             measure_steps: int = 3, fast: bool = False
             ) -> Optional[Dict[str, Any]]:
        """Prune by compile, then time the ``top_k`` smallest-memory
        candidates; returns the best config (or None if all fail).

        fast=True: skip timing — rank by compiled peak memory alone
        (model-based mode; useful where each trial's compile is the cost).
        """
        cands = self.candidates()
        log_dist(f"autotuner: {len(cands)} candidates", ranks=[0])
        probed = [self._probe(c) for c in cands]
        viable = [r for r in probed if r.compiled_ok]
        self.results = probed
        if not viable:
            # XLA's static memory analysis over-reports vs the real
            # allocator (temp accounting is conservative); the budget
            # prune is a heuristic, measurement is ground truth — try
            # the smallest-peak candidates, runtime OOM fails per-trial
            compiled = [r for r in probed if r.peak_bytes > 0]
            if not compiled:
                logger.warning("autotuner: no candidate compiled")
                self._write_results()
                return None
            logger.warning(
                "autotuner: every candidate exceeds the static HBM "
                "budget; measuring near-floor candidates anyway (the "
                "static estimate over-reports vs the allocator)")
            floor_r = min(compiled, key=lambda r: r.peak_bytes)
            near = [r for r in compiled
                    if r.peak_bytes <= floor_r.peak_bytes * 1.5]
            # keep the big-batch preference within the near-floor band —
            # pure smallest-peak would only ever measure the tiniest
            # micro batch (runtime OOMs fail per-trial and lose anyway)
            # — but always include the floor candidate so an all-OOM
            # round still falls back to the config most likely to fit
            near.sort(key=lambda r: (
                -r.config.get("train_micro_batch_size_per_chip", 0),
                r.peak_bytes))
            viable = near[:top_k]
            if floor_r not in viable:
                viable[-1] = floor_r
        # prefer larger micro-batch at equal viability: sort by batch desc,
        # peak asc — big batches amortize overhead, the usual TPU winner
        viable.sort(key=lambda r: (
            -r.config["train_micro_batch_size_per_chip"], r.peak_bytes))
        if fast:
            best = viable[0]
            self._write_results()
            self._persist_best(best.config)
            return best.config
        timed = [self._measure(r.config, measure_steps)
                 for r in viable[:top_k]]
        self.results = probed + timed
        ran = [r for r in timed if r.ran]
        self._write_results()
        if not ran:
            self._persist_best(viable[0].config)
            return viable[0].config
        best = max(ran, key=lambda r: r.metric_value)
        log_dist(
            f"autotuner best: micro="
            f"{best.config['train_micro_batch_size_per_chip']} "
            f"zero={best.config['zero_optimization']['stage']} "
            f"→ {best.metric_value:.1f} samples/s", ranks=[0])
        self._persist_best(best.config, best.metric_value)
        return best.config

    @staticmethod
    def tuned_defaults(cfg: Dict[str, Any]) -> Dict[str, Any]:
        """Surface a candidate's private model-axis keys as the public
        knob names the bench / engine understand."""
        out = json.loads(json.dumps(cfg))
        out["remat"] = bool(out.pop("_remat", False))
        if "_remat_policy" in out:
            out["remat_policy"] = out.pop("_remat_policy")
        if "_tiled_logits" in out:
            out["tiled_logits"] = int(out.pop("_tiled_logits"))
        if "_attn_chunks" in out:
            out["attn_chunks"] = int(out.pop("_attn_chunks"))
        if "_prefetch_depth" in out:
            out.setdefault("performance", {})["param_prefetch_depth"] = \
                int(out.pop("_prefetch_depth"))
        if "_overlap_depth" in out:
            out.setdefault("performance", {})["overlap_depth"] = \
                int(out.pop("_overlap_depth"))
        if "_sp_mode" in out:
            out["sp_mode"] = str(out.pop("_sp_mode"))
        if "_quant_mode" in out:
            out["quant_mode"] = str(out.pop("_quant_mode"))
        return out

    def _persist_best(self, cfg: Dict[str, Any],
                      metric_value: Optional[float] = None) -> None:
        if not self.persist_path:
            return
        payload = self.tuned_defaults(cfg)
        if metric_value is not None:
            payload["_tuned_samples_per_sec"] = float(metric_value)
        d = os.path.dirname(self.persist_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.persist_path, "w") as f:
            json.dump(payload, f, indent=2, default=str)
        log_dist(f"autotuner: persisted best config → {self.persist_path}",
                 ranks=[0])

    def _write_results(self):
        if not self.results_dir:
            return
        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "autotuner_results.json"),
                  "w") as f:
            json.dump([r.to_dict() for r in self.results], f, indent=2,
                      default=str)


# ---------------------------------------------------------------------------
# dstpu-autotune CLI (reference: `deepspeed --autotuning tune`,
# launcher/runner.py:407 entry into Autotuner.tune)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="dstpu-autotune",
        description="search micro-batch / ZeRO stage / remat for a zoo "
                    "model on the attached chips; prints the best config")
    ap.add_argument("--model", default="gpt2-125m",
                    help="zoo preset name (models/zoo.py)")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--config", default=None,
                    help="base ds_config JSON file (default: bf16+adamw)")
    ap.add_argument("--micro-batch-sizes", type=int, nargs="+", default=None)
    ap.add_argument("--zero-stages", type=int, nargs="+", default=None)
    ap.add_argument("--remat", type=int, nargs="+", default=None,
                    help="0/1 values to try")
    ap.add_argument("--remat-policies", nargs="+", default=None,
                    help="named remat policies to try (activation_"
                         "checkpointing registry); 'none' = model default")
    ap.add_argument("--tiled-logits", type=int, nargs="+", default=None,
                    help="vocab-head tile counts to try (0 = untiled)")
    ap.add_argument("--attn-chunks", type=int, nargs="+", default=None,
                    help="FPDT attention query-chunk counts to try")
    ap.add_argument("--prefetch-depths", type=int, nargs="+", default=None,
                    help="layer-prefetch ring depths to try (1 = plain "
                         "double buffering)")
    ap.add_argument("--sp-modes", nargs="+", default=None,
                    help="sequence-parallel strategy candidates "
                         "(ulysses/ring) for sp-enabled models")
    ap.add_argument("--overlap-depths", type=int, nargs="+", default=None,
                    help="overlap-engine depths to try (0 = unstaged "
                         "schedule; k pins the k newest in-flight "
                         "transfers into the issuing layer's stage)")
    ap.add_argument("--quant-modes", nargs="+", default=None,
                    help="ZeRO++ quantization modes to try (grammar: "
                         "off | qwz+[qgz|qar]+hpz<k>, e.g. off qwz "
                         "qwz+qgz qar qwz+qgz+hpz8)")
    ap.add_argument("--kv-quant-bits", type=int, nargs="+", default=None,
                    help="serving KV-pool storage bits to try (0 = bf16 "
                         "pool, 8 = int8 blocks + scales, 4 = packed-"
                         "nibble uint8 blocks + scales)")
    ap.add_argument("--flash-blocks", nargs="+", default=None,
                    help="flash block_q x block_k candidates to try "
                         "('512x512' labels; 'auto' = all shape-legal "
                         "power-of-two divisors of --seq)")
    ap.add_argument("--gmm-tiles", nargs="+", default=None,
                    help="grouped-matmul m x n x k tile candidates "
                         "('512x1024x512' labels; power-of-two entries, "
                         "the kernel snaps to legal divisors per shape)")
    ap.add_argument("--pages-per-block", type=int, nargs="+", default=None,
                    help="paged-attention KV pages folded per compute "
                         "block (>=1; bit-identical output for every "
                         "value, only the grid geometry changes)")
    ap.add_argument("--handoff-wires", nargs="+", default=None,
                    help="disagg KV-handoff wire codecs to try "
                         "(auto/raw/int8/int4)")
    ap.add_argument("--fast", action="store_true",
                    help="rank by compiled memory only (no timed runs)")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--results-dir", default=None)
    ap.add_argument("--persist", default=None, metavar="PATH",
                    help="write the winning config JSON here (bench.py "
                         "reads it back as real-shape defaults)")
    args = ap.parse_args(argv)

    import numpy as np

    from deepspeed_tpu.models.zoo import get_model

    if args.config:
        with open(args.config) as f:
            base = json.load(f)
    else:
        base = {"optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True}, "steps_per_print": 1_000_000}

    def model_factory():
        return get_model(args.model, max_seq_len=args.seq)

    vocab = model_factory().config.vocab_size
    rng = np.random.default_rng(0)

    def batch_fn(global_batch):
        return {"input_ids": rng.integers(
            0, vocab, (global_batch, args.seq + 1)).astype(np.int32)}

    space = {}
    if args.micro_batch_sizes:
        space["micro_batch_sizes"] = args.micro_batch_sizes
    if args.zero_stages:
        space["zero_stages"] = args.zero_stages
    if args.remat is not None:
        space["remat"] = [bool(v) for v in args.remat]
    if args.remat_policies is not None:
        space["remat_policies"] = [None if p == "none" else p
                                   for p in args.remat_policies]
    if args.tiled_logits is not None:
        space["tiled_logits"] = args.tiled_logits
    if args.attn_chunks is not None:
        space["attn_chunks"] = args.attn_chunks
    if args.prefetch_depths is not None:
        space["prefetch_depths"] = args.prefetch_depths
    if args.overlap_depths is not None:
        space["overlap_depths"] = args.overlap_depths
    if args.sp_modes is not None:
        space["sp_modes"] = args.sp_modes
    if args.quant_modes is not None:
        # validate the labels up front (fail before any trial compiles)
        for qm in args.quant_modes:
            parse_quant_mode(qm)
        space["quant_modes"] = args.quant_modes
    if args.kv_quant_bits is not None:
        for b in args.kv_quant_bits:
            if b not in (0, 4, 8):
                ap.error(f"--kv-quant-bits values must be 0, 4 or 8, "
                         f"got {b}")
        space["kv_quant_bits"] = args.kv_quant_bits
    if args.handoff_wires is not None:
        for w in args.handoff_wires:
            if w not in ("auto", "raw", "int8", "int4"):
                ap.error(f"--handoff-wires values must be auto/raw/int8/"
                         f"int4, got {w!r}")
        space["handoff_wires"] = args.handoff_wires
    if args.flash_blocks is not None:
        labels = []
        for fb in args.flash_blocks:
            if fb == "auto":
                labels.extend(legal_flash_blocks(args.seq))
                continue
            try:
                parse_blocks(fb, 2)
            except ValueError as e:
                ap.error(str(e))
            labels.append(fb)
        space["flash_blocks"] = labels
    if args.gmm_tiles is not None:
        for gt in args.gmm_tiles:
            try:
                parse_blocks(gt, 3)
            except ValueError as e:
                ap.error(str(e))
        space["gmm_tiles"] = args.gmm_tiles
    if args.pages_per_block is not None:
        for p in args.pages_per_block:
            if p < 1:
                ap.error(f"--pages-per-block values must be >= 1, got {p}")
        space["pages_per_block"] = args.pages_per_block
    tuner = Autotuner(model_factory, base, batch_fn,
                      tuning_space=space or None,
                      results_dir=args.results_dir,
                      persist_path=args.persist)
    best = tuner.tune(fast=args.fast, measure_steps=args.steps)
    if best is None:
        print(json.dumps({"error": "no viable config"}))
        return 1
    # surface winning model knobs (model flags, not config keys) as
    # top-level entries so the printed config reproduces the result
    print(json.dumps(Autotuner.tuned_defaults(best)))
    return 0
