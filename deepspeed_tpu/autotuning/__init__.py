"""Autotuning subsystem (reference: deepspeed/autotuning/)."""

from deepspeed_tpu.autotuning.autotuner import (  # noqa: F401
    Autotuner,
    AutotunerResult,
)
