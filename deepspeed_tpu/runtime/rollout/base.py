"""Rollout abstraction: pluggable generation-for-RL.

Reference: ``deepspeed/runtime/rollout/base.py:88`` (``BaseRollout``) — a
stable interface RL trainers call for trajectory generation, decoupled
from *how* generation runs (hybrid engine, external server, ...).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class RolloutRequest:
    """One generation request (reference request dataclass)."""

    prompts: Any  # [B, S] token array (np/list)
    max_new_tokens: int = 128
    temperature: float = 1.0
    top_k: int = 0
    seed: int = 0
    eos_token_id: Optional[int] = None
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RolloutResponse:
    """Sequences [B, S+N] + per-row prompt lengths (so the trainer can
    split prompt/completion) + optional per-token logprobs."""

    sequences: np.ndarray
    prompt_lengths: np.ndarray
    logprobs: Optional[np.ndarray] = None
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def completions(self) -> List[np.ndarray]:
        return [seq[plen:] for seq, plen in
                zip(self.sequences, self.prompt_lengths)]


class RolloutEngine(abc.ABC):
    """Reference BaseRollout contract: generate + weight-sync lifecycle."""

    @abc.abstractmethod
    def generate(self, request: RolloutRequest) -> RolloutResponse:
        ...

    def sync_weights(self) -> None:
        """Refresh generation weights from the trainer (no-op when the
        implementation shares parameters)."""

    def shutdown(self) -> None:
        """Release generation resources."""
