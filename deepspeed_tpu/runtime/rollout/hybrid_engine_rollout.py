"""Rollout over the hybrid engine.

Reference: ``deepspeed/runtime/rollout/hybrid_engine_rollout.py:29``
(``HybridEngineRollout``) — the in-process rollout implementation: the
trainer's own weights generate, no weight transfer needed.
"""

from __future__ import annotations

import numpy as np

from deepspeed_tpu.runtime.rollout.base import (RolloutEngine,
                                                RolloutRequest,
                                                RolloutResponse)


class HybridEngineRollout(RolloutEngine):
    def __init__(self, hybrid_engine):
        self.hybrid_engine = hybrid_engine

    def generate(self, request: RolloutRequest) -> RolloutResponse:
        prompts = np.asarray(request.prompts, np.int32)
        if prompts.ndim == 1:
            prompts = prompts[None]
        seqs = self.hybrid_engine.generate(
            prompts, max_new_tokens=request.max_new_tokens,
            temperature=request.temperature, top_k=request.top_k,
            seed=request.seed, eos_token_id=request.eos_token_id)
        plens = np.full(prompts.shape[0], prompts.shape[1], np.int64)
        return RolloutResponse(sequences=np.asarray(seqs),
                               prompt_lengths=plens,
                               metadata=dict(request.metadata))

    def sync_weights(self) -> None:
        self.hybrid_engine._sync()
