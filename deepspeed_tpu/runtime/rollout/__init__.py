"""Rollout engines for RL training (reference: deepspeed/runtime/rollout/)."""

from deepspeed_tpu.runtime.rollout.base import (  # noqa: F401
    RolloutEngine,
    RolloutRequest,
    RolloutResponse,
)
from deepspeed_tpu.runtime.rollout.hybrid_engine_rollout import (  # noqa: F401
    HybridEngineRollout,
)
