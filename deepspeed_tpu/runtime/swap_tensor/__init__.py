"""NVMe tensor swapping for ZeRO-Infinity-style memory extension.

Reference: deepspeed/runtime/swap_tensor/ (AsyncTensorSwapper
async_swapper.py:19, AsyncPartitionedParameterSwapper
partitioned_param_swapper.py:37, PartitionedOptimizerSwapper
optimizer_utils.py/partitioned_optimizer_swapper.py:27). The device leg
is JAX host transfer; these managers own the host<->NVMe leg on the
native AIO library.
"""

from deepspeed_tpu.runtime.swap_tensor.swapper import (
    AsyncTensorSwapper, SwapBufferPool, TensorSwapStore)
