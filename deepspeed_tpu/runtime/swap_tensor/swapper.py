"""Async host<->NVMe swap of flat numpy buffers.

Reference mapping:
  * ``SwapBufferPool``   — pinned staging buffers
    (swap_tensor/utils.py SwapBufferPool/SwapBufferManager).
  * ``AsyncTensorSwapper`` — fire-and-forget swap-out of buffers with
    deferred completion (swap_tensor/async_swapper.py:19
    AsyncTensorSwapper: add_buffers/swap_out_tensors/
    wait_for_swapout... semantics).
  * ``TensorSwapStore`` — keyed store of named flat tensors on disk with
    swap_in/swap_out, used by the optimizer/param swappers
    (partitioned_optimizer_swapper.py:27, partitioned_param_swapper.py:37).

All byte counts are element counts × 4 (fp32) or × 2 (bf16); files are
one-tensor-per-file under a swap folder, like the reference's
``zero_stage_3`` swap layout.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.native.aio import (
    AsyncIOHandle, DEFAULT_BLOCK_SIZE, DEFAULT_QUEUE_DEPTH, DEFAULT_THREADS,
    PinnedBuffer)
from deepspeed_tpu.utils.logging import logger


class SwapBufferPool:
    """Fixed pool of pinned staging buffers (reference SwapBufferPool)."""

    def __init__(self, count: int, elems: int, dtype=np.float32):
        self.elems = elems
        self.dtype = np.dtype(dtype)
        self._buffers = [PinnedBuffer(elems * self.dtype.itemsize, dtype)
                         for _ in range(count)]
        self._free = list(range(count))

    def get(self) -> Tuple[int, np.ndarray]:
        if not self._free:
            raise RuntimeError("swap buffer pool exhausted")
        i = self._free.pop()
        return i, self._buffers[i].array

    def put(self, i: int) -> None:
        self._free.append(i)

    def available(self) -> int:
        return len(self._free)

    def free(self):
        for b in self._buffers:
            b.free()
        self._buffers = []
        self._free = []


class AsyncTensorSwapper:
    """Queue buffers for async swap-out; completion deferred to
    ``wait_for_swapout`` (reference async_swapper.py:19)."""

    def __init__(self, aio: Optional[AsyncIOHandle] = None):
        self.aio = aio or AsyncIOHandle()
        self._inflight: List[str] = []

    def swap_out(self, arr: np.ndarray, path: str) -> None:
        self.aio.async_pwrite(arr, path)
        self._inflight.append(path)

    def swap_in(self, arr: np.ndarray, path: str) -> None:
        self.aio.async_pread(arr, path)
        self._inflight.append(path)

    def wait(self) -> None:
        errs = self.aio.wait()
        inflight = self._inflight
        self._inflight = []
        if errs:
            # the native layer reports a count, not which request failed —
            # list the whole in-flight set for diagnosis
            raise IOError(f"tensor swap failed: {errs} of {len(inflight)} "
                          f"requests errored (in-flight: {inflight})")


class TensorSwapStore:
    """Named flat tensors swapped to one file each under ``folder``.

    The optimizer swapper (runtime/offload.py) registers each state
    buffer once, then brackets the host step with swap_in/swap_out.
    Reads/writes within one request are parallelized across the AIO
    worker pool; ``sync=False`` swap-outs let the caller overlap the next
    shard's compute with the write-back.
    """

    def __init__(self, folder: str, aio: Optional[AsyncIOHandle] = None):
        self.folder = folder
        os.makedirs(folder, exist_ok=True)
        self.aio = aio or AsyncIOHandle()
        self._meta: Dict[str, Tuple[int, np.dtype]] = {}

    def _path(self, name: str) -> str:
        safe = name.replace("/", "_").replace(".", "_")
        return os.path.join(self.folder, f"{safe}.swp")

    def register(self, name: str, arr: np.ndarray) -> None:
        """Initial swap-out; afterwards the host copy may be dropped."""
        self._meta[name] = (arr.size, arr.dtype)
        self.aio.async_pwrite(arr, self._path(name))

    def contains(self, name: str) -> bool:
        return name in self._meta

    def swap_in(self, name: str, out: Optional[np.ndarray] = None,
                sync: bool = True) -> np.ndarray:
        size, dtype = self._meta[name]
        if out is None:
            out = np.empty(size, dtype)
        assert out.size == size and out.dtype == dtype
        self.aio.async_pread(out, self._path(name))
        if sync:
            self._wait()
        return out

    def swap_out(self, name: str, arr: np.ndarray, sync: bool = False) -> None:
        self._meta[name] = (arr.size, arr.dtype)
        self.aio.async_pwrite(arr, self._path(name))
        if sync:
            self._wait()

    def wait(self) -> None:
        self._wait()

    def _wait(self):
        errs = self.aio.wait()
        if errs:
            raise IOError(f"swap store I/O failed ({errs} errors)")

    def nbytes(self) -> int:
        return sum(s * np.dtype(d).itemsize for s, d in self._meta.values())

    def purge(self) -> None:
        for name in self._meta:
            try:
                os.unlink(self._path(name))
            except OSError:
                pass
        self._meta.clear()
