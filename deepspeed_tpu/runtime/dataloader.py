"""Data loading.

Analog of the reference's ``DeepSpeedDataLoader`` + ``RepeatingLoader``
(runtime/dataloader.py) without a torch dependency: batches are numpy
pytrees; each host loads only its process's slice of the global batch and
the engine assembles the global sharded array
(jax.make_array_from_process_local_data).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from deepspeed_tpu.utils.logging import logger


def default_collate(samples: Sequence[Any]):
    """Stack a list of sample pytrees into a batch pytree."""
    import jax

    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                        *samples)


class DeepSpeedDataLoader:
    """Batches an indexable or iterable dataset for this host.

    With multiple processes, each host reads its contiguous shard of the
    sample space (data-parallel sharding, reference
    DistributedSampler-equivalent behavior in runtime/dataloader.py).
    """

    def __init__(self, dataset, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True):
        import jax

        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0
        self._num_procs = jax.process_count()
        self._proc_id = jax.process_index()
        self._warned_stream_shuffle = False
        try:
            self._len = len(dataset)
        except TypeError:
            self._len = None

    def __len__(self):
        if self._len is None:
            raise TypeError("iterable dataset has no length")
        per_proc = self._len // self._num_procs
        n = per_proc // self.batch_size
        if not self.drop_last and per_proc % self.batch_size:
            n += 1
        return n

    def set_epoch(self, epoch: int):
        self._epoch = epoch

    # -- resume (resilience/resume.py; docs/resilience.md) --------------
    # Iteration order is a pure function of (seed, epoch): restoring the
    # epoch and replaying the intra-epoch offset reproduces the exact
    # remaining batch stream.
    def state_dict(self):
        return {"epoch": int(self._epoch), "seed": int(self.seed),
                "shuffle": bool(self.shuffle)}

    def load_state_dict(self, sd) -> None:
        if int(sd.get("seed", self.seed)) != int(self.seed):
            logger.warning(
                f"dataloader resume: checkpoint seed {sd.get('seed')} != "
                f"configured seed {self.seed} — the replayed batch "
                "stream will differ from the original run")
        self.set_epoch(int(sd.get("epoch", 0)))

    def __iter__(self) -> Iterator:
        if self._len is None:
            if self.shuffle and not self._warned_stream_shuffle:
                self._warned_stream_shuffle = True
                logger.warning(
                    "shuffle=True is ignored for a length-less iterable "
                    "dataset: samples stream in the order the dataset "
                    "yields them (shuffle inside the dataset, or provide "
                    "__len__ + __getitem__ for index shuffling)")
            return self._iter_stream()
        return self._iter_indexed()

    def _iter_indexed(self):
        idx = np.arange(self._len)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        per_proc = self._len // self._num_procs
        idx = idx[self._proc_id * per_proc:(self._proc_id + 1) * per_proc]
        end = per_proc - (per_proc % self.batch_size) if self.drop_last else per_proc
        for start in range(0, end, self.batch_size):
            chunk = idx[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in chunk])

    def _iter_stream(self):
        buf = []
        for i, sample in enumerate(self.dataset):
            if i % self._num_procs != self._proc_id:
                continue
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.collate_fn(buf)


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference
    runtime/dataloader.py RepeatingLoader)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self._epoch = 0
        self._offset = 0  # batches yielded since the last epoch restart

    def __iter__(self):
        return self

    # -- resume (resilience/resume.py; docs/resilience.md) --------------
    def state_dict(self):
        sd = {"epoch": int(self._epoch),
              "offset_batches": int(self._offset)}
        if hasattr(self.loader, "state_dict"):
            sd["loader"] = self.loader.state_dict()
        return sd

    def load_state_dict(self, sd) -> None:
        """Restore epoch position and restart the inner iterator; the
        caller (resume_data_iter) then replays ``offset_batches`` pulls
        to land on the first unconsumed batch."""
        if "loader" in sd and hasattr(self.loader, "load_state_dict"):
            self.loader.load_state_dict(sd["loader"])
        self._epoch = int(sd.get("epoch", 0))
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(self._epoch)
        self.data_iter = iter(self.loader)
        self._offset = 0

    def __next__(self):
        try:
            batch = next(self.data_iter)
            self._offset += 1
            return batch
        except StopIteration:
            self._epoch += 1
            self._offset = 0
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(self._epoch)
            self.data_iter = iter(self.loader)
            try:
                batch = next(self.data_iter)
                self._offset += 1
                return batch
            except StopIteration:
                # a restart that immediately exhausts means the wrapped
                # loader yields nothing — restarting again would spin
                # forever, so fail loudly instead
                raise ValueError(
                    "RepeatingLoader: loader produced no batches (the "
                    "wrapped loader's iterator was empty after a "
                    "restart)") from None
