"""ZeRO++ qgZ at stage 3: quantized gradient reduction for the GSPMD path.

Reference: ``all_to_all_quant_reduce`` (runtime/comm/coalesced_collectives.py:31)
reduces stage-3 gradients with a hierarchical quantized all-to-all — int8
within a node, int4 across nodes (kernels csrc/quantization/quant_reduce.cu)
— instead of a full-width reduce-scatter. Gradient reduction is the
bandwidth bottleneck qgZ exists for; this module is its TPU expression.

GSPMD can't quantize the collectives it inserts itself, so the trick is to
never let it insert one: the engine computes **per-group gradients** (one
group per batch shard, via ``jax.vmap`` over a reshaped batch) so the
cross-shard sum is still explicit as a [G, ...] group axis, then this
module reduces that axis with the wire quantized:

  1. reshape groups [G, ...] → [dp, fsdp, ...] (dp-major, matching the
     mesh order of the batch sharding);
  2. blockwise int8 quantize (local op — each device holds its own
     group's full-width grad);
  3. **reshard** the int8 payload so the fsdp mesh axis moves from the
     group dim onto the parameter's fsdp-sharded dim — GSPMD lowers a
     sharding transpose to an all-to-all, so the wire is s8 (the HLO
     test asserts this);
  4. dequantize + sum the in-group axis locally in fp32;
  5. when dp > 1, repeat over dp at ``level2_bits`` (int4 by default,
     mirroring the reference's inter-node precision) — the hierarchical
     second level;
  6. constrain to the engine's grad sharding (fsdp on the partition dim).

Accuracy contract matches the reference: quantization noise bounded by
per-block scales, exact in expectation (round-to-nearest, symmetric).
Loss-weighting semantics: groups average uniformly (1/G), i.e. each
batch shard's *mean* loss counts equally — the same per-rank-mean
averaging torch DDP and the reference's data-parallel reduction use.
With uneven loss_mask populations across shards this differs from the
engine's exact path, which normalizes by the global token count per
microbatch; the divergence is zero for unmasked LM batches (equal
tokens per shard) and bounded by the shard-count imbalance otherwise.
Memory note: per-group grads are full-width on each device until step 3 —
the same transient an unquantized unreduced gradient occupies; qgZ trades
that for 2-4x less reduction wire, its purpose on DCN-bound meshes.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

QGZ_BLOCK = 256


def _axes_of(entry):
    if entry is None:
        return ()
    return (entry,) if isinstance(entry, str) else tuple(entry)


def _without(entry, axis):
    kept = tuple(a for a in _axes_of(entry) if a != axis)
    return kept[0] if len(kept) == 1 else (kept or None)


def _with(entry, axis):
    axes = _axes_of(entry) + (axis,)
    return axes[0] if len(axes) == 1 else axes


def _quant(g, block_axis: int, block: int, bits: int):
    """Blockwise symmetric quantize along ``block_axis`` → (q, scales).

    q is int8 or int4 (jnp casts clamp); scales are fp32 with the block
    dim kept so both reshard with the same spec.
    """
    n = g.shape[block_axis]
    blocked = g.shape[:block_axis] + (n // block, block) + g.shape[block_axis + 1:]
    f = g.reshape(blocked)
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.max(jnp.abs(f), axis=block_axis + 1, keepdims=True) / qmax
    s = jnp.where(s == 0.0, 1.0, s)
    dtype = jnp.int4 if bits == 4 else jnp.int8
    q = jnp.round(f / s).astype(dtype)
    return q, s, blocked


def _blocked_spec(entries, block_axis: int):
    """Spec for the blocked layout: the block dim splits ``block_axis``
    into (n_blocks, block); sharding stays on the n_blocks half."""
    return list(entries[:block_axis + 1]) + [None] + list(entries[block_axis + 1:])


def _reduce_leaf(g, out_sharding: NamedSharding, mesh, dp: int, fsdp: int,
                 ep: int, bits1: int, bits2: Optional[int]):
    """g: [G, *shape] fp32 per-group grads, G = dp*fsdp*ep
    (BATCH_AXES-major: dp, fsdp, ep). Returns the reduced grad [*shape]
    constrained to ``out_sharding``.

    Levels, in order (each moves one token-group axis onto the payload
    dim that axis shards in ``out_sharding`` — GSPMD lowers the sharding
    transpose to an all-to-all, so the wire is the quantized payload):

      1. fsdp @ bits1 (int8) → the fsdp-partitioned dim.
      2. ep   @ bits1 (int8) → the expert-stacked dim, when the leaf has
         one (expert-dim-aware grouping: each ep shard receives exactly
         its own experts' gradient slices — the expert-grad counterpart
         of the reference's all_to_all_quant_reduce,
         coalesced_collectives.py:31). Dense leaves on an ep mesh have
         no ep dim; their ep group folds into the replica level below.
      3. remaining replica axes (dp, and ep for dense leaves) @ bits2
         (int4 by default — the reference's inter-node precision).
    """
    G = dp * fsdp * ep
    shape = g.shape[1:]
    nd = len(shape)
    out_entries = list(out_sharding.spec) + [None] * (nd
                                                      - len(out_sharding.spec))

    # the dim the engine partitions grads over (fsdp from FSDP_RULES)
    part_dim = next((i for i, e in enumerate(out_entries)
                     if "fsdp" in _axes_of(e)), None)
    # the expert-stacked dim (EP_RULES) — target of the ep group level
    exp_dim = next((i for i, e in enumerate(out_entries)
                    if "ep" in _axes_of(e)), None)

    # residual sharding of the payload dims while group levels run
    # (fsdp/ep re-land on their dims level by level; tp/pp stay put)
    payload = [_without(_without(e, "fsdp"), "ep") for e in out_entries]

    # block along the last dim; blocks must tile within every sharding
    # layout the payload passes through
    div = 1
    for a in _axes_of(out_entries[-1]):
        div *= mesh.shape.get(a, 1)
    last = shape[-1]
    block = math.gcd(last // div, QGZ_BLOCK) if last % max(div, 1) == 0 else 1
    exact = part_dim is None or block <= 1

    g = g.reshape(dp, fsdp, ep, *shape)
    g = lax.with_sharding_constraint(
        g, NamedSharding(mesh, P("dp", "fsdp", "ep", *payload)))

    if exact:
        # nothing to win (unpartitioned or unblockable leaf — 1-D norm
        # scales and friends): exact f32 reduction, tiny bytes
        red = jnp.sum(g, axis=(0, 1, 2)) / G
        return lax.with_sharding_constraint(red, out_sharding)

    groups = ["dp", "fsdp", "ep"]  # leading group dims of g

    def move_level(g, names, target_dim, bits):
        """Quantize, a2a the named group dims onto ``target_dim``, and
        sum them out. g: [*groups, *shape]; returns [*groups', *shape]."""
        ng = len(groups)
        kept_sizes = tuple(g.shape[i] for i, a in enumerate(groups)
                           if a not in names)
        block_axis = ng + nd - 1
        q, s, _ = _quant(g, block_axis, block, bits)
        from_spec = _blocked_spec(list(groups) + payload, block_axis)
        to_groups = [None if a in names else a for a in groups]
        to_payload = list(payload)
        ent = payload[target_dim]
        for a in names:
            ent = _with(ent, a)
        to_payload[target_dim] = ent
        to_spec = _blocked_spec(to_groups + to_payload, block_axis)
        from deepspeed_tpu.comm import comm as _comm

        q = lax.with_sharding_constraint(q, NamedSharding(mesh, P(*from_spec)))
        s = lax.with_sharding_constraint(s, NamedSharding(mesh, P(*from_spec)))
        # the to_spec constraints ARE the a2a wire (GSPMD lowers the
        # axis move to all-to-all); traced_span accounts the int8/int4
        # payload + fp32 scale bytes — wire, not logical — in the
        # comms logger, flight ring, and Perfetto comm lanes
        tag = "+".join(names)
        with _comm.traced_span("all_to_all", q, tuple(names),
                               f"qgz_{tag}_int{bits}"):
            q = lax.with_sharding_constraint(
                q, NamedSharding(mesh, P(*to_spec)))
        with _comm.traced_span("all_to_all", s, tuple(names),
                               f"qgz_{tag}_scales"):
            s = lax.with_sharding_constraint(
                s, NamedSharding(mesh, P(*to_spec)))
        idxs = tuple(i for i, a in enumerate(groups) if a in names)
        out = (q.astype(jnp.float32) * s).sum(axis=idxs)
        payload[target_dim] = to_payload[target_dim]
        for a in names:
            groups.remove(a)
        return out.reshape(kept_sizes + shape)

    # ---- level 1: int8 all-to-all over fsdp ---------------------------
    if fsdp > 1:
        g = move_level(g, ["fsdp"], part_dim, bits1)
    else:
        g = g.sum(axis=groups.index("fsdp")).reshape(
            (dp, ep) + shape)
        groups.remove("fsdp")

    # ---- level 2: int8 all-to-all over ep → the expert dim ------------
    if ep > 1 and exp_dim is not None:
        g = move_level(g, ["ep"], exp_dim, bits1)

    # ---- level 3: remaining replica axes at bits2 ---------------------
    rem = [a for a in list(groups) if mesh.shape.get(a, 1) > 1]
    if rem and bits2:
        g = move_level(g, rem, part_dim, bits2)
    red = g.sum(axis=tuple(range(len(groups)))).reshape(shape) / G

    return lax.with_sharding_constraint(red, out_sharding)


def qgz_reduce_tree(g_groups, grad_shardings, mesh, bits1: int = 8,
                    bits2: Optional[int] = 4):
    """Reduce a tree of per-group gradients [G, *shape] → [*shape] with
    quantized wire. ``grad_shardings``: matching tree of NamedShardings
    (the engine's grad plan)."""
    dp = mesh.shape.get("dp", 1)
    fsdp = mesh.shape.get("fsdp", 1)
    ep = mesh.shape.get("ep", 1)
    return jax.tree.map(
        lambda g, sh: _reduce_leaf(g, sh, mesh, dp, fsdp, ep, bits1, bits2),
        g_groups, grad_shardings)
