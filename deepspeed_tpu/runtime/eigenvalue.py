"""Block-wise Hessian eigenvalue estimation (MoQ scheduling signal).

Reference: ``deepspeed/runtime/eigenvalue.py:153`` (``Eigenvalue``) —
power iteration on the loss curvature per layer block; the
mixture-of-quantization scheduler uses the eigenvalue ratio to decide
which layers can drop precision earlier.

TPU-native: Hessian-vector products come from ``jax.jvp`` over
``jax.grad`` (forward-over-reverse), compiled by XLA; power iteration is
a ``lax.fori``-style Python loop over compiled HVPs (iteration counts
are small and static).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _tree_dot(a, b) -> jax.Array:
    return sum(jnp.vdot(x, y) for x, y in
               zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _tree_norm(a) -> jax.Array:
    return jnp.sqrt(_tree_dot(a, a).real)


def _normalize(a):
    n = _tree_norm(a) + 1e-12
    return jax.tree.map(lambda x: x / n, a)


class Eigenvalue:
    """Power-iteration top Hessian eigenvalue per parameter block.

    Reference constructor knobs (verbose/max_iter/tol/stability/
    gas_boundary_resolution/layer_name/layer_num) map onto max_iter/tol
    here; blocks are top-level pytree keys instead of module-name
    prefixes.
    """

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 seed: int = 0):
        self.verbose = verbose
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.stability = float(stability)
        self.seed = seed

    def compute_eigenvalue(self, loss_fn: Callable, params,
                           block: Optional[str] = None) -> float:
        """Top eigenvalue of the Hessian of ``loss_fn(params)`` restricted
        to ``block`` (a top-level key) or the full tree."""
        if block is not None:
            sub = params[block]

            def f(sub_p):
                return loss_fn({**params, block: sub_p})
        else:
            sub, f = params, loss_fn

        grad_fn = jax.grad(f)

        @jax.jit
        def hvp(v):
            return jax.jvp(grad_fn, (sub,), (v,))[1]

        key = jax.random.PRNGKey(self.seed)
        leaves, treedef = jax.tree.flatten(sub)
        keys = jax.random.split(key, len(leaves))
        v = jax.tree.unflatten(treedef, [
            jax.random.normal(k, x.shape, jnp.float32)
            for k, x in zip(keys, leaves)])
        v = _normalize(v)

        eig_prev = 0.0
        for i in range(self.max_iter):
            hv = hvp(v)
            eig = float(_tree_dot(v, hv).real)
            v = _normalize(hv)
            if abs(eig - eig_prev) < self.tol * max(abs(eig), self.stability):
                break
            eig_prev = eig
        if self.verbose:
            print(f"eigenvalue[{block or 'all'}]: {eig:.4e} ({i + 1} iters)")
        return eig

    def compute_eigenvalues(self, loss_fn: Callable, params
                            ) -> Dict[str, float]:
        """Per-top-level-block eigenvalues (reference returns per-layer
        list used by the MoQ schedule)."""
        return {k: self.compute_eigenvalue(loss_fn, params, block=k)
                for k in params}
