"""1-bit compressed-communication optimizers (1-bit Adam family).

Reference: deepspeed/runtime/fp16/onebit/{adam.py:14 OnebitAdam,
zoadam.py:14 ZeroOneAdam, lamb.py:16 OnebitLamb} with the compressed
allreduce backends in runtime/comm/{nccl,compressed}.py
(NcclBackend.compressed_allreduce: sign-compress with per-tensor scale +
per-worker error feedback, allreduce the 1-bit representation).

Algorithm (1-bit Adam, Tang et al.): a full-precision *warmup* phase runs
plain Adam; at ``freeze_step`` the variance term freezes and from then on
only the momentum is communicated, sign-compressed with error feedback —
a 32x reduction in gradient-sync volume.

TPU-native expression: the engine's normal path lets GSPMD insert the
gradient reduction, which leaves nothing to compress. Here the
forward/backward runs inside a ``jax.shard_map`` that is MANUAL over the
dp axis only (``axis_names={'dp'}``; tp/sp stay under GSPMD), so the
per-rank local gradients are visible, and the compressed allreduce is an
explicit ``lax.pmean`` of ``sign(x) * scale`` — riding ICI, with the
error-feedback buffer carried as a per-rank state (leading dp axis).

Constraints (same as the reference's): ZeRO stage <= 1, no optimizer
offload; masters/moments are replicated over dp.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils import jaxcompat

ONEBIT_OPTIMIZERS = ("onebitadam", "zerooneadam", "onebitlamb")


class OneBitState(NamedTuple):
    master: Any   # fp32 master params (replicated over dp)
    m: Any        # momentum (replicated)
    v: Any        # variance (frozen after freeze_step)
    error: Any    # per-rank error feedback, leaves [dp, *shape]
    step: jax.Array


def _tree_zeros_like(tree):
    return jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), tree)


def parse_onebit_params(name: str, params: Dict) -> Dict:
    p = dict(params or {})
    out = {
        "kind": name,
        "lr": p.pop("lr", 1e-3),
        "betas": tuple(p.pop("betas", (0.9, 0.999))),
        "eps": p.pop("eps", 1e-8),
        "weight_decay": p.pop("weight_decay", 0.0),
        "freeze_step": p.pop("freeze_step", 100),
        # zerooneadam: variance refresh interval during compression
        # (reference var_update_scaler zoadam.py; deviation documented in
        # build_onebit_step)
        "var_update_interval": p.pop("var_update_interval", 16),
        # onebitlamb: trust-ratio clamp (reference lamb.py coeff bounds)
        "max_coeff": p.pop("max_coeff", 10.0),
        "min_coeff": p.pop("min_coeff", 0.01),
    }
    p.pop("cuda_aware", None)
    p.pop("comm_backend_name", None)
    return out


def build_onebit_step(model, mesh, cfg, opt: Dict, param_shardings,
                      lr_schedule: Optional[Callable]):
    """Returns (init_fn(rng) -> (params, OneBitState),
    step_fn(params, state, batches) -> (params, state, metrics))."""
    gas = cfg.gradient_accumulation_steps
    cdt = cfg.compute_dtype
    beta1, beta2 = opt["betas"]
    eps = opt["eps"]
    wd = opt["weight_decay"]
    freeze_step = opt["freeze_step"]
    kind = opt["kind"]
    base_lr = opt["lr"]
    grad_clip = cfg.gradient_clipping

    dp = mesh.shape.get("dp", 1)

    def init_fn(rng):
        p32 = model.init(rng)
        p32 = jax.tree.map(lambda x: x.astype(jnp.float32), p32)
        params = jax.tree.map(lambda x: x.astype(cdt), p32)
        m = _tree_zeros_like(p32)
        v = _tree_zeros_like(p32)
        error = jax.tree.map(
            lambda x: jnp.zeros((dp,) + x.shape, jnp.float32), p32)
        return params, OneBitState(p32, m, v, error,
                                   jnp.asarray(0, jnp.int32))

    def local_grads(params, batches, m, error, step):
        """MANUAL over dp: local grads -> compressed/full momentum sync.
        batches leaves: [gas, B/dp, ...]; error leaves [1, *shape]."""
        from deepspeed_tpu.runtime import sharding as shard_lib

        # trace-time: the model's sharding constraints reference mesh axes
        # that are manual inside this shard_map region
        with shard_lib.disable_constraints():
            return _local_grads_inner(params, batches, m, error, step)

    def _local_grads_inner(params, batches, m, error, step):
        def total_loss(p):
            def body(carry, mb):
                loss, _aux = model.loss(p, mb)
                return carry + loss / gas, loss

            total, losses = lax.scan(body, jnp.asarray(0.0, jnp.float32),
                                     batches)
            return total, losses

        (_, losses), grads = jax.value_and_grad(
            total_loss, has_aux=True)(params)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)

        # candidate momentum from LOCAL grads
        m_cand = jax.tree.map(lambda mm, g: beta1 * mm + (1 - beta1) * g,
                              m, grads)

        def warmup(_):
            g_avg = jax.tree.map(lambda g: lax.pmean(g, "dp"), grads)
            m_new = jax.tree.map(lambda mm, g: beta1 * mm + (1 - beta1) * g,
                                 m, g_avg)
            return m_new, error, g_avg

        def compressed(_):
            def comp_leaf(mc, e):
                c_in = mc + e[0]
                scale = jnp.mean(jnp.abs(c_in))
                comp = jnp.sign(c_in) * scale
                m_new = lax.pmean(comp, "dp")
                new_e = (c_in - comp)[None]
                return m_new, new_e

            treedef = jax.tree.structure(m_cand)
            m_list, e_list = [], []
            for mc, e in zip(jax.tree.leaves(m_cand), jax.tree.leaves(error)):
                mn, ne = comp_leaf(mc, e)
                m_list.append(mn)
                e_list.append(ne)
            m_new = jax.tree.unflatten(treedef, m_list)
            new_e = jax.tree.unflatten(treedef, e_list)
            g_zero = _tree_zeros_like(m_cand)
            return m_new, new_e, g_zero

        m_new, new_error, g_avg = lax.cond(step < freeze_step, warmup,
                                           compressed, operand=None)
        loss_avg = lax.pmean(jnp.mean(losses), "dp")
        return m_new, new_error, g_avg, loss_avg

    batch_spec = P(None, "dp")
    rep = P()

    def step_fn(params, state: OneBitState, batches, lr_override=None):
        """lr_override: fp32 scalar operand; NaN = use the traced
        schedule (the engine's set_lr without a rebuild — same runtime-lr
        technique as the ZeRO++ step, runtime/zeropp.py)."""
        step = state.step
        err_specs = jax.tree.map(lambda _: P("dp"), state.error)
        batch_specs = jax.tree.map(lambda _: batch_spec, batches)

        sm = jaxcompat.shard_map(
            partial(local_grads),
            mesh=mesh, axis_names={"dp"},
            in_specs=(rep, batch_specs, rep, err_specs, rep),
            out_specs=(rep, err_specs, rep, rep),
            check_vma=False)
        m_new, new_error, g_avg, loss = sm(params, batches, state.m,
                                           state.error, step)

        in_warmup = step < freeze_step
        # variance: updated in warmup, frozen after (zerooneadam: also
        # refreshed every var_update_interval steps from |m| as a proxy —
        # documented deviation from the reference's local-step schedule,
        # comm volume matches 1-bit Adam)
        def v_warm(v, g):
            return beta2 * v + (1 - beta2) * g * g

        if kind == "zerooneadam":
            refresh = (step % opt["var_update_interval"] == 0)
            v_new = jax.tree.map(
                lambda v, g, mm: jnp.where(
                    in_warmup, v_warm(v, g),
                    jnp.where(refresh, beta2 * v + (1 - beta2) * mm * mm, v)),
                state.v, g_avg, m_new)
        else:
            v_new = jax.tree.map(
                lambda v, g: jnp.where(in_warmup, v_warm(v, g), v),
                state.v, g_avg)

        lr = (lr_schedule(step) if lr_schedule is not None
              else jnp.asarray(base_lr, jnp.float32))
        if lr_override is not None:
            lr = jnp.where(jnp.isnan(lr_override), lr, lr_override)

        bc1 = 1 - beta1 ** (step.astype(jnp.float32) + 1)
        bc2 = 1 - beta2 ** (step.astype(jnp.float32) + 1)

        def upd_leaf(master, mm, vv):
            update = (mm / bc1) / (jnp.sqrt(vv / bc2) + eps)
            if wd:
                update = update + wd * master
            return update

        updates = jax.tree.map(upd_leaf, state.master, m_new, v_new)

        gnorm = jnp.sqrt(sum(jnp.sum(u.astype(jnp.float32) ** 2)
                             for u in jax.tree.leaves(updates)))
        coef = jnp.asarray(1.0, jnp.float32)
        if grad_clip:
            coef = jnp.minimum(1.0, grad_clip / (gnorm + 1e-6))

        if kind == "onebitlamb":
            # layerwise trust ratio (reference lamb.py fused coefficients)
            def lamb_scale(master, u):
                wn = jnp.sqrt(jnp.sum(master.astype(jnp.float32) ** 2))
                un = jnp.sqrt(jnp.sum(u.astype(jnp.float32) ** 2))
                ratio = jnp.where(un > 0, wn / (un + 1e-12), 1.0)
                return jnp.clip(ratio, opt["min_coeff"], opt["max_coeff"])

            master_new = jax.tree.map(
                lambda master, u: master - lr * coef * lamb_scale(master, u) * u,
                state.master, updates)
        else:
            master_new = jax.tree.map(
                lambda master, u: master - lr * coef * u,
                state.master, updates)

        params_new = jax.tree.map(lambda mm: mm.astype(cdt), master_new)
        metrics = {"loss": loss, "lr": lr, "grad_norm": gnorm,
                   "loss_scale": jnp.asarray(1.0),
                   "overflow": jnp.asarray(False),
                   "compressed": ~in_warmup}
        return params_new, OneBitState(master_new, m_new, v_new, new_error,
                                       step + 1), metrics

    return init_fn, step_fn


def validate_onebit_config(cfg) -> None:
    if cfg.zero_optimization.stage > 1:
        raise ValueError(
            f"1-bit optimizers require ZeRO stage <= 1 (reference "
            f"onebit/adam.py constraint), got stage="
            f"{cfg.zero_optimization.stage}")
    off = cfg.zero_optimization.offload_optimizer
    if off is not None and (off.device or "none") != "none":
        raise ValueError("1-bit optimizers are incompatible with "
                         "optimizer offload")
