"""ZeRO-Offload / ZeRO-Infinity equivalent: host (+NVMe) optimizer states.

Reference semantics (runtime/zero/offload_config.py, stage_1_and_2.py
cpu_offload path, stage3.py offload_optimizer + swap_tensor/*): fp32
master weights and optimizer moments live on the host (or NVMe); the
device computes grads in compute dtype; each boundary the grads' local
partition is copied host-side, the vectorized native CPU optimizer
(ops/native/cpu_optimizer.py, reference csrc/adam/cpu_adam.cpp) steps the
flat shard, and the updated compute-dtype shard is uploaded back.

Partitioning falls out of the grad/param sharding plan: each process
updates exactly the UNIQUE addressable shards of every leaf (dedup by
shard.index — replicas along tp/sp axes are uploaded to every holder but
stepped once), which is precisely the ZeRO partition of the local host.

NVMe tier: with ``offload_optimizer.device == "nvme"`` the fp32 master +
moments of each shard live in a TensorSwapStore (native AIO) and are
swapped in/out around that shard's step (moments are detached from RAM
after swap-out), so resident optimizer state is bounded at one shard;
the fetched gradient shards are still all host-resident within a step
(reference: PartitionedOptimizerSwapper partitioned_optimizer_swapper.py:27).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.ops.native.builder import build_native_lib
from deepspeed_tpu.ops.native.cpu_optimizer import (
    CPU_OPTIMIZERS, CPUAdam, bf16_to_f32, f32_to_bf16)
from deepspeed_tpu.runtime.swap_tensor.swapper import TensorSwapStore
from deepspeed_tpu.utils import memspace
from deepspeed_tpu.utils.logging import log_dist, logger

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None


def _leaf_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(kp) for kp, _ in flat]
    leaves = [v for _, v in flat]
    return paths, leaves, treedef


def _index_key(index) -> str:
    return repr(index)


def _pinned_single_device(device):
    """Single-device pinned-host sharding, degrading to plain device
    placement on backends without a pinned-host space (CPU sim)."""
    from jax.sharding import SingleDeviceSharding

    if not memspace.memories_supported():
        return SingleDeviceSharding(device)
    return SingleDeviceSharding(device, memory_kind="pinned_host")


def _to_f32(host: np.ndarray) -> np.ndarray:
    if _BF16 is not None and host.dtype == _BF16:
        return bf16_to_f32(host.view(np.uint16)).reshape(host.shape)
    return np.ascontiguousarray(host, np.float32)


class HostOffloadOptimizer:
    """Owns host-resident fp32 master params + moments for every unique
    local shard; steps them with the native CPU optimizer."""

    def __init__(self, params, optimizer_name: str = "adamw",
                 optimizer_params: Optional[dict] = None,
                 compute_dtype=None, grad_clip: float = 0.0,
                 nvme_path: Optional[str] = None,
                 host_memory_leaf_prefixes: Tuple[str, ...] = ()):
        # leaves whose path starts with one of these prefixes are uploaded
        # into pinned HOST memory instead of HBM (ZeRO-Infinity
        # offload_param pairing: the engine streams them per layer)
        self.host_memory_leaf_prefixes = tuple(host_memory_leaf_prefixes)
        optimizer_params = dict(optimizer_params or {})
        self.lr = float(optimizer_params.pop("lr", 1e-3))
        name = optimizer_name.lower()
        if name in ("adam", "adamw"):
            optimizer_params.setdefault("adamw_mode", name == "adamw")
        self._opt_cls = CPU_OPTIMIZERS.get(name)
        if self._opt_cls is None:
            raise ValueError(
                f"host offload supports {sorted(CPU_OPTIMIZERS)}, got {name!r}")
        self._opt_kwargs = optimizer_params
        self.grad_clip = grad_clip
        self.compute_dtype = compute_dtype

        self._swap: Optional[TensorSwapStore] = None
        if nvme_path:
            folder = os.path.join(nvme_path, f"dstpu_opt_swap_{os.getpid()}",
                                  f"rank{jax.process_index()}")
            self._swap = TensorSwapStore(folder)

        # masters[(leaf_path, index_key)] = fp32 flat buffer (or None when
        # swapped out); optimizers keyed the same.
        self.masters: Dict[Tuple[str, str], Optional[np.ndarray]] = {}
        self.optimizers: Dict[Tuple[str, str], object] = {}
        self._shard_shapes: Dict[Tuple[str, str], tuple] = {}
        self._owned_cache: Optional[set] = None
        self._init_from_params(params)
        n = sum(o.n for o in self.optimizers.values())
        where = "nvme" if self._swap else "cpu"
        log_dist(f"host offload optimizer: {len(self.optimizers)} shards, "
                 f"{n/1e6:.1f}M local elements on {where}", ranks=[0])

    # ------------------------------------------------------------------
    def _init_from_params(self, params) -> None:
        paths, leaves, _ = _leaf_paths(params)
        # global layout of the optimizer partition, for rebuilds after load
        self._leaf_layout: Dict[str, Tuple[tuple, object]] = {}
        self._shard_index: Dict[Tuple[str, str], tuple] = {}
        for path, leaf in zip(paths, leaves):
            self._leaf_layout[path] = (leaf.shape, leaf.sharding)
            for shard in leaf.addressable_shards:
                key = (path, _index_key(shard.index))
                if key in self.masters:
                    continue
                self._shard_index[key] = shard.index
                host = np.asarray(shard.data)
                master = _to_f32(host).reshape(-1).copy()
                self._shard_shapes[key] = host.shape
                opt = self._opt_cls(master.size, lr=self.lr, **self._opt_kwargs)
                self.optimizers[key] = opt
                if self._swap is not None:
                    self._swap.register(f"{path}.{_index_key(shard.index)}.master",
                                        master)
                    self.masters[key] = None
                else:
                    self.masters[key] = master
        if self._swap is not None:
            # moments start as zeros; register lazily at first swap-out
            self._swap.wait()

    # ------------------------------------------------------------------
    def _swap_in(self, key) -> np.ndarray:
        path, idx = key
        master = self._swap.swap_in(f"{path}.{idx}.master")
        opt = self.optimizers[key]
        sd = opt.state_dict()  # (re)allocates moment buffers via ensure_state
        for name in sd:
            if name == "step":
                continue
            sname = f"{path}.{idx}.{name}"
            if self._swap.contains(sname):
                self._swap.swap_in(sname, out=sd[name])
        return master

    def _swap_out(self, key, master: np.ndarray) -> None:
        path, idx = key
        self._swap.swap_out(f"{path}.{idx}.master", master)
        sd = self.optimizers[key].state_dict()
        for name, arr in sd.items():
            if name == "step":
                continue
            self._swap.swap_out(f"{path}.{idx}.{name}", arr)
        self._swap.wait()
        # bound host RAM: moments live on NVMe between steps
        self.optimizers[key].detach_state()

    # ------------------------------------------------------------------
    def _owned_keys(self, g_paths, g_leaves) -> set:
        """Keys of shards this process owns for grad-norm accounting (the
        lowest (process_index, device_id) replica). Static for a fixed
        sharding — computed once and cached."""
        if self._owned_cache is not None:
            return self._owned_cache
        my_proc = jax.process_index()
        owned = set()
        for path, gleaf in zip(g_paths, g_leaves):
            idx_map = gleaf.sharding.devices_indices_map(gleaf.shape)
            owner: Dict[str, Tuple[int, int]] = {}
            for device, index in idx_map.items():
                k = _index_key(index)
                cand = (device.process_index, device.id)
                if k not in owner or cand < owner[k]:
                    owner[k] = cand
            for k, (proc, _dev) in owner.items():
                if proc == my_proc:
                    owned.add((path, k))
        self._owned_cache = owned
        return owned

    def step(self, grads, params, lr: Optional[float] = None,
             grad_scale: Optional[float] = None,
             skip_on_nonfinite: bool = False):
        """Apply one update; returns (new_cdt_tree, grad_norm, overflow).

        ``grads`` must carry the optimizer (fully-sharded) sharding — its
        shard layout IS the ZeRO partition this host owns. The returned
        tree carries the same sharding in compute dtype; the engine
        reshards it to the param sharding under jit, which is exactly the
        reference's "allgather updated partitions" collective
        (stage_1_and_2.py step :2204), but emitted by XLA over ICI.
        """
        lr = self.lr if lr is None else float(lr)
        g_paths, g_leaves, g_treedef = _leaf_paths(grads)
        p_paths, p_leaves, _ = _leaf_paths(params)
        assert g_paths == p_paths, "grad/param tree mismatch"

        # 1) fetch unique grad shards to host (device->host copy). bf16
        # grads stay bf16 (uint16 bit view) — the native optimizer kernels
        # consume them directly (dstpu_adam_step_bf16grad).
        host_grads: Dict[Tuple[str, str], np.ndarray] = {}
        for path, gleaf in zip(g_paths, g_leaves):
            for shard in gleaf.addressable_shards:
                key = (path, _index_key(shard.index))
                if key in host_grads or key not in self.optimizers:
                    continue
                host = np.asarray(shard.data)
                if _BF16 is not None and host.dtype == _BF16:
                    host_grads[key] = np.ascontiguousarray(
                        host.view(np.uint16)).reshape(-1)
                else:
                    host_grads[key] = np.ascontiguousarray(
                        host, np.float32).reshape(-1)

        # 2) global grad norm. Each shard is counted by exactly ONE process
        # globally: the owner is the lowest (process_index, device_id)
        # holding it — in-process replicas are deduped by the host_grads
        # keying, cross-process replicas by the (cached) ownership set.
        owned = self._owned_keys(g_paths, g_leaves)
        lib = build_native_lib()
        sq = 0.0
        for key, arr in host_grads.items():
            if key not in owned:
                continue
            if arr.dtype == np.uint16:
                f = bf16_to_f32(arr)
                sq += float(np.dot(f, f))
            elif lib is not None:
                import ctypes

                sq += lib.dstpu_sq_norm(
                    arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    arr.size)
            else:
                sq += float(np.dot(arr, arr))
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            sq = float(np.sum(multihost_utils.process_allgather(
                np.asarray([sq]))))
        if grad_scale and grad_scale != 1.0:
            sq /= grad_scale ** 2
        gnorm = float(np.sqrt(sq))

        coef = 1.0
        if grad_scale and grad_scale != 1.0:
            coef /= grad_scale
        if self.grad_clip and gnorm > self.grad_clip:
            coef *= self.grad_clip / (gnorm + 1e-6)
        # only the fp16 loss-scaling protocol skips steps on overflow
        # (matching the device path's apply_update); bf16 runs apply the
        # step so a NaN source is visible, not silently spun on.
        overflow = skip_on_nonfinite and not np.isfinite(gnorm)

        if overflow:
            return None, gnorm, True

        # 3) step each unique local shard (ZeRO partition of this host)
        updated: Dict[Tuple[str, str], np.ndarray] = {}
        for (path, gleaf), pleaf in zip(zip(g_paths, g_leaves), p_leaves):
            cdt = pleaf.dtype
            use_bf16_out = (_BF16 is not None and cdt == _BF16)
            for shard in gleaf.addressable_shards:
                key = (path, _index_key(shard.index))
                if key in updated or key not in self.optimizers:
                    continue
                g = host_grads[key]
                if coef != 1.0:
                    # scaling needs fp32; otherwise bf16 grads flow to the
                    # native bf16-grad kernel unwidened
                    if g.dtype == np.uint16:
                        g = bf16_to_f32(g)
                    g = g * np.float32(coef)
                master = (self._swap_in(key) if self._swap is not None
                          else self.masters[key])
                out_bf16 = (np.empty(master.size, np.uint16)
                            if use_bf16_out else None)
                self.optimizers[key].step(master, g, param_bf16_out=out_bf16,
                                          lr=lr)
                shape = self._shard_shapes[key]
                if use_bf16_out:
                    updated[key] = out_bf16.view(_BF16).reshape(shape)
                else:
                    updated[key] = master.reshape(shape).astype(cdt)
                if self._swap is not None:
                    self._swap_out(key, master)

        # 4) upload: rebuild each leaf WITH THE GRAD (optimizer) SHARDING;
        # the engine reshards to the param sharding under jit. Leaves
        # marked host-memory never touch HBM: they upload into pinned
        # host buffers and the engine's reshard keeps them there.
        new_leaves = []
        for (path, gleaf), pleaf in zip(zip(g_paths, g_leaves), p_leaves):
            cdt = pleaf.dtype
            to_host = any(path.startswith(p)
                          for p in self.host_memory_leaf_prefixes)
            sharding = (memspace.with_memory_kind(gleaf.sharding,
                                                  "pinned_host")
                        if to_host else gleaf.sharding)
            bufs = []
            for shard in gleaf.addressable_shards:
                key = (path, _index_key(shard.index))
                if to_host:
                    # host-memory leaves stay FP32 (master precision;
                    # sub-32-bit host->device streaming is unsupported);
                    # pleaf.dtype is fp32 for them, so updated[] is too
                    piece = np.ascontiguousarray(updated[key],
                                                 dtype=np.float32)
                    bufs.append(jax.device_put(
                        piece, _pinned_single_device(shard.device)))
                else:
                    piece = updated[key].astype(cdt, copy=False)
                    bufs.append(jax.device_put(piece, shard.device))
            new_leaves.append(jax.make_array_from_single_device_arrays(
                gleaf.shape, sharding, bufs))
        new_tree = jax.tree_util.tree_unflatten(g_treedef, new_leaves)
        return new_tree, gnorm, overflow

    # ------------------------------------------------------------------
    def reinit_masters(self, p32_tree) -> None:
        """Re-seed fp32 masters from a device tree carrying the optimizer
        sharding (moments reset to zero). Used when a checkpoint is loaded
        without optimizer state."""
        paths, leaves, _ = _leaf_paths(p32_tree)
        for path, leaf in zip(paths, leaves):
            for shard in leaf.addressable_shards:
                key = (path, _index_key(shard.index))
                if key not in self.optimizers:
                    continue
                master = _to_f32(np.asarray(shard.data)).reshape(-1).copy()
                self.optimizers[key] = self._opt_cls(master.size, lr=self.lr,
                                                     **self._opt_kwargs)
                if self._swap is not None:
                    self._swap_out(key, master)
                    self.masters[key] = None
                else:
                    self.masters[key] = master

    # ------------------------------------------------------------------
    # fragment APIs (utils/tensor_fragment.py backing when offloaded)
    # ------------------------------------------------------------------
    def _master_of(self, key) -> np.ndarray:
        if self._swap is not None:
            return self._swap.swap_in(f"{key[0]}.{key[1]}.master")
        return self.masters[key]

    def _leaf_keys(self, keystr: str):
        keys = [k for k in self.optimizers if k[0] == keystr]
        if not keys:
            known = sorted({k[0] for k in self.optimizers})
            raise KeyError(f"no offloaded shards for param {keystr!r}; "
                           f"known leaves: {known[:10]}...")
        return keys

    def full_fp32_param(self, keystr: str) -> np.ndarray:
        """Assemble the global fp32 master from local shards. Multi-host:
        only valid when this process holds every shard (single-host or
        replicated layouts); raises otherwise."""
        gshape, _ = self._leaf_layout[keystr]
        out = np.zeros(gshape, np.float32)
        covered = 0
        for key in self._leaf_keys(keystr):
            idx = self._shard_index[key]
            piece = self._master_of(key).reshape(self._shard_shapes[key])
            out[idx] = piece
            covered += piece.size
        if covered < int(np.prod(gshape)):
            raise ValueError(
                f"param {keystr!r}: local shards cover {covered} of "
                f"{int(np.prod(gshape))} elements — full assembly needs "
                "all shards on this host (use local_fp32_param instead)")
        return out

    def local_fp32_param(self, keystr: str) -> np.ndarray:
        key = self._leaf_keys(keystr)[0]
        return self._master_of(key).reshape(self._shard_shapes[key])

    def set_full_fp32_param(self, keystr: str, value: np.ndarray) -> None:
        value = np.asarray(value, np.float32)
        gshape, _ = self._leaf_layout[keystr]
        assert value.shape == tuple(gshape), (value.shape, gshape)
        for key in self._leaf_keys(keystr):
            idx = self._shard_index[key]
            master = np.ascontiguousarray(value[idx]).reshape(-1)
            if self._swap is not None:
                self._swap.swap_out(f"{key[0]}.{key[1]}.master", master,
                                    sync=True)
            else:
                self.masters[key] = master

    def full_optimizer_state(self, keystr: str, state_key: str
                             ) -> Optional[np.ndarray]:
        gshape, _ = self._leaf_layout[keystr]
        out = np.zeros(gshape, np.float32)
        for key in self._leaf_keys(keystr):
            if self._swap is not None:
                self._swap_in(key)
            sd = self.optimizers[key].state_dict()
            if state_key not in sd:
                return None
            out[self._shard_index[key]] = np.asarray(
                sd[state_key]).reshape(self._shard_shapes[key])
            if self._swap is not None:
                self.optimizers[key].detach_state()
        return out

    # ------------------------------------------------------------------
    # checkpoint surface (engine CheckpointIO hooks)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        """NVMe caveat: the returned dict holds ALL local shards' masters
        and moments at once (np.savez needs them together) — peak host RAM
        during checkpointing is the full local optimizer state."""
        out = {}
        for key, opt in self.optimizers.items():
            master = (self._swap_in(key) if self._swap is not None
                      else self.masters[key])
            entry = {"master": np.asarray(master),
                     "shape": self._shard_shapes[key]}
            entry.update({k: np.asarray(v) if isinstance(v, np.ndarray) else v
                          for k, v in opt.state_dict().items()})
            out[f"{key[0]}|{key[1]}"] = entry
            if self._swap is not None:
                # the dict keeps the refs; drop the optimizer's own copies
                opt.detach_state()
        return out

    def load_state_dict(self, sd: dict) -> None:
        matched = set()
        for flat_key, entry in sd.items():
            path, idx = flat_key.split("|", 1)
            key = (path, idx)
            if key not in self.optimizers:
                logger.warning(f"offload load: unknown shard {key}; skipped")
                continue
            master = np.ascontiguousarray(entry["master"], np.float32)
            opt_sd = {k: v for k, v in entry.items()
                      if k not in ("master", "shape")}
            self.optimizers[key].load_state_dict(opt_sd)
            if self._swap is not None:
                self._swap_out(key, master)
            else:
                self.masters[key] = master
            matched.add(key)
        missing = set(self.optimizers) - matched
        if missing:
            # an unmatched shard would keep its INIT master, and the next
            # sync/step would overwrite the restored params with it — fail
            # loudly instead (topology changed: resave from the original
            # layout or load with load_optimizer_states=False).
            raise ValueError(
                f"offload optimizer state covers {len(matched)} of "
                f"{len(self.optimizers)} local shards; {len(missing)} "
                "missing (e.g. "
                f"{sorted(missing)[:2]}). The checkpoint was saved on a "
                "different process/mesh layout — load with "
                "load_optimizer_states=False to rebuild masters from the "
                "checkpoint params.")

    def sync_params_from_masters(self, params):
        """Rebuild a compute-dtype tree (optimizer sharding) from host
        masters; the engine reshards it to the param sharding. Used after
        checkpoint load."""
        p_paths, p_leaves, p_treedef = _leaf_paths(params)
        new_leaves = []
        for path, pleaf in zip(p_paths, p_leaves):
            cdt = pleaf.dtype
            gshape, sharding = self._leaf_layout[path]
            to_host = any(path.startswith(p)
                          for p in self.host_memory_leaf_prefixes)
            # the recorded layout is the MASTERS' placement (often fully
            # pinned); the rebuilt compute tree must be pinned only for
            # streamed prefixes and device elsewhere, and the buffer
            # placement below must match the sharding exactly
            sharding = memspace.with_memory_kind(
                sharding, "pinned_host" if to_host else "device")
            bufs = []
            idx_map = sharding.addressable_devices_indices_map(gshape)
            for device, index in idx_map.items():
                key = (path, _index_key(index))
                # only the master is needed here — don't drag moments in
                master = (self._swap.swap_in(f"{path}.{index!r}.master")
                          if self._swap is not None
                          else self.masters.get(key))
                shape = self._shard_shapes[key]
                if _BF16 is not None and cdt == _BF16:
                    piece = f32_to_bf16(master).view(_BF16).reshape(shape)
                else:
                    piece = master.reshape(shape).astype(cdt)
                if to_host:
                    bufs.append(jax.device_put(
                        piece, _pinned_single_device(device)))
                else:
                    bufs.append(jax.device_put(piece, device))
            new_leaves.append(jax.make_array_from_single_device_arrays(
                gshape, sharding, bufs))
        return jax.tree_util.tree_unflatten(p_treedef, new_leaves)
