"""The training engine.

TPU-native analog of ``DeepSpeedEngine`` (reference: runtime/engine.py:235)
and ``deepspeed.initialize`` (__init__.py:93). The reference wraps eager
autograd and hand-schedules partitioning/communication; here the whole
GAS boundary compiles into ONE XLA program:

  * ``train_batch`` jits a scan over microbatches; gradient accumulation is
    the backward of that scan, so gradients are reduced ONCE per boundary —
    the comm schedule ZeRO-1 builds by hand (stage_1_and_2.py:1125
    bucketed reduction at boundary), and strictly less communication than
    the reference's per-microbatch stage-2 reduce — while remat keeps
    activation memory at one microbatch.
  * ZeRO stages are sharding constraints (runtime/sharding.py): XLA emits
    the reduce-scatter (stage 2), parameter all-gathers with prefetch
    (stage 3 ≈ partitioned_param_coordinator.py), and overlaps them
    (overlap_comm ≈ the latency-hiding scheduler).
  * ``forward``/``backward``/``step`` keep the reference's micro-step API
    (engine.py:2675,3066,3241) for parity: forward computes loss+grads in
    one jitted call, backward accumulates, step applies at the GAS
    boundary.

``initialize`` returns the reference's 4-tuple
(engine, optimizer, dataloader, lr_scheduler).
"""

from __future__ import annotations

import os
import time
from collections import deque
from functools import partial
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.config.config import Config, load_config
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.runtime import sharding as shard_lib
from deepspeed_tpu.runtime.loss_scaler import (
    LossScaleState, has_overflow, init_loss_scale, update_loss_scale)
from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule
from deepspeed_tpu.runtime.optimizer import (
    MixedPrecisionState, apply_mixed_precision_update, get_base_optimizer,
    init_mixed_precision)
from deepspeed_tpu.runtime.prefetch import PrefetchingIterator
from deepspeed_tpu.utils import memspace
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (
    BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER,
    SynchronizedWallClockTimer, ThroughputTimer, TRAIN_BATCH_TIMER)


def initialize(
    args=None,
    model=None,
    optimizer=None,
    model_parameters=None,
    training_data=None,
    lr_scheduler=None,
    mesh=None,
    topology=None,
    dist_init_required: Optional[bool] = None,
    collate_fn=None,
    config=None,
    config_params=None,
):
    """Reference-parity entry point (deepspeed/__init__.py:93).

    `model` is a model object exposing ``init(rng) -> params``,
    ``loss(params, batch) -> (loss, aux)`` and ``logical_axes()`` (see
    models/transformer.py TransformerLM), or any ``(loss_fn, params)``
    pair passed as (model=loss_fn, model_parameters=params).
    Returns (engine, optimizer_view, dataloader, lr_scheduler_fn).
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    config = config if config is not None else config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)

    comm.init_distributed(dist_init_required=dist_init_required)
    engine = Engine(
        model=model,
        config=load_config(config),
        mesh=mesh,
        topology=topology,
        model_parameters=model_parameters,
        training_data=training_data,
        lr_scheduler=lr_scheduler,
        collate_fn=collate_fn,
        client_optimizer=optimizer,
    )
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


class _FnModel:
    """Adapts a bare (loss_fn, params) pair to the model protocol."""

    def __init__(self, loss_fn: Callable, params):
        self._loss_fn = loss_fn
        self._params = params

    def init(self, rng):
        return self._params

    def loss(self, params, batch):
        out = self._loss_fn(params, batch)
        return out if isinstance(out, tuple) else (out, {})

    def logical_axes(self):
        # unannotated: every dim eligible for fsdp via first-dim fallback
        return jax.tree.map(lambda p: tuple("embed" if i == 0 else None
                                            for i in range(jnp.ndim(p))),
                            self._params)


class _InflightStep:
    """One dispatched-but-unresolved train step (dispatch-ahead window):
    the async metrics plus everything the deferred host reads need —
    snapshotted at dispatch so drain-time logging reports the step's own
    numbers, not the engine's current ones."""

    __slots__ = ("step", "metrics", "struct", "samples", "host_ms",
                 "dispatch_t", "host_t0", "sync")

    def __init__(self, step, metrics, struct, samples, host_ms,
                 dispatch_t, host_t0, sync):
        self.step = step
        self.metrics = metrics
        self.struct = struct          # abstract batch (shapes/dtypes)
        self.samples = samples        # global_samples after this step
        self.host_ms = host_ms        # host time from entry to dispatch
        self.dispatch_t = dispatch_t  # perf_counter at dispatch return
        self.host_t0 = host_t0        # perf_counter at train_batch entry
        self.sync = sync              # dispatched under the blocking loop


class Engine:
    """Owns params/optimizer state, the compiled step functions, timers,
    monitors and checkpointing (reference DeepSpeedEngine engine.py:235)."""

    def __init__(self, model, config: Config, mesh: Optional[Mesh] = None,
                 topology=None, model_parameters=None, training_data=None,
                 lr_scheduler=None, collate_fn=None, client_optimizer=None,
                 seed: Optional[int] = None):
        if callable(model) and not hasattr(model, "loss"):
            model = _FnModel(model, model_parameters)
        self.model = self.module = model
        self.config = config

        # -- mesh (engine.py:1627 _configure_distributed_model analog) ----
        # known before mesh selection: a client optimizer disqualifies the
        # ZeRO++ step, so the default mesh must not assume it
        self._client_optimizer_present = client_optimizer is not None
        if mesh is None:
            mesh = self._default_mesh(topology)
        self.mesh = mesh
        topo.set_global_mesh(mesh)
        self.dp_world_size = topo.get_data_parallel_world_size(mesh)
        config.resolve_batch_size(self.dp_world_size)
        self.plan = shard_lib.make_sharding_plan(config, mesh)
        comm.configure(config)
        from deepspeed_tpu.runtime import activation_checkpointing as act_ckpt

        act_ckpt.configure(config.activation_checkpointing)
        from deepspeed_tpu.utils import memory as mem_util

        mem_util.configure(config.memory_breakdown)
        mem_util.see_memory_usage("engine init: before model setup")
        from deepspeed_tpu.ops import attention as attn_ops

        if config.sparse_attention is not None:
            import dataclasses as _dc

            from deepspeed_tpu.ops.pallas.blocksparse_attention import \
                from_config as sparse_from_config

            scfg = config.sparse_attention
            kblk = getattr(getattr(config, "kernels", None),
                           "blocksparse_block", 0)
            if kblk and kblk != scfg.block:
                # kernels.blocksparse_block overrides the layout/kernel
                # block granularity (0 = follow sparse_attention.block)
                scfg = _dc.replace(scfg, block=kblk)
            attn_ops.set_sparse_config(sparse_from_config(scfg))
            if getattr(getattr(model, "config", None), "attn_impl",
                       None) != "blocksparse":
                logger.warning(
                    "sparse_attention configured but the model's "
                    "attn_impl is not 'blocksparse' — dense attention "
                    "will run; set attn_impl='blocksparse' on the model "
                    "config to activate the layout")
            if config.sparse_attention.attention == "bidirectional":
                logger.warning(
                    "sparse_attention.attention='bidirectional': "
                    "causality comes from the model (the LM stack is "
                    "causal); the layout is applied either way")
        else:
            # a previous engine in this process may have installed a
            # layout into the process-global dispatcher — clear it
            attn_ops.set_sparse_config(None)

        # kernel geometry + dispatch policy (config.kernels): block sizes
        # and the cost-table dispatch mode feed the same process-global
        # dispatcher the sparse layout uses — multi_head_attention and the
        # paged serving path read them at trace time
        attn_ops.set_kernel_config(getattr(config, "kernels", None))

        # -- MoE expert execution engine selection (config.moe.impl) ------
        mcfg = getattr(model, "config", None)
        if (config.moe.impl != "auto" and mcfg is not None
                and hasattr(mcfg, "moe_impl")
                and mcfg.moe_impl != config.moe.impl):
            import dataclasses as _dc

            model.config = _dc.replace(mcfg, moe_impl=config.moe.impl)

        # -- performance block → model config (docs/performance.md) -------
        # fp8 MLP matmuls and the layer-prefetch ring depth live on the
        # model config (they change the traced program); the engine is
        # the bridge from the DeepSpeed-style config block. An explicit
        # performance.param_prefetch_depth beats the model/env default.
        perf = getattr(config, "performance", None)
        mcfg = getattr(model, "config", None)
        perf_updates = {}
        if perf is not None and mcfg is not None:
            if getattr(perf, "fp8_mlp", False) \
                    and hasattr(mcfg, "fp8_mlp") and not mcfg.fp8_mlp:
                perf_updates["fp8_mlp"] = True
            ppd = getattr(perf, "param_prefetch_depth", None)
            if ppd is not None and hasattr(mcfg, "prefetch_depth") \
                    and mcfg.prefetch_depth != int(ppd):
                perf_updates["prefetch_depth"] = int(ppd)
            od = getattr(perf, "overlap_depth", None)
            if od is not None and hasattr(mcfg, "overlap_depth") \
                    and mcfg.overlap_depth != int(od):
                perf_updates["overlap_depth"] = int(od)
        if perf_updates:
            import dataclasses as _dc

            model.config = _dc.replace(mcfg, **perf_updates)

        # -- sequence-parallel planner (parallel/auto_sp.py) --------------
        # When the mesh has an sp axis AND sp was opted into (model flag
        # or sequence_parallel.size > 1 — an sp mesh axis alone also
        # serves sequence-sharded activations without sp attention, so
        # it is not treated as opt-in), compose the long-context plan
        # onto the model config at init. SPPlan.apply is conservative:
        # only fields still at their defaults change;
        # sequence_parallel.auto_plan=False opts out entirely.
        sp_cfg = getattr(config, "sequence_parallel", None)
        mcfg = getattr(model, "config", None)
        if (sp_cfg is not None and getattr(sp_cfg, "auto_plan", True)
                and mcfg is not None and hasattr(mcfg, "num_heads")
                and int(dict(mesh.shape).get("sp", 1)) > 1
                and (getattr(mcfg, "sequence_parallel", False)
                     or getattr(sp_cfg, "size", 1) > 1)):
            from deepspeed_tpu.parallel.auto_sp import \
                plan_sequence_parallel

            budget_gb = getattr(sp_cfg, "hbm_budget_gb", None)
            try:
                _dbytes = int(jnp.dtype(mcfg.dtype).itemsize)
            except Exception:
                _dbytes = 2
            sp_plan = plan_sequence_parallel(
                mcfg.max_seq_len, mcfg.num_heads,
                getattr(mcfg, "num_kv_heads", None), mesh,
                int(budget_gb * 2 ** 30) if budget_gb else None,
                head_dim=mcfg.head_dim, hidden_size=mcfg.hidden_size,
                batch_size=config.train_micro_batch_size_per_chip or 1,
                dtype_bytes=_dbytes)
            self.sp_plan = sp_plan
            new_mcfg = sp_plan.apply(mcfg)
            if new_mcfg is not mcfg:
                model.config = new_mcfg
                log_dist("sp planner: " + "; ".join(sp_plan.reasons),
                         ranks=[0])
        else:
            self.sp_plan = None

        self.micro_batch_size = config.train_micro_batch_size_per_chip
        self.gradient_accumulation_steps = config.gradient_accumulation_steps
        self.train_batch_size = config.train_batch_size
        self.compute_dtype = config.compute_dtype
        self._post_step_hooks = []

        # -- 1-bit compressed-comm optimizers (runtime/onebit.py) ---------
        opt_name = ((config.optimizer.type if config.optimizer else "")
                    or "").lower().replace("_", "").replace("-", "")
        from deepspeed_tpu.runtime.onebit import (
            ONEBIT_OPTIMIZERS, validate_onebit_config)

        self._onebit = opt_name in ONEBIT_OPTIMIZERS
        if self._onebit:
            validate_onebit_config(config)

        # -- optimizer (engine.py:1901 _configure_optimizer analog) -------
        if self._onebit:
            self.tx = None
            from deepspeed_tpu.runtime.onebit import parse_onebit_params

            self._onebit_params = parse_onebit_params(
                opt_name, (config.optimizer.params or {})
                if config.optimizer else {})
            self._base_lr = self._onebit_params["lr"]
            self.lr_schedule = get_lr_schedule(config.scheduler,
                                               base_lr=self._base_lr)
        elif client_optimizer is not None:
            self.tx = client_optimizer  # user-supplied optax transform
            self._base_lr = None
        else:
            sched = get_lr_schedule(config.scheduler,
                                    base_lr=self._config_lr())
            self.lr_schedule = sched
            self.tx, self._base_lr = get_base_optimizer(config.optimizer, sched)
        if not hasattr(self, "lr_schedule"):
            self.lr_schedule = None
        self.lr_scheduler = lr_scheduler or self.lr_schedule

        # -- offload (ZeRO-Offload/Infinity analog) -----------------------
        off_cfg = config.zero_optimization.offload_optimizer
        self._offload_device = (off_cfg.device if off_cfg is not None
                                else "none") or "none"
        self._offload = None  # built in _build_state when enabled
        self._zenflow = None  # built alongside _offload when configured
        if config.zero_optimization.zenflow is not None and \
                self._offload_device != "cpu":
            raise ValueError(
                "zero_optimization.zenflow requires "
                "offload_optimizer.device='cpu' (ZenFlow keeps masters "
                "host-resident; the NVMe swap tier does not apply), got "
                f"device={self._offload_device!r}")

        # -- ZeRO++ quantized-collective step (runtime/zeropp.py) ---------
        self._zeropp = self._zeropp_applicable(config) and not self._onebit
        self._zeropp_state = None
        # set_lr under a compiled runtime-lr step (zeropp/onebit): the lr
        # rides as an operand, NaN = use the traced schedule
        self._lr_override = None
        zq = config.zero_optimization
        # stage-3 qwZ: int8 parameter all-gather in the GSPMD fetch path
        # (reference partition_parameters.py:1446). Composes with tp/sp/
        # hpZ/MiCS since it is just a constraint pair around the gather;
        # armed per-engine via the sharding module switch.
        # pp composes since round 4: the pipeline region is manual over
        # pp only, so the int8 fetch constraints stay live in stage
        # bodies (parallel/pipeline.py manual_axes). pp×fsdp×tp composes
        # since round 5: the partitioner CHECK that used to kill that
        # mesh class was the vocab-parallel lookup's gather (see
        # sharding.py vocab_parallel_lookup), not the qwZ fetch pair.
        self._qwz_stage3 = (zq.stage == 3 and zq.zero_quantized_weights
                            and not config.moe.enabled)
        if (zq.stage == 3 and zq.zero_quantized_weights
                and not self._qwz_stage3):
            from deepspeed_tpu.utils import telemetry

            reason = "moe"
            telemetry.count("zeropp.qwz_disabled", reason)
            logger.warning(
                f"ZeRO++ qwZ stage-3 is inert for this config ({reason}) "
                "— layer gathers stay full-width bf16")
        if self._qwz_stage3:
            log_dist("ZeRO++ qwZ: stage-3 int8 quantized parameter "
                     "all-gather enabled (fsdp axis)", ranks=[0])
        # qgZ for the GSPMD path (stages 2-3): per-group grads (vmap over
        # batch shards) + explicit int8[/int4 hierarchical] all-to-all
        # reduction (runtime/qgz.py; reference coalesced_collectives.py:31
        # all_to_all_quant_reduce). Composes with tp and sp (sp grads
        # reduce full-width inside each group's backward — intra-slice
        # ICI; the fsdp/dp reduction, the DCN-bound wire, is quantized)
        # and with optimizer offload/zenflow (the wire quantizes before
        # the host grad copy — grad_step runs the same construction).
        # Stage 2 with fsdp>1 routes here too, retiring the legacy
        # manual-dp step's fsdp rejection (runtime/zeropp.py:74).
        # MoE/ep composes since round 5: the ep token-group axis reduces
        # expert grads onto the expert-stacked dim with int8 wire
        # (expert-dim-aware grouping, runtime/qgz.py level 2); the
        # grouped MoE dispatch falls back to the einsum path under the
        # per-group vmap (parallel/moe.py — a shard_map can't map a
        # vmapped token axis). Remaining exclusion: pp.
        self._qgz_stage3 = (
            zq.stage >= 2 and zq.zero_quantized_gradients
            and self.mesh.shape.get("pp", 1) <= 1
            and self.mesh.shape.get("fsdp", 1) > 1)
        if self._qgz_stage3:
            log_dist(
                "ZeRO++ qgZ: stage-3 quantized gradient reduction enabled "
                f"(int8 over fsdp={self.mesh.shape['fsdp']}"
                + (f", int8 expert-grads over ep={self.mesh.shape['ep']}"
                   if self.mesh.shape.get("ep", 1) > 1 else "")
                + (f", int4 over dp={self.mesh.shape['dp']}"
                   if self.mesh.shape.get("dp", 1) > 1 else "") + ")",
                ranks=[0])
        elif zq.stage == 3 and zq.zero_quantized_gradients:
            from deepspeed_tpu.utils import telemetry

            telemetry.count("zeropp.qgz_disabled",
                            "config outside qgZ support matrix")
            logger.warning(
                "ZeRO++ qgZ at stage 3 requires no optimizer offload, "
                "no pp axis, and fsdp > 1 — this config fails that, so "
                "gradients reduce at full width")
        if (zq.zero_quantized_weights or zq.zero_quantized_gradients) \
                and not self._zeropp and not self._qwz_stage3 \
                and not self._qgz_stage3:
            logger.warning(
                "ZeRO++ flags (qwZ/qgZ) are wired for: stage 1-2 with "
                "adam/adamw (no client optimizer), bf16, no optimizer "
                "offload, no MoE, no sp/pp axes (tp composes), no "
                "hpZ/MiCS grouping, no 1-bit optimizer; or stage-3 "
                "zero_quantized_weights/zero_quantized_gradients (dense "
                "models). This config fails those, so the quantized path "
                "is disabled and the standard step runs")

        # -- state init (sharded; zero.Init analog is in abstract init) ---
        # streamed-param subtrees (offload_param): the host_param_paths
        # protocol (runtime/param_stream.py) or TransformerLM's "layers"
        _proto = getattr(model, "host_param_paths", None)
        self._host_param_paths = (tuple(_proto) if _proto is not None
                                  else ("layers",))
        self._rng = jax.random.PRNGKey(seed if seed is not None else config.seed)
        self._axes = model.logical_axes()
        self._build_state()
        self._build_step_fns()

        # -- observability ------------------------------------------------
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size,
            steps_per_output=config.steps_per_print)
        self.monitor = self._build_monitor()
        # unified observability hub: per-step StepTrace rows, stall
        # watchdog, on-demand profiler capture (docs/observability.md)
        self.hub = None
        self.watchdog = None
        self.flight = None
        self._trace_capture = None
        self._obs_cfg = getattr(config, "observability", None)
        if self._obs_cfg is None or self._obs_cfg.enabled:
            try:
                from deepspeed_tpu.observability import (StallWatchdog,
                                                         TraceCapture,
                                                         get_hub)

                self.hub = get_hub()
                self.hub.configure(self._obs_cfg,
                                   rank=jax.process_index())
                self.watchdog = StallWatchdog.from_config(
                    getattr(self._obs_cfg, "watchdog", None),
                    report_fn=self._on_stall_report)
                self._trace_capture = TraceCapture.from_env()
            except Exception as e:
                logger.warning(f"observability hub disabled: {e}")
            try:
                # crash flight recorder: ring of step/collective/
                # checkpoint events, dumped on crash/SIGTERM/watchdog
                # fire (docs/observability.md "Flight recorder")
                from deepspeed_tpu.observability import flight_recorder \
                    as _fr
                from deepspeed_tpu.observability.fleet import \
                    resolve_run_dir

                self.flight = _fr.get_flight_recorder()
                self.flight.configure(
                    capacity=getattr(self._obs_cfg, "flight_events", None),
                    rank=jax.process_index(),
                    run_dir=resolve_run_dir(self._obs_cfg))
                if not self.flight.enabled:
                    self.flight = None
                else:
                    _fr.install_crash_handlers()
            except Exception as e:
                logger.warning(f"flight recorder disabled: {e}")

        # -- resilience (resilience block; docs/resilience.md) ------------
        # PreemptionGuard: SIGTERM → drain + emergency checkpoint at the
        # next GAS boundary (second SIGTERM escalates through the flight
        # recorder's chained dump-and-kill handler, installed above).
        # Chaos injector: armed only when DSTPU_CHAOS is set — one `is
        # None` check per step/input-pull otherwise.
        self.preempted = False
        self.loaded_data_cursor = None  # manifest cursor from last load
        self._last_save_dir = None      # emergency-save fallback target
        self._last_data_iter = None     # data_cursor loader-state source
        self._resilience_cfg = rcfg = getattr(config, "resilience", None)
        self._preempt_guard = None
        self._chaos = None
        try:
            from deepspeed_tpu.resilience.chaos import get_chaos_injector

            inj = get_chaos_injector()
            self._chaos = inj if inj.armed else None
        except Exception as e:
            logger.warning(f"chaos injector unavailable: {e}")
        if rcfg is None or (rcfg.enabled and rcfg.preemption_guard):
            try:
                from deepspeed_tpu.resilience.preemption import \
                    PreemptionGuard

                self._preempt_guard = PreemptionGuard(
                    save_deadline_s=getattr(
                        rcfg, "preemption_save_deadline_s", 60.0)
                    if rcfg is not None else 60.0)
                self._preempt_guard.install()
            except Exception as e:
                logger.warning(f"preemption guard disabled: {e}")
                self._preempt_guard = None
        self._flops_per_token = None   # cached model.flops_per_token()
        self._last_batches_struct = None  # abstract batch for roofline()
        self._roofline_cost = None     # cached XLA cost analysis
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._pending = None  # (loss, grads) between forward() and backward()
        self._grad_acc = None  # accumulation buffer for the micro-step path

        # -- pipelined loop (performance block; docs/performance.md) ------
        # dispatch-ahead: up to pipeline_depth steps stay in flight; the
        # deferred host reads run when each step drains. 0 = the blocking
        # loop. DSTPU_DISPATCH_AHEAD env beats the config block.
        perf = getattr(config, "performance", None)
        env_depth = os.environ.get("DSTPU_DISPATCH_AHEAD", "")
        self._dispatch_ahead = (int(env_depth) if env_depth != ""
                                else int(getattr(perf, "pipeline_depth", 0)
                                         or 0))
        self._prefetch_depth = int(getattr(perf, "prefetch_depth", 0) or 0)
        self._inflight: deque = deque()  # _InflightStep, oldest first
        self._prefetcher = None       # PrefetchingIterator over data_iter
        self._prefetch_source = None  # the data_iter the prefetcher owns
        self._last_drain_t = None     # perf_counter at the previous drain
        if self._dispatch_ahead > 0:
            log_dist(f"pipelined loop: dispatch-ahead depth "
                     f"{self._dispatch_ahead}, input prefetch depth "
                     f"{self._prefetch_depth}", ranks=[0])

        # -- curriculum learning (reference engine curriculum_learning
        # config + set_custom_curriculum_learning_schedule) ---------------
        self.curriculum_scheduler = None
        de = config.data_efficiency
        if de.enabled and de.curriculum_metrics:
            from deepspeed_tpu.runtime.data_pipeline import \
                CurriculumScheduler

            if len(de.curriculum_metrics) > 1:
                logger.warning(
                    "data_efficiency: multiple curriculum metrics "
                    f"configured ({sorted(de.curriculum_metrics)}); the "
                    "engine schedules only the first — drive the others "
                    "via DeepSpeedDataSampler directly")
            first = next(iter(de.curriculum_metrics.values()))
            self.curriculum_scheduler = CurriculumScheduler(first)

        # -- dataloader (engine.py:364 deepspeed_io analog) ---------------
        self.training_dataloader = None
        if training_data is not None:
            from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

            self.training_dataloader = DeepSpeedDataLoader(
                training_data, batch_size=self.micro_batch_size,
                collate_fn=collate_fn)

        from deepspeed_tpu.checkpoint.state import CheckpointIO

        self._ckpt_io = CheckpointIO(self)

        n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(
            jax.eval_shape(lambda: self.params)))
        # multi-host sanity: every process must have resolved the same
        # topology/batch/model geometry (reference
        # assert_ints_same_as_other_ranks at ZeRO init)
        comm.assert_same_across_processes(
            "engine_init", [
                self.micro_batch_size, self.gradient_accumulation_steps,
                self.train_batch_size, config.zero_optimization.stage,
                n_params,
            ] + [f"{a}={s}" for a, s in self.mesh.shape.items()])
        log_dist(
            f"engine ready: {n_params/1e6:.1f}M params, zero_stage="
            f"{config.zero_optimization.stage}, dp={self.dp_world_size}, "
            f"micro={self.micro_batch_size}, gas="
            f"{self.gradient_accumulation_steps}", ranks=[0])

        # -- quantization telemetry (observability/quant_stats.py) --------
        # Quantized collectives without error measurement are the failure
        # mode ROADMAP item 1 names: warn once when qwZ/qgZ run blind,
        # collect quant.* metrics (init-time param-side sample + flight
        # dump context) when collection is configured.
        zq_flags = config.zero_optimization
        if (zq_flags.zero_quantized_weights
                or zq_flags.zero_quantized_gradients):
            try:
                from deepspeed_tpu.observability import quant_stats as _qs

                if _qs.collection_configured(self._obs_cfg):
                    _qs.install_engine_collector(self)
                else:
                    from deepspeed_tpu.utils.logging import warning_once

                    warning_once(
                        "ZeRO++ quantization (zero_quantized_weights/"
                        "zero_quantized_gradients) is enabled but no "
                        "quant.* collection is configured — quantization "
                        "error and wire bytes are unmeasured. Set "
                        "observability.quant_stats=true or "
                        "DSTPU_QUANT_STATS=1 (docs/quantized_comm.md).")
            except Exception as e:
                logger.warning(f"quant telemetry unavailable: {e}")
        mem_util.see_memory_usage("engine init: ready")

    # ------------------------------------------------------------------
    def _config_lr(self) -> float:
        if self.config.optimizer and "lr" in (self.config.optimizer.params or {}):
            return self.config.optimizer.params["lr"]
        return 1e-3

    def _zeropp_applicable(self, config) -> bool:
        """ZeRO++ step preconditions knowable from config + ctor args (the
        1-bit exclusion is checked at the call sites). Model-parallel
        axes, hpZ/MiCS grouping, fp16, MoE, offload, and client
        optimizers all fall back to the standard path (with a warning)."""
        from deepspeed_tpu.runtime.zeropp import zeropp_enabled

        z = config.zero_optimization
        off = z.offload_optimizer
        offdev = (off.device if off is not None else "none") or "none"
        opt = ((config.optimizer.type if config.optimizer else "")
               or "adamw").lower().replace("_", "").replace("-", "")
        return (zeropp_enabled(config) and offdev == "none"
                and not config.fp16.enabled
                and not config.moe.enabled
                and not getattr(self, "_client_optimizer_present", False)
                and config.sequence_parallel.size == 1
                and config.pipeline.stages == 1
                and z.zero_hpz_partition_size <= 1
                and z.mics_shard_size <= 0
                # fsdp/sp/ep/pp meshes route to the per-group qgZ
                # construction instead (build_zeropp_step is manual over
                # dp only and would reject them, zeropp.py:74). During
                # default-mesh selection (self.mesh not set yet) the
                # mesh WILL be dp-only if this returns True, so the
                # axes check passes vacuously.
                and all(m.shape.get(a, 1) == 1
                        for a in ("fsdp", "sp", "ep", "pp")
                        for m in [getattr(self, "mesh", None)] if m)
                and opt in ("adam", "adamw", "fusedadam", "fusedadamw"))

    def _default_mesh(self, topology) -> Mesh:
        if topology is not None:
            return topo.build_mesh(topology)
        cfg = self.config
        sizes = dict(pp=cfg.pipeline.stages,
                     tp=cfg.tensor_parallel.size,
                     sp=cfg.sequence_parallel.size,
                     ep=cfg.moe.ep_size if cfg.moe.enabled else 1)
        if self._zeropp_applicable(cfg):
            # the quantized-collective step shards its masters over dp
            sizes.update(dp=-1, fsdp=1)
        elif cfg.zero_optimization.stage >= 1:
            # hpZ and MiCS are the same construction: shard state within a
            # group of `size` chips (ICI), replicate across groups (DCN) —
            # fsdp=group, dp=replicas (reference mics.py / hpZ
            # partition_parameters.py:1806)
            hpz = cfg.zero_optimization.zero_hpz_partition_size
            mics = cfg.zero_optimization.mics_shard_size
            group = hpz if hpz > 1 else (mics if mics > 0 else 0)
            if group > 1:
                sizes.update(fsdp=group, dp=-1)
            else:
                sizes.update(fsdp=-1, dp=1)
        else:
            sizes.update(dp=-1, fsdp=1)
        return topo.build_mesh(topo.TopologyConfig(**sizes))

    # ------------------------------------------------------------------
    def _build_state(self):
        """Init params (compute dtype) + fp32 master/optimizer state, all
        born sharded: init runs under jit with sharding constraints so the
        full replicated model never materializes (zero.Init analog,
        partition_parameters.py:884)."""
        plan, mesh = self.plan, self.mesh
        param_sh = plan.param_shardings(self._axes)
        opt_sh = plan.opt_shardings(self._axes)
        cdt = self.compute_dtype

        if self._onebit:
            # masters/moments replicated over dp (stage<=1 layout); error
            # feedback is per-rank: leading dp axis, sharded over dp
            from deepspeed_tpu.runtime.onebit import (OneBitState,
                                                      build_onebit_step)

            init_fn, step_fn = build_onebit_step(
                self.model, mesh, self.config, self._onebit_params,
                param_sh, self.lr_schedule)
            self._onebit_step_fn = step_fn
            rep = NamedSharding(mesh, P())
            err_sh = jax.tree.map(
                lambda s: NamedSharding(mesh, P("dp")), param_sh)
            master_sh = param_sh
            out_sh = (param_sh, OneBitState(master=master_sh, m=master_sh,
                                            v=master_sh, error=err_sh,
                                            step=rep))
            with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else _nullctx():
                self.params, self._onebit_state = jax.jit(
                    init_fn, out_shardings=out_sh)(self._rng)
            self.opt_state = None
        elif self._zeropp:
            # ZeRO++ quantized-collective step: fp32 masters live as
            # [dp, shard] arrays (the ZeRO-1/2 partition), params
            # replicated in compute dtype
            from deepspeed_tpu.runtime.zeropp import (ZeroppState,
                                                      build_zeropp_step)

            ocfg_params = dict((self.config.optimizer.params or {})
                               if self.config.optimizer else {})
            z = self.config.zero_optimization
            init_fn, step_fn = build_zeropp_step(
                self.model, mesh, self.gradient_accumulation_steps,
                base_lr=self._config_lr(), lr_schedule=self.lr_schedule,
                betas=tuple(ocfg_params.get("betas", (0.9, 0.999))),
                eps=float(ocfg_params.get("eps", 1e-8)),
                weight_decay=float(ocfg_params.get("weight_decay", 0.01)),
                grad_clip=self.config.gradient_clipping,
                qg_enabled=z.zero_quantized_gradients, qg_bits=8,
                qw_enabled=z.zero_quantized_weights, qw_bits=8,
                compute_dtype=cdt, param_shardings=param_sh,
                qar_enabled=z.zero_quantized_allreduce, qar_bits=8)
            self._zeropp_step_fn = step_fn
            rep = NamedSharding(mesh, P())
            sh = NamedSharding(mesh, P("dp"))
            master_sh = jax.tree.map(lambda _: sh, param_sh)
            out_sh = (param_sh, ZeroppState(master=master_sh, m=master_sh,
                                            v=master_sh, step=rep))
            with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else _nullctx():
                self.params, self._zeropp_state = jax.jit(
                    init_fn, out_shardings=out_sh)(self._rng)
            self.opt_state = None
        elif self._offload_device in ("cpu", "nvme"):
            # fp32 init sharded like optimizer state and written STRAIGHT
            # to pinned host memory (out_shardings memory kind): the full
            # fp32 model never resides in HBM, so multi-B-param offload
            # configs initialize on one 16GB chip (zero.Init analog for
            # the offload tier; reference stage_1_and_2.py cpu_offload /
            # stage3.py offload_optimizer paths).
            def init32(rng):
                p32 = self.model.init(rng)
                return _constrain_tree(p32, opt_sh)

            # the CPU simulator can't lower in-jit host placement
            # ("side-effect ops cannot be replicated"); there the fp32
            # tree is small — init on device and move below
            host_init = jax.default_backend() == "tpu"
            out_sh = (jax.tree.map(
                lambda s: memspace.with_memory_kind(s, "pinned_host"),
                opt_sh)
                if host_init else opt_sh)
            with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else _nullctx():
                p32 = jax.jit(init32, out_shardings=out_sh)(self._rng)
            if not host_init:
                def _pin(a):
                    try:
                        return jax.device_put(
                            a, memspace.with_memory_kind(
                                a.sharding, "pinned_host"))
                    except Exception:
                        # multi-process CPU sim: jax routes this
                        # device_put through a jit reshard (device order
                        # differs across processes) and the CPU backend
                        # rejects in-jit host placement ("side-effect
                        # ops cannot be replicated"). Memory kind is
                        # simulation-moot there — keep device placement.
                        return a

                p32 = jax.tree.map(_pin, p32)
            from deepspeed_tpu.runtime.offload import HostOffloadOptimizer

            ocfg = self.config.optimizer
            off = self.config.zero_optimization.offload_optimizer
            poff = self.config.zero_optimization.offload_param
            host_prefixes = (
                tuple(f"['{k}']" for k in self._host_param_paths)
                if poff is not None and poff.device != "none" else ())
            self._offload = HostOffloadOptimizer(
                p32,
                optimizer_name=(ocfg.type if ocfg else "adamw") or "adamw",
                optimizer_params=dict((ocfg.params or {}) if ocfg else {}),
                compute_dtype=cdt,
                grad_clip=self.config.gradient_clipping,
                nvme_path=(off.nvme_path
                           if self._offload_device == "nvme" else None),
                host_memory_leaf_prefixes=host_prefixes)
            # ZenFlow masters come from the TRUE fp32 init
            self._zenflow = self._maybe_build_zenflow(p32)
            # the compute-dtype params must land back in DEVICE memory —
            # XLA would otherwise propagate the staged inputs' host space
            # into the outputs. TPU: out_shardings memory kind; CPU sim:
            # explicit device_put (in-jit placement doesn't lower there).
            if host_init:
                cast = jax.jit(
                    lambda t: jax.tree.map(lambda m: m.astype(cdt), t),
                    out_shardings=jax.tree.map(
                        lambda s: memspace.with_memory_kind(s, "device"),
                        param_sh))
                self.params = cast(p32)
            else:
                cast = jax.jit(
                    lambda t: _constrain_tree(
                        jax.tree.map(lambda m: m.astype(cdt), t), param_sh))
                self.params = jax.tree.map(
                    lambda a: jax.device_put(
                        a, memspace.with_memory_kind(a.sharding, "device")),
                    cast(p32))
            if host_prefixes and isinstance(p32, dict):
                # streamed params stay the pinned fp32 masters (the
                # compiled step fetches one layer at a time); drop the
                # device bf16 copies the cast produced
                self.params = dict(self.params)
                for key in getattr(self, "_host_param_paths", ("layers",)):
                    if key in p32:
                        self.params[key] = p32[key]
            self.opt_state = None
        else:
            def init_fn(rng):
                p32 = self.model.init(rng)
                p32 = _constrain_tree(p32, opt_sh)
                mp = init_mixed_precision(p32, self.tx)
                params = jax.tree.map(lambda m: m.astype(cdt), mp.master)
                params = _constrain_tree(params, param_sh)
                return params, mp

            with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else _nullctx():
                self.params, self.opt_state = jax.jit(init_fn)(self._rng)
        self._param_shardings = param_sh
        self._opt_shardings = opt_sh
        self._setup_param_host_offload()
        # scalars live replicated on the mesh so every jitted fn (and every
        # checkpoint restore) sees one consistent device set
        rep = NamedSharding(mesh, P())
        self.loss_scale_state = jax.device_put(
            init_loss_scale(self.config.fp16), rep)
        self.step_count = jax.device_put(jnp.asarray(0, jnp.int32), rep)

    # ------------------------------------------------------------------
    def _build_step_fns(self):
        cfg = self.config
        plan = self.plan
        grad_sh = plan.grad_shardings(self._axes)
        param_sh = self._param_shardings
        cdt = self.compute_dtype
        gas = self.gradient_accumulation_steps
        fp16 = cfg.fp16.enabled
        grad_clip = cfg.gradient_clipping

        # trace-scoped qwZ arming: only THIS engine's traces see the
        # quantized fetch (a second engine in the process must not flip it)
        qwz_bits = 8 if self._qwz_stage3 else None

        from deepspeed_tpu.parallel import pipeline as pipe_mod

        pp_defaults = pipe_mod.schedule_defaults(cfg.pipeline.microbatches,
                                                 cfg.pipeline.window,
                                                 cfg.pipeline.schedule)

        def model_loss(params, batch):
            with shard_lib.qwz_context(qwz_bits), pp_defaults:
                return self.model.loss(params, batch)

        def loss_of(params, batch, scale):
            loss, aux = model_loss(params, batch)
            return loss * scale, (loss, aux)

        def fwd_bwd(params, batch, scale):
            """One microbatch: loss + fp32 grads (grad-sharding applied →
            stage-2 reduce-scatter happens here)."""
            (scaled, (loss, _aux)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, batch, scale)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            grads = _constrain_tree(grads, grad_sh)
            return loss, grads

        def apply_update(params, opt_state, ls_state, step, grads, ntokens):
            overflow = (has_overflow(grads) if fp16
                        else jnp.asarray(False))
            scale = ls_state.scale if fp16 else None
            params, opt_state, gnorm = apply_mixed_precision_update(
                opt_state, grads, self.tx, cdt, grad_clip=grad_clip,
                grad_scale=scale, skip=overflow if fp16 else None)
            params = _constrain_tree(params, param_sh)
            new_ls = (update_loss_scale(ls_state, overflow, cfg.fp16)
                      if fp16 else ls_state)
            new_step = step + jnp.where(overflow, 0, 1).astype(jnp.int32)
            lr = (self.lr_schedule(step) if self.lr_schedule
                  else jnp.asarray(self._base_lr or 0.0))
            metrics = {"grad_norm": gnorm, "lr": lr,
                       "loss_scale": new_ls.scale,
                       "overflow": overflow}
            return params, opt_state, new_ls, new_step, metrics

        qgz = self._qgz_stage3
        if qgz:
            from deepspeed_tpu.runtime.qgz import qgz_reduce_tree

            n_groups = int(np.prod([self.mesh.shape.get(a, 1)
                                    for a in topo.BATCH_AXES]))
            sp_n = self.mesh.shape.get("sp", 1)

            def _group_batches(batches):
                """[gas, B, ...] leaves → [gas, G, B/G, ...] with the
                group dim on the batch axes. The sequence dim is left
                unconstrained — the model's own activation constraints
                re-pin it to sp inside each group's trace, and a "sp"
                entry here trips an XLA SPMD-partitioner grouped-sharding
                CHECK (num_groups mismatch) when combined with the
                vmapped group axis."""
                def reshape(x):
                    return lax.with_sharding_constraint(
                        x.reshape(x.shape[0], n_groups,
                                  x.shape[1] // n_groups, *x.shape[2:]),
                        NamedSharding(self.mesh, P(None, topo.BATCH_AXES)))

                return jax.tree.map(reshape, batches)

        def train_step(params, opt_state, ls_state, step, batches):
            """Fused GAS boundary: grads of a scan over microbatches —
            one reduction per boundary, remat caps activation memory."""
            scale = ls_state.scale if fp16 else jnp.asarray(1.0, jnp.float32)

            def total_loss(params):
                if gas == 1:
                    # no microbatch loop: a scan-of-one still nests a
                    # while-loop around the model's own (chunk/tile)
                    # loops, and on TPU that extra level can push the
                    # hosted-FPDT backward's DMA loop nests past the
                    # compiler's int32 bounds check
                    mb = jax.tree.map(lambda b: b[0], batches)
                    scaled, (loss, aux) = loss_of(params, mb, scale)
                    return scaled, (loss[None], jnp.asarray(
                        aux.get("ntokens", 0.0), jnp.float32)[None])

                def body(carry, mb):
                    scaled, (loss, aux) = loss_of(params, mb, scale)
                    return carry + scaled / gas, (loss, aux.get("ntokens", 0.0))
                total, (losses, ntoks) = lax.scan(
                    body, jnp.asarray(0.0, jnp.float32), batches)
                return total, (losses, ntoks)

            if qgz:
                # qgZ: one gradient per batch-shard group (no implicit
                # GSPMD reduction), then explicit quantized-wire reduce
                def per_group(params, mbs):
                    def body(carry, mb):
                        scaled, (loss, aux) = loss_of(params, mb, scale)
                        return (carry + scaled / gas,
                                (loss, aux.get("ntokens", 0.0)))
                    total, (losses, ntoks) = lax.scan(
                        body, jnp.asarray(0.0, jnp.float32), mbs)
                    return total, (losses, ntoks)

                from deepspeed_tpu.runtime import sharding as shard_lib

                grouped = _group_batches(batches)
                # the group dim carries the batch axes; activation
                # constraints inside the mapped trace must not re-pin
                # them (sharding.vmapped_axes)
                with shard_lib.vmapped_axes(topo.BATCH_AXES):
                    (_, (losses_g, ntoks_g)), g_groups = jax.vmap(
                        jax.value_and_grad(per_group, has_aux=True),
                        in_axes=(None, 1))(params, grouped)
                g_groups = jax.tree.map(
                    lambda g: g.astype(jnp.float32), g_groups)
                grads = qgz_reduce_tree(g_groups, grad_sh, self.mesh)
                losses = jnp.mean(losses_g, axis=0)
                ntoks = jnp.sum(ntoks_g, axis=0)
            else:
                (_, (losses, ntoks)), grads = jax.value_and_grad(
                    total_loss, has_aux=True)(params)
                grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
                grads = _constrain_tree(grads, grad_sh)
            params, opt_state, new_ls, new_step, metrics = apply_update(
                params, opt_state, ls_state, step, grads, ntoks)
            metrics["loss"] = jnp.mean(losses)
            return params, opt_state, new_ls, new_step, metrics

        opt_sh = self._opt_shardings
        off_cfg = cfg.zero_optimization.offload_optimizer
        grad_xfer_bf16 = (off_cfg is not None
                          and off_cfg.grad_transfer_dtype == "bf16")

        def grad_step(params, batches, scale):
            """Offload path: (loss-scaled) grads only — the update happens
            host-side in the native CPU optimizer (runtime/offload.py),
            which unscales by grad_scale. grad_transfer_dtype=bf16 halves
            device->host volume and feeds the native bf16-grad kernel.
            Under qgZ the cross-shard reduction is the quantized-wire
            construction (the wire quantizes BEFORE the host grad copy —
            reference applies all_to_all_quant_reduce in offload configs
            too, coalesced_collectives.py:31)."""

            def total_loss(params):
                if gas == 1:
                    # see train_step.total_loss: no scan-of-one wrapper
                    mb = jax.tree.map(lambda b: b[0], batches)
                    loss, aux = model_loss(params, mb)
                    return loss * scale, loss[None]

                def body(carry, mb):
                    loss, aux = model_loss(params, mb)
                    return carry + loss * scale / gas, loss

                total, losses = lax.scan(body, jnp.asarray(0.0, jnp.float32),
                                         batches)
                return total, losses

            if qgz:
                def per_group(p, mbs):
                    def body(carry, mb):
                        loss, aux = model_loss(p, mb)
                        return carry + loss * scale / gas, loss

                    total, losses = lax.scan(
                        body, jnp.asarray(0.0, jnp.float32), mbs)
                    return total, losses

                from deepspeed_tpu.runtime import sharding as shard_lib

                grouped = _group_batches(batches)
                with shard_lib.vmapped_axes(topo.BATCH_AXES):
                    (_, losses_g), g_groups = jax.vmap(
                        jax.value_and_grad(per_group, has_aux=True),
                        in_axes=(None, 1))(params, grouped)
                g_groups = jax.tree.map(
                    lambda g: g.astype(jnp.float32), g_groups)
                grads = qgz_reduce_tree(g_groups, grad_sh, self.mesh)
                losses = jnp.mean(losses_g, axis=0)
            else:
                (_, losses), grads = jax.value_and_grad(
                    total_loss, has_aux=True)(params)
            xfer = jnp.bfloat16 if grad_xfer_bf16 else jnp.float32
            grads = jax.tree.map(lambda g: g.astype(xfer), grads)
            grads = _constrain_tree(grads, opt_sh)
            return grads, jnp.mean(losses)

        donate = (0, 1, 2, 3)
        self._jit_train_step = jax.jit(train_step, donate_argnums=donate)
        self._jit_grad_step = jax.jit(grad_step)
        if self._onebit:
            self._jit_onebit = jax.jit(self._onebit_step_fn,
                                       donate_argnums=(0, 1))
        if self._zeropp:
            self._jit_zeropp = jax.jit(self._zeropp_step_fn,
                                       donate_argnums=(0, 1))
        # offload resharding hops: host-updated (optimizer-sharded) tree →
        # param sharding = the "allgather updated partitions" collective,
        # compiled by XLA over ICI; and grad-acc → optimizer sharding.
        self._jit_reshard_to_params = jax.jit(lambda t: t,
                                              out_shardings=param_sh)
        stream_paths = [
            k for k in getattr(self, "_host_param_paths", ("layers",))
            if isinstance(param_sh, dict) and k in param_sh]
        if getattr(self, "_param_host_offload", False) and stream_paths:
            # updated streamed params land straight in pinned host memory
            # — the full stack must never materialize in HBM (the point
            # of offload_param). XLA rejects host-kind out_shardings on
            # replicated leaves inside jit ("side-effect ops cannot be
            # replicated"), so this reshard runs as an out-of-jit
            # device_put over a sharding tree instead.
            host_sh = dict(param_sh)
            for key in stream_paths:
                host_sh[key] = jax.tree.map(
                    lambda s: memspace.with_memory_kind(s, "pinned_host"),
                    param_sh[key])
            self._jit_reshard_to_params = lambda t: jax.device_put(
                t, host_sh)
        self._jit_to_opt_sharding = jax.jit(
            lambda t: t, out_shardings=opt_sh)
        self._jit_fwd_bwd = jax.jit(fwd_bwd)
        self._jit_apply = jax.jit(apply_update, donate_argnums=(0, 1, 2, 3, 4))
        self._jit_eval = jax.jit(model_loss)
        self._jit_accumulate = jax.jit(
            lambda acc, g, c: jax.tree.map(lambda a, b: a + b * c, acc, g),
            donate_argnums=(0,))

    # ------------------------------------------------------------------
    # data plumbing
    # ------------------------------------------------------------------
    def _batch_sharding(self, leading_dims: int = 1):
        spec = [topo.BATCH_AXES] + [None] * 0
        if leading_dims == 2:  # [gas, batch, ...]
            spec = [None, topo.BATCH_AXES]
        return NamedSharding(self.mesh, P(*spec))

    def shard_batch(self, batch, leading_dims: int = 1):
        """Host batch (numpy tree, per-process slice) → global device arrays."""
        sh = self._batch_sharding(leading_dims)

        def put(x):
            x = np.asarray(x)
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)

        return jax.tree.map(put, batch)

    def _next_microbatches(self, data_iter, n: int):
        out = []
        for i in range(n):
            if self._chaos is not None:
                self._chaos.on_input_batch()
            try:
                out.append(next(data_iter))
            except StopIteration:
                if i == 0:
                    raise  # clean end-of-data at a boundary
                raise RuntimeError(
                    f"data iterator exhausted mid-gradient-accumulation "
                    f"(got {i} of {n} microbatches): wrap the loader in "
                    "deepspeed_tpu.runtime.dataloader.RepeatingLoader so "
                    "epochs restart at the boundary") from None
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *out)
        return self.shard_batch(stacked, leading_dims=2)

    def _next_batches(self, data_iter):
        """Stacked+sharded microbatches for one boundary, routed through
        the background prefetcher when the caller is streaming.

        Promotion heuristic: the first time an iterator is seen it is
        pulled synchronously (a one-shot ``iter([batch])`` must not be
        consumed ahead of the caller); passing the SAME iterator again
        means the caller treats it as a stream, so it is handed to a
        :class:`PrefetchingIterator` whose worker pulls/stacks/transfers
        the next boundaries while the current step computes. Multi-host
        runs stay synchronous (cross-host transfer issue order)."""
        gas = self.gradient_accumulation_steps
        if self._prefetch_depth <= 0 or jax.process_count() > 1:
            return self._next_microbatches(data_iter, gas)
        if data_iter is self._prefetch_source:
            if self._prefetcher is None:
                self._prefetcher = PrefetchingIterator(
                    lambda: self._next_microbatches(data_iter, gas),
                    depth=self._prefetch_depth, name="train-input")
            return next(self._prefetcher)
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
        self._prefetch_source = data_iter
        return self._next_microbatches(data_iter, gas)

    # ------------------------------------------------------------------
    # reference-parity training API
    # ------------------------------------------------------------------
    def _effective_depth(self) -> int:
        """Dispatch-ahead window for the next step. Paths that must read
        host values inside the step (host-optimizer offload) or that
        observe engine state step-by-step (post-step hooks) force the
        blocking loop."""
        if self._dispatch_ahead <= 0:
            return 0
        if self._offload is not None:
            return 0  # host optimizer reads grads/gnorm synchronously
        if self._post_step_hooks:
            return 0  # hooks expect a settled engine after every step
        return self._dispatch_ahead

    def train_batch(self, data_iter=None) -> jax.Array:
        """One full training step (micro × GAS) — the fast path
        (reference PipelineEngine.train_batch pipe/engine.py:337 naming).

        With ``performance.pipeline_depth`` K >= 1 the returned loss is
        an async ``jax.Array``: up to K dispatched steps stay in flight
        and the per-step host reads (overflow accounting, steps_per_print
        logging, monitor/hub rows) defer until each step's metrics
        resolve at drain time, so the host never sits on the device
        critical path. ``synchronize()`` drains the window. K = 0 is the
        blocking loop, bit-identical to the pre-pipelined behavior."""
        if data_iter is None:
            if self.training_dataloader is None:
                raise ValueError("train_batch needs data_iter or training_data")
            data_iter = iter(self.training_dataloader)
        self._last_data_iter = data_iter  # data_cursor loader-state source
        depth = self._effective_depth()
        sync = depth == 0
        host_t0 = time.perf_counter()
        if sync:
            self.timers(TRAIN_BATCH_TIMER).start()
            self.tput_timer.start()
        batches = self._next_batches(data_iter)
        step_no = self.global_steps + 1
        if self._chaos is not None:
            self._chaos.on_step(step_no)
        if self.flight is not None:
            self.flight.record("step_entry", step=step_no,
                               inflight=len(self._inflight))
        if self._trace_capture is not None:
            self._trace_capture.on_step_begin(step_no)
        if sync and self.watchdog is not None:
            # armed until the step's results are blocked on below: a
            # wedged collective fires a stack/memory report
            self.watchdog.arm(step_no)
        with topo.use_mesh(self.mesh):
            metrics = self._dispatch_train_step(batches)
        dispatch_t = time.perf_counter()
        if self.flight is not None:
            self.flight.record(
                "step_dispatch", step=step_no,
                host_ms=round((dispatch_t - host_t0) * 1000.0, 3))
        # dispatch-order bookkeeping; the host READS defer to the drain
        self.global_steps += 1
        self.global_samples += self.train_batch_size
        for hook in self._post_step_hooks:
            hook(self)
        self._ckpt_io.maybe_commit()
        self._inflight.append(_InflightStep(
            step=step_no, metrics=metrics,
            struct=jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batches),
            samples=self.global_samples,
            host_ms=(dispatch_t - host_t0) * 1000.0,
            dispatch_t=dispatch_t, host_t0=host_t0, sync=sync))
        if not sync and self.watchdog is not None:
            # one deadline budgets the whole in-flight window (the oldest
            # step's deadline scaled by the window size) — between
            # train_batch calls the window stays armed, so a wedged
            # collective inside it still fires a report
            self.watchdog.arm(self._inflight[0].step,
                              window=len(self._inflight))
        while len(self._inflight) > depth:
            self._drain_one()
        if (self._preempt_guard is not None
                and self._preempt_guard.should_checkpoint()):
            # GAS boundary after a preemption notice: drain the window
            # and land an emergency checkpoint before the grace runs out
            self._emergency_checkpoint()
        return metrics["loss"]

    def _drain_one(self) -> None:
        """Resolve the oldest in-flight step: block on its metrics, then
        run its deferred host reads and emit its trace row."""
        entry = self._inflight.popleft()
        metrics = entry.metrics
        if entry.sync:
            # blocking path: identical ordering to the classic loop
            self._after_step_host(metrics, entry.step, entry.samples)
            self.timers(TRAIN_BATCH_TIMER).stop(block=metrics["loss"])
            wall_ms = self._last_step_wall_ms()
            if self._trace_capture is not None:
                self._trace_capture.on_step_end(entry.step)
            if self.watchdog is not None:
                self.watchdog.disarm()
                self.watchdog.observe(wall_ms / 1000.0, entry.step)
            self._last_drain_t = time.perf_counter()
        else:
            jax.block_until_ready(metrics["loss"])
            resolved_t = time.perf_counter()
            # drain-to-drain span ≈ this step's device time once the
            # pipeline is full; during fill it degrades to dispatch→done
            base = (entry.host_t0 if self._last_drain_t is None
                    else max(self._last_drain_t, entry.host_t0))
            wall_ms = (resolved_t - base) * 1000.0
            self._last_drain_t = resolved_t
            self._after_step_host(metrics, entry.step, entry.samples,
                                  wall_s=wall_ms / 1000.0)
            self.timers(TRAIN_BATCH_TIMER).record_ms(wall_ms)
            if self._trace_capture is not None:
                self._trace_capture.on_step_end(entry.step)
            if self.watchdog is not None:
                self.watchdog.observe(wall_ms / 1000.0, entry.step)
                if self._inflight:
                    self.watchdog.arm(self._inflight[0].step,
                                      window=len(self._inflight))
                else:
                    self.watchdog.disarm()
        if self.flight is not None:
            self.flight.record("step_drain", step=entry.step,
                               wall_ms=round(wall_ms, 3),
                               inflight=len(self._inflight))
        if self.hub is not None:
            self._emit_step_trace(entry.step, metrics, entry.struct,
                                  wall_ms, host_gap_ms=entry.host_ms,
                                  samples=entry.samples,
                                  inflight=len(self._inflight))

    def synchronize(self) -> "Engine":
        """Drain every dispatched-but-unresolved train step (pipeline
        barrier for the dispatch-ahead loop): blocks until all in-flight
        metrics resolve and their deferred host reads — overflow/skip
        counts, logging, monitor and hub rows — have run. The engine
        calls it at checkpoint/eval/state-export boundaries; call it
        manually before reading engine counters mid-run or at exit. A
        no-op under the blocking loop."""
        while self._inflight:
            self._drain_one()
        return self

    def _emergency_checkpoint(self) -> None:
        """Preemption-notice path: drain, save, force-commit — bounded by
        ``resilience.preemption_save_deadline_s``. Sets ``preempted`` so
        the training loop can exit cleanly; a torn save is harmless (no
        manifest ⇒ auto-resume falls back to the previous good tag)."""
        guard = self._preempt_guard
        rcfg = self._resilience_cfg
        save_dir = ((getattr(rcfg, "emergency_save_dir", None)
                     if rcfg is not None else None)
                    or self._last_save_dir)
        self.preempted = True
        if self.flight is not None:
            self.flight.record("preempt_drain", step=self.global_steps,
                               inflight=len(self._inflight))
        self.synchronize()
        if save_dir is None:
            logger.error(
                "resilience: preemption notice but no checkpoint dir is "
                "known (no prior save_checkpoint and no "
                "resilience.emergency_save_dir) — exiting WITHOUT an "
                "emergency save")
            if self.flight is not None:
                self.flight.record("preempt_save_skipped", reason="no_dir")
            return
        from deepspeed_tpu.resilience.policy import (_DeadlineExpired,
                                                     run_with_deadline)

        t0 = time.perf_counter()

        def _save():
            self.save_checkpoint(save_dir)
            self._ckpt_io.commit_pending()  # async engines: force durable

        try:
            if jax.process_count() > 1:
                # multi-host publish runs collectives that must issue
                # from this thread in lockstep on every rank — the
                # deadline is advisory there (the scheduler's SIGKILL is
                # the real bound)
                _save()
            else:
                run_with_deadline(_save, guard.save_deadline_s,
                                  name="preempt_save")
        except _DeadlineExpired:
            logger.error(
                f"resilience: emergency checkpoint blew its "
                f"{guard.save_deadline_s:g}s deadline — exiting with the "
                "save incomplete (manifest validation will reject it and "
                "resume from the previous good tag)")
            if self.flight is not None:
                self.flight.record("preempt_save_timeout",
                                   deadline_s=guard.save_deadline_s)
            return
        wall = time.perf_counter() - t0
        if self.flight is not None:
            self.flight.record("preempt_save_done",
                               step=self.global_steps,
                               wall_ms=round(wall * 1000.0, 1))
        logger.warning(
            f"resilience: emergency checkpoint committed to {save_dir} "
            f"in {wall:.2f}s; engine.preempted=True — stop training and "
            "exit")

    def _dispatch_train_step(self, batches):
        lr_over = jnp.asarray(
            self._lr_override if self._lr_override is not None
            else float("nan"), jnp.float32)
        if self._onebit:
            self.params, self._onebit_state, metrics = self._jit_onebit(
                self.params, self._onebit_state, batches, lr_over)
            self.step_count = self._onebit_state.step
        elif self._zeropp:
            self.params, self._zeropp_state, metrics = self._jit_zeropp(
                self.params, self._zeropp_state, batches, lr_over)
            self.step_count = self._zeropp_state.step
        elif self._offload is not None:
            scale = (self.loss_scale_state.scale if self.config.fp16.enabled
                     else jnp.asarray(1.0, jnp.float32))
            grads, loss = self._jit_grad_step(self.params, batches, scale)
            metrics = self._offload_apply(grads, loss)
        else:
            (self.params, self.opt_state, self.loss_scale_state,
             self.step_count, metrics) = self._jit_train_step(
                self.params, self.opt_state, self.loss_scale_state,
                self.step_count, batches)
        return metrics

    def forward(self, batch, *args, **kwargs):
        """Micro-step path: compute loss (grads cached for backward)."""
        if self._onebit or self._zeropp:
            raise RuntimeError(
                "1-bit/ZeRO++ quantized optimizers support the fused "
                "train_batch() path only (the compressed collective lives "
                "inside the compiled step); use engine.train_batch(...)")
        self.timers(FORWARD_GLOBAL_TIMER).start()
        batch = self.shard_batch(batch)
        scale = (self.loss_scale_state.scale if self.config.fp16.enabled
                 else jnp.asarray(1.0, jnp.float32))
        with topo.use_mesh(self.mesh):
            loss, grads = self._jit_fwd_bwd(self.params, batch, scale)
        self._pending = (loss, grads)
        self.timers(FORWARD_GLOBAL_TIMER).stop(block=loss)
        return loss

    __call__ = forward

    def backward(self, loss=None, retain_graph: bool = False):
        """Accumulate the cached grads (reference engine.backward
        engine.py:3066)."""
        if self._pending is None:
            raise RuntimeError("backward() called without a prior forward()")
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        _, grads = self._pending
        self._pending = None
        coef = jnp.asarray(1.0 / self.gradient_accumulation_steps, jnp.float32)
        if self._grad_acc is None:
            self._grad_acc = jax.tree.map(lambda g: g * coef, grads)
        else:
            self._grad_acc = self._jit_accumulate(self._grad_acc, grads, coef)
        self.micro_steps += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        """Reference engine.py:3270."""
        return self.micro_steps % self.gradient_accumulation_steps == 0

    def step(self):
        """Apply the update at the GAS boundary (reference engine.py:3241)."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self._grad_acc is None:
            raise RuntimeError("step() called without accumulated gradients")
        self.timers(STEP_GLOBAL_TIMER).start()
        if self._offload is not None:
            grads = self._jit_to_opt_sharding(self._grad_acc)
            metrics = self._offload_apply(grads, None)
        else:
            (self.params, self.opt_state, self.loss_scale_state,
             self.step_count, metrics) = self._jit_apply(
                self.params, self.opt_state, self.loss_scale_state,
                self.step_count, self._grad_acc, jnp.asarray(0.0))
        self._grad_acc = None
        self._after_step(metrics)
        self.timers(STEP_GLOBAL_TIMER).stop()

    def _maybe_build_zenflow(self, params_fp32):
        """Config-driven ZenFlow (reference zenflow_stage_1_and_2.py:47
        enablement via the zero_optimization.zenflow block): replaces the
        blocking host step with top-k on-device updates + an overlapped
        host pass. Multi-host: each process's host optimizer owns its
        devices' shards (per-shard masters in runtime/zenflow.py); device
        selection/updates are plain SPMD jits, so no full leaf is ever
        flattened host-side."""
        zf = self.config.zero_optimization.zenflow
        if zf is None:
            return None
        if self.config.zero_optimization.offload_param is not None and \
                self.config.zero_optimization.offload_param.device != "none":
            logger.warning("zenflow does not compose with offload_param "
                           "streaming; falling back to the blocking "
                           "offload step")
            return None
        from deepspeed_tpu.runtime.zenflow import (ZenFlowConfig,
                                                   ZenFlowOptimizer)

        ocfg = self.config.optimizer
        p = dict((ocfg.params or {}) if ocfg else {})
        cfg = ZenFlowConfig(
            topk_ratio=zf.topk_ratio, update_interval=zf.update_interval,
            select_interval=zf.select_interval,
            overlap_step=zf.overlap_step,
            workers=getattr(zf, "workers", 1),
            betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0))
        return ZenFlowOptimizer(params_fp32, cfg,
                                lr=p.get("lr", self._base_lr or 1e-3),
                                param_dtype=self.compute_dtype)

    def _setup_param_host_offload(self) -> None:
        """ZeRO-Infinity param tier (reference offload_config.py:21
        offload_param + partitioned_param_swapper semantics): layer
        params move to pinned host memory and the model's scan streams
        one layer at a time to HBM (models/transformer.py
        param_host_offload path). Requires the host optimizer tier."""
        pcfg = self.config.zero_optimization.offload_param
        self._param_host_offload = bool(
            pcfg is not None and pcfg.device != "none")
        if not self._param_host_offload:
            return
        if pcfg.device == "nvme":
            logger.warning("offload_param.device='nvme': layer params are "
                           "held in pinned host RAM (the NVMe tier applies "
                           "to optimizer state); proceeding with cpu "
                           "placement")
        if self._offload is None:
            # (1-bit/ZeRO++ cannot reach here: their validators/gating
            # already reject or disable themselves under optimizer
            # offload, so _offload is always set when offload_optimizer
            # is configured)
            raise ValueError(
                "offload_param requires offload_optimizer (the ZeRO-"
                "Infinity pairing): add zero_optimization."
                "offload_optimizer.device='cpu'")
        if self.mesh.shape.get("pp", 1) > 1:
            raise ValueError("offload_param does not compose with the "
                             "pipeline-parallel layer path yet")
        mcfg = getattr(self.model, "config", None)
        if getattr(self.model, "host_param_paths", None) is not None:
            # model-agnostic protocol (runtime/param_stream.py): the
            # model declares which top-level stacked subtrees stream
            # (self._host_param_paths, set at init) and consults
            # model.param_host_offload in its apply
            self.model.param_host_offload = True
        elif mcfg is not None and hasattr(mcfg, "param_host_offload"):
            updates = {}
            if not mcfg.param_host_offload:
                updates["param_host_offload"] = True
            if not getattr(mcfg, "remat", True):
                # without remat every fetched layer is saved as a backward
                # residual and the full stack materializes in HBM anyway —
                # force the streaming-compatible mode on
                logger.warning("offload_param requires per-layer remat to "
                               "keep the stack out of HBM; enabling remat")
                updates["remat"] = True
            if updates:
                import dataclasses as _dc

                self.model.config = _dc.replace(mcfg, **updates)
        else:
            raise ValueError(
                "offload_param needs a model that supports streaming: "
                "either config.param_host_offload (TransformerLM family) "
                "or the host_param_paths protocol "
                "(runtime/param_stream.py)")
        self.params = self._place_layer_params_on_host(self.params)
        log_dist("offload_param: layer params pinned to host memory; "
                 "the compiled step streams one layer at a time", ranks=[0])

    def _place_layer_params_on_host(self, params):
        # host copies are staged in FP32: sub-32-bit host->device streaming
        # is not supported by current TPU runtimes, and fp32 is the master
        # precision anyway (the layer body casts to compute dtype right
        # after the fetch, so HBM holds one fp32 layer transiently)
        from deepspeed_tpu.runtime.param_stream import pin_to_host

        paths = getattr(self, "_host_param_paths", ("layers",))
        if not isinstance(params, dict):
            return params
        out = dict(params)
        for key in paths:
            if key in out:
                out[key] = pin_to_host(out[key])
        return out

    def _offload_apply(self, grads, loss):
        """Host-side optimizer step (ZeRO-Offload boundary): device grads
        → native CPU optimizer → resharded device params."""
        lr = (float(self.lr_schedule(self.step_count)) if self.lr_schedule
              else float(self._base_lr or 0.0))
        fp16 = self.config.fp16.enabled
        scale = float(self.loss_scale_state.scale) if fp16 else None
        if self._zenflow is not None:
            import optax

            # one fused coefficient applies unscaling + clipping; gnorm
            # stays a device scalar (no host sync) unless fp16 needs the
            # overflow decision
            gnorm = optax.global_norm(grads)
            if scale and scale != 1.0:
                gnorm = gnorm / scale
            coef = jnp.asarray(1.0 / (scale or 1.0), jnp.float32)
            clip = self.config.gradient_clipping
            if clip and clip > 0:
                coef = coef * jnp.minimum(1.0, clip / (gnorm + 1e-6))
            if (clip and clip > 0) or (scale and scale != 1.0):
                grads = jax.tree.map(lambda g: g * coef.astype(g.dtype),
                                     grads)
            overflow = bool(fp16 and not np.isfinite(float(gnorm)))
            new_tree = (None if overflow
                        else self._zenflow.step(grads, self.params, lr=lr))
        else:
            new_tree, gnorm, overflow = self._offload.step(
                grads, self.params, lr=lr, grad_scale=scale,
                skip_on_nonfinite=fp16)
        if not overflow:
            # reshard targets host memory kind for layers under
            # offload_param (out_shardings in _build_step_fns)
            self.params = self._jit_reshard_to_params(new_tree)
            self.step_count = self.step_count + 1
        if fp16:
            self.loss_scale_state = jax.device_put(
                update_loss_scale(self.loss_scale_state,
                                  jnp.asarray(overflow), self.config.fp16),
                NamedSharding(self.mesh, P()))
        self._last_grad_norm = gnorm
        metrics = {"grad_norm": jnp.asarray(gnorm), "lr": jnp.asarray(lr),
                   "loss_scale": self.loss_scale_state.scale,
                   "overflow": jnp.asarray(overflow)}
        if loss is not None:
            metrics["loss"] = loss
        return metrics

    def eval_batch(self, batch):
        self.synchronize()  # eval boundary: settle the in-flight window
        batch = self.shard_batch(batch)
        with topo.use_mesh(self.mesh):
            loss, _aux = self._jit_eval(self.params, batch)
        return loss

    def set_custom_curriculum_learning_schedule(self, fn):
        """Reference engine API: plug a step→difficulty callable into the
        curriculum scheduler (requires a 'custom' curriculum config)."""
        if self.curriculum_scheduler is None:
            raise RuntimeError(
                "no curriculum scheduler: enable data_efficiency with a "
                "curriculum_metrics block first")
        self.curriculum_scheduler.set_custom_get_difficulty(fn)

    def get_data_difficulty(self) -> Optional[int]:
        """Current curriculum difficulty (None when curriculum is off)."""
        if self.curriculum_scheduler is None:
            return None
        return self.curriculum_scheduler.get_difficulty(self.global_steps)

    def register_post_step_hook(self, fn):
        """``fn(engine)`` runs after every optimizer step (compression
        re-masking, progressive layer drop, custom callbacks)."""
        self._post_step_hooks.append(fn)
        return fn

    def _after_step(self, metrics):
        """Synchronous post-step (micro-step ``step()`` path): dispatch
        bookkeeping plus the host reads in one go."""
        self.global_steps += 1
        self.global_samples += self.train_batch_size
        for hook in self._post_step_hooks:
            hook(self)
        # decoupled checkpoint engine: publish a finished async save at the
        # GAS boundary (reference engine.py:3273)
        self._ckpt_io.maybe_commit()
        self._after_step_host(metrics, self.global_steps,
                              self.global_samples)

    def _after_step_host(self, metrics, step_no, samples, wall_s=None):
        """Per-step host reads. Under dispatch-ahead these run at drain
        time — reading ``overflow`` forces the sync, so deferring them is
        what keeps the host off the critical path; ``step_no``/``samples``
        are the step's own snapshots, not the engine's current counters.
        ``wall_s`` set means the span was measured externally
        (drain-to-drain) instead of by the throughput timer's start/stop
        pair."""
        if bool(metrics.get("overflow", False)):
            self.skipped_steps += 1
        if wall_s is None:
            self.tput_timer.stop(global_step=True)
        else:
            self.tput_timer.record(wall_s)
        if step_no % self.config.steps_per_print == 0:
            loss = metrics.get("loss")
            loss_s = f"loss={float(loss):.4f}, " if loss is not None else ""
            log_dist(
                f"step={step_no}, {loss_s}"
                f"lr={float(metrics['lr']):.3e}, "
                f"grad_norm={float(metrics['grad_norm']):.3f}", ranks=[0])
        if self.monitor is not None and self.monitor.enabled:
            events = [("Train/Samples/train_loss",
                       float(metrics.get("loss", 0.0)), samples),
                      ("Train/Samples/lr", float(metrics["lr"]),
                       samples)]
            self.monitor.write_events(events)
        if self.config.wall_clock_breakdown and \
                step_no % self.config.steps_per_print == 0:
            self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                             STEP_GLOBAL_TIMER, TRAIN_BATCH_TIMER])
        fp = self.config.flops_profiler
        if fp.enabled and step_no == fp.profile_step \
                and jax.process_index() == 0:
            # rank 0 only: the profile recompiles the step (lowering is
            # process-local, no collectives run) and writes output_file
            from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler

            prof = FlopsProfiler(engine=self)
            prof.start_profile()
            prof.stop_profile()
            prof.print_model_profile(profile_step=fp.profile_step,
                                     module_depth=fp.module_depth,
                                     top_modules=fp.top_modules,
                                     detailed=fp.detailed,
                                     output_file=fp.output_file)
            prof.end_profile()

    def _build_monitor(self):
        try:
            from deepspeed_tpu.monitor.monitor import MonitorMaster

            return MonitorMaster(self.config.monitor)
        except Exception as e:
            logger.debug(f"monitor disabled: {e}")
            return None

    # ------------------------------------------------------------------
    # observability (docs/observability.md)
    # ------------------------------------------------------------------
    def _last_step_wall_ms(self) -> float:
        records = self.timers(TRAIN_BATCH_TIMER).records
        return records[-1] if records else 0.0

    def _on_stall_report(self, report: str) -> None:
        if self.hub is not None:
            self.hub.counter_add("train.stalls")
            self.hub.record_event("stall_report", step=self.global_steps,
                                  report=report)

    def _batch_tokens(self, batches):
        """Trained tokens in one train_batch: gas * B * S with input_ids
        [gas, B, S+1] (next-token objective trains S positions per
        sequence — the same count bench.py divides by)."""
        try:
            ids = batches.get("input_ids") if hasattr(batches, "get") \
                else None
            if ids is None:
                leaves = jax.tree.leaves(batches)
                ids = leaves[0] if leaves else None
            if ids is None or ids.ndim < 2 or ids.shape[-1] < 2:
                return None
            return int(np.prod(ids.shape[:-1])) * (ids.shape[-1] - 1)
        except Exception:
            return None

    def _model_flops_per_token(self):
        if self._flops_per_token is None:
            fn = getattr(self.model, "flops_per_token", None)
            try:
                self._flops_per_token = float(fn()) if callable(fn) else 0.0
            except Exception:
                self._flops_per_token = 0.0
        return self._flops_per_token or None

    def _emit_step_trace(self, step_no, metrics, struct, wall_ms,
                         host_gap_ms=None, samples=None,
                         inflight=0) -> None:
        try:
            from deepspeed_tpu.observability import StepTrace
            from deepspeed_tpu.observability import roofline as _rl
            from deepspeed_tpu.utils.memory import device_memory_stats

            samples = self.global_samples if samples is None else samples
            self._last_batches_struct = struct
            dt = wall_ms / 1000.0
            tokens = self._batch_tokens(struct)
            n_chips = max(1, len(jax.devices()))
            tps = tokens / dt if (tokens and dt > 0) else None
            tps_chip = tps / n_chips if tps else None
            mfu_val = fpt = peak = None
            if tps_chip:
                fpt = self._model_flops_per_token()
                if fpt:
                    peak = _rl.detect_peak_tflops(jax.devices()[0])
                    mfu_val = _rl.mfu(tps_chip, fpt, peak)

            def _f(key):
                v = metrics.get(key)
                try:
                    return None if v is None else float(v)
                except Exception:
                    return None

            comm_total, comm_delta = self.hub.comm_deltas()
            compile_d = self.hub.compile_delta()
            trace = StepTrace(
                step=step_no, wall_ms=wall_ms, tokens=tokens,
                tokens_per_sec=tps, tokens_per_sec_per_chip=tps_chip,
                n_chips=n_chips, loss=_f("loss"),
                grad_norm=_f("grad_norm"), lr=_f("lr"),
                loss_scale=_f("loss_scale"),
                overflow=bool(metrics.get("overflow", False)),
                skipped_steps=self.skipped_steps,
                mfu=mfu_val, mfu_source="model" if mfu_val else None,
                flops_per_token=fpt, peak_tflops=peak,
                host_gap_ms=host_gap_ms, inflight=inflight,
                compile_events=int(compile_d["events"]),
                compile_secs=compile_d["secs"],
                comm_bytes_total=comm_total or None,
                comm_bytes_delta=comm_delta or None,
                device_mem=device_memory_stats())
            self.hub.record_step(trace)
            if self.monitor is not None and self.monitor.enabled and \
                    step_no % self.config.steps_per_print == 0:
                events = [("Train/Samples/step_seconds", dt, samples)]
                if tps is not None:
                    events.append(("Train/Samples/tokens_per_sec", tps,
                                   samples))
                if mfu_val is not None:
                    events.append(("Train/Samples/mfu", mfu_val, samples))
                self.monitor.write_events(events)
            if self._roofline_cost is None and step_no >= 2 and (
                    os.environ.get("DSTPU_ROOFLINE", "") == "1"
                    or getattr(self._obs_cfg, "xla_cost_analysis", False)):
                self.roofline()
        except Exception as e:  # observability must never fail the step
            logger.warning(f"step trace emission failed: {e}")

    def roofline(self, step_seconds=None):
        """Classify the compiled train step against the chip roofline.

        Lowers + compiles the active step function once more (XLA's
        ``cost_analysis`` lives on the compiled executable) and caches
        the cost — expensive for big models, hence opt-in via
        ``observability.xla_cost_analysis`` or ``DSTPU_ROOFLINE=1``
        (then it runs once, after step 2). Needs one prior
        ``train_batch`` for the batch shapes."""
        from deepspeed_tpu.observability import roofline as _rl
        from deepspeed_tpu.utils.hlo_bytes import program_costs

        if self._roofline_cost is None:
            if self._last_batches_struct is None:
                raise RuntimeError(
                    "roofline() needs one prior train_batch() (the batch "
                    "shapes come from it)")
            b = self._last_batches_struct
            lr_over = jnp.asarray(float("nan"), jnp.float32)
            with topo.use_mesh(self.mesh):
                if self._onebit:
                    lowered = self._jit_onebit.lower(
                        self.params, self._onebit_state, b, lr_over)
                elif self._zeropp:
                    lowered = self._jit_zeropp.lower(
                        self.params, self._zeropp_state, b, lr_over)
                elif self._offload is not None:
                    lowered = self._jit_grad_step.lower(
                        self.params, b, jnp.asarray(1.0, jnp.float32))
                else:
                    lowered = self._jit_train_step.lower(
                        self.params, self.opt_state, self.loss_scale_state,
                        self.step_count, b)
            self._roofline_cost = program_costs(lowered.compile())
        if step_seconds is None:
            wall = self._last_step_wall_ms()
            step_seconds = wall / 1000.0 if wall > 0 else None
        dev = jax.devices()[0]
        summary = _rl.roofline_summary(
            self._roofline_cost, _rl.detect_peak_tflops(dev),
            _rl.detect_hbm_gbps(dev), step_seconds=step_seconds)
        if self.hub is not None:
            self.hub.record_event("roofline", step=self.global_steps,
                                  **summary)
            self.hub.gauge("train.arithmetic_intensity",
                           summary["arithmetic_intensity"])
            if "hw_flops_utilization" in summary:
                self.hub.gauge("train.hw_flops_utilization",
                               summary["hw_flops_utilization"])
        return summary

    # ------------------------------------------------------------------
    # optimizer view + state accessors
    # ------------------------------------------------------------------
    @property
    def optimizer(self):
        return _OptimizerView(self)

    def get_lr(self):
        if self.lr_schedule is not None:
            return [float(self.lr_schedule(self.step_count))]
        return [self._base_lr or 0.0]

    def set_lr(self, lr: float) -> None:
        """Client lr override (the reference-common
        ``optimizer.param_groups[0]['lr'] = x`` pattern). The compiled
        step bakes the lr closure at trace time, so this rebuilds the
        step functions — recompilation happens on the next call (cheap
        relative to how rarely clients poke lr mid-run)."""
        if self._zeropp or getattr(self, "_onebit", False):
            # the ZeRO++ and 1-bit steps take lr as a runtime operand
            # (NaN = use the traced schedule), so no rebuild is needed
            self._lr_override = float(lr)
            self._base_lr = float(lr)
            if self.lr_schedule is not None:
                logger.warning("set_lr override disables the configured "
                               "lr schedule for the runtime-lr step")
                self.lr_schedule = None
            return
        if self._client_optimizer_present:
            raise NotImplementedError(
                "set_lr: the engine cannot re-point a client-supplied "
                "optax transform's lr; rebuild the transform and engine")
        self._base_lr = float(lr)
        if self.lr_schedule is not None:
            logger.warning("set_lr/param_groups override disables the "
                           "configured lr schedule")
            self.lr_schedule = None
        if self.config.optimizer is None:
            # the engine was built with the default transform — pin the
            # implied optimizer into the config so the rebuild below
            # carries the new lr (a skipped rebuild would silently keep
            # the old lr in the compiled step)
            from deepspeed_tpu.config.config import OptimizerConfig

            self.config.optimizer = OptimizerConfig(
                type="adamw", params={"lr": float(lr)})
        self.config.optimizer.params = dict(
            self.config.optimizer.params or {}, lr=float(lr))
        # rebuild the optax transform: the old tx closed over the
        # previous lr (state layout is unchanged — same optimizer)
        self.tx, _ = get_base_optimizer(self.config.optimizer, None)
        self._build_step_fns()

    # ------------------------------------------------------------------
    # state offload between phases (reference engine.offload_states
    # engine.py:5573 / reload_states — frees HBM for e.g. RLHF
    # generation with another model copy)
    # ------------------------------------------------------------------
    def offload_states(self, include=None, device: str = "cpu",
                       pin_memory: bool = True, non_blocking: bool = False):
        """Move engine-held device state to pinned host memory.

        ``include`` limits the set: any of {"lp_params", "optim_states"}
        (reference OffloadStateTypeEnum names accepted; grads have no
        persistent buffer here — they live inside the compiled step).
        """
        if device != "cpu":
            raise ValueError("offload_states supports device='cpu' only")
        self.synchronize()
        include = set(include or ("lp_params", "optim_states"))
        known = {"lp_params", "hp_params", "optim_states", "lp_grads",
                 "contiguous_grad_buffer"}
        unknown = include - known
        if unknown:
            raise ValueError(f"unknown offload_states entries {unknown}")

        def to_host(tree):
            return jax.tree.map(
                lambda a: jax.device_put(
                    a, memspace.with_memory_kind(a.sharding, "pinned_host"))
                if isinstance(a, jax.Array)
                and memspace.memories_supported()
                and a.sharding.memory_kind != "pinned_host" else a, tree)

        if include & {"lp_params", "hp_params"}:
            self.params = to_host(self.params)
        if "optim_states" in include and self.opt_state is not None:
            self.opt_state = to_host(self.opt_state)
        self._states_offloaded = True
        if self.flight is not None:
            self.flight.record("offload_states", step=self.global_steps,
                               include=sorted(include))

    def reload_states(self, non_blocking: bool = False):
        """Inverse of offload_states: device placement restored."""
        if not getattr(self, "_states_offloaded", False):
            return
        if self.flight is not None:
            self.flight.record("reload_states", step=self.global_steps)

        def to_device(tree):
            return jax.tree.map(
                lambda a: jax.device_put(
                    a, memspace.with_memory_kind(a.sharding, "device"))
                if isinstance(a, jax.Array)
                and memspace.memory_kind_of(a) == "pinned_host"
                else a, tree)

        if getattr(self, "_param_host_offload", False):
            # streamed params live on host by design; restore the rest
            paths = getattr(self, "_host_param_paths", ("layers",))
            kept = {k: self.params[k] for k in paths
                    if isinstance(self.params, dict) and k in self.params}
            self.params = to_device(self.params)
            if kept:
                self.params = dict(self.params)
                self.params.update(kept)
        else:
            self.params = to_device(self.params)
        if self.opt_state is not None:
            self.opt_state = to_device(self.opt_state)
        self._states_offloaded = False

    def get_global_grad_norm(self):
        return getattr(self, "_last_grad_norm", None)

    # -- reference-parity engine API ------------------------------------
    def no_sync(self):
        """Context manager suppressing DP grad sync during accumulation
        (reference engine.no_sync engine.py:2897). On TPU the micro-step
        path accumulates grads that XLA has already reduced — sum and
        reduce commute, so the math (and the comm volume per GAS window
        under reduce-scatter) matches the reference's deferred sync; the
        context exists for API compatibility."""
        import contextlib

        return contextlib.nullcontext()

    def compile(self, backend=None, compile_kwargs=None):
        """Reference engine.compile (engine.py:5472). Everything here is
        already traced+compiled by XLA on first use; this warms the train
        step's compile cache eagerly instead."""
        del backend, compile_kwargs
        self._compiled = True
        return self

    def train(self, mode: bool = True):
        """Mode toggles are meaningless for pure functions; kept for the
        reference's nn.Module-style call sites."""
        del mode
        return self

    def eval(self):
        return self.train(False)

    def module_state_dict(self):
        """Host copy of the model parameters (reference
        module_state_dict engine.py:3693): path → np.ndarray."""
        self.synchronize()
        flat, _ = jax.tree_util.tree_flatten_with_path(self.params)
        out = {}
        for path, leaf in flat:
            key = ".".join(getattr(p, "key", str(getattr(p, "idx", p)))
                           for p in path)
            out[key] = np.asarray(leaf)
        return out

    def load_module_state_dict(self, state_dict, strict: bool = True):
        """Inverse of module_state_dict: place host arrays back with the
        engine's shardings. strict=True raises on missing AND unexpected
        keys (torch/DeepSpeed strict-load semantics)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(self.params)
        leaves = []
        missing = []
        seen = set()
        for path, leaf in flat:
            key = ".".join(getattr(p, "key", str(getattr(p, "idx", p)))
                           for p in path)
            seen.add(key)
            if key in state_dict:
                leaves.append(jax.device_put(
                    np.asarray(state_dict[key], dtype=leaf.dtype),
                    leaf.sharding))
            else:
                missing.append(key)
                leaves.append(leaf)
        unexpected = sorted(set(state_dict) - seen)
        if strict and (missing or unexpected):
            raise KeyError(
                f"missing keys: {missing[:5]}"
                f"{'...' if len(missing) > 5 else ''}; unexpected keys: "
                f"{unexpected[:5]}{'...' if len(unexpected) > 5 else ''}")
        self.params = jax.tree_util.tree_unflatten(treedef, leaves)

    @property
    def loss_scale(self) -> float:
        return float(self.loss_scale_state.scale)

    def zero_grad(self):
        self._grad_acc = None

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:4557,4079)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest: bool = True):
        # drain in-flight steps first: the saved counters (global_steps,
        # skipped_steps) and state must reflect every dispatched step
        self.synchronize()
        self._last_save_dir = save_dir  # emergency-save fallback target
        if self.flight is not None:
            self.flight.record("checkpoint_save", step=self.global_steps,
                               tag=str(tag), phase="begin")
        out = self._ckpt_io.save(save_dir, tag=tag,
                                 client_state=client_state,
                                 save_latest=save_latest)
        if self.flight is not None:
            self.flight.record("checkpoint_save", step=self.global_steps,
                               tag=str(tag), phase="end")
        return out

    def load_checkpoint(self, load_dir, tag=None,
                        load_module_strict: bool = True,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True):
        self.synchronize()  # in-flight steps must not outlive old state
        if self.flight is not None:
            self.flight.record("checkpoint_load", tag=str(tag),
                               phase="begin")
        out = self._ckpt_io.load(load_dir, tag=tag,
                                 load_optimizer_states=load_optimizer_states)
        if self.flight is not None:
            self.flight.record("checkpoint_load", tag=str(tag),
                               phase="end")
        if getattr(self, "_param_host_offload", False):
            # restored leaves come back in device memory; re-pin layers
            self.params = self._place_layer_params_on_host(self.params)
        return out

    def resume_data_iter(self, data_iter, source=None):
        """Position ``data_iter`` at the first microbatch the checkpoint
        never consumed, using the manifest's data cursor from the last
        ``load_checkpoint`` (no-op on a fresh run). Call BEFORE the first
        ``train_batch`` so the prefetcher only ever sees the positioned
        stream; ``source`` optionally names the loader object (e.g. a
        ``RepeatingLoader``) whose ``load_state_dict`` restores
        epoch/rng state. See docs/resilience.md."""
        from deepspeed_tpu.resilience.resume import resume_data_iter

        return resume_data_iter(data_iter, self.loaded_data_cursor,
                                source=source)


class _LRGroup(dict):
    """One live param group: reading 'lr' reflects the engine; writing
    'lr' re-points the compiled step (reference clients mutate
    ``param_groups[0]['lr']`` and expect it to take effect)."""

    def __init__(self, engine: "Engine"):
        super().__init__()
        self._engine = engine
        self._refresh()

    def _refresh(self):
        # keep the plain-dict view (get()/items()/copy()) in sync with
        # the engine so every read path reports the live lr
        dict.__setitem__(self, "lr", self._engine.get_lr()[0])

    def __getitem__(self, key):
        if key == "lr":
            self._refresh()
        return super().__getitem__(key)

    def get(self, key, default=None):
        if key == "lr":
            self._refresh()
        return super().get(key, default)

    def items(self):
        self._refresh()
        return super().items()

    def __setitem__(self, key, value):
        if key == "lr":
            self._engine.set_lr(float(value))  # raises before storing
        super().__setitem__(key, value)


class _OptimizerView:
    """Duck-types the bits of a torch optimizer users poke (param_groups
    lr); returned as the 2nd element of initialize()'s tuple."""

    def __init__(self, engine: Engine):
        self._engine = engine
        self._groups = [_LRGroup(engine)]

    @property
    def param_groups(self):
        return self._groups

    @property
    def state(self):
        return self._engine.opt_state


def _constrain_tree(tree, shardings):
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(x, s), tree, shardings)


class _nullctx:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False
