"""Offline per-sample difficulty analysis.

Reference: ``runtime/data_pipeline/data_sampling/data_analyzer.py:23``
(``DataAnalyzer``) and ``:457`` (``DistributedDataAnalyzer``) — scan a
dataset once, compute one or more per-sample metric values (sequence
length, vocab rarity, ...), and write index files that map a difficulty
value to the sample ids at that difficulty. The curriculum sampler
consumes these indexes at training time.

On-disk layout per metric:

    <out>/<metric>/sample_values.npy        value per sample id
    <out>/<metric>/index_to_sample.json     {difficulty: [sample ids]}
    <out>/<metric>/metadata.json            {num_samples, min, max}
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


# built-in metric functions (reference data_analyzer metric_function)
def metric_seqlen(sample) -> int:
    return int(np.asarray(sample).size)


def metric_vocab_rarity(vocab_freq: np.ndarray) -> Callable:
    """Reference vocab_rarity: sum of -log p(token) over the sample."""
    logp = -np.log(np.clip(vocab_freq / max(vocab_freq.sum(), 1), 1e-12, 1))

    def fn(sample) -> int:
        toks = np.asarray(sample).astype(np.int64).ravel()
        return int(logp[toks].sum())

    return fn


BUILTIN_METRICS: Dict[str, Callable] = {"seqlen": metric_seqlen}


class DataAnalyzer:
    """Single-process scan (reference DataAnalyzer.run_map/run_reduce)."""

    def __init__(self, dataset, output_dir: str,
                 metric_names: Sequence[str] = ("seqlen",),
                 metric_functions: Optional[Dict[str, Callable]] = None,
                 num_quantiles: int = 0):
        self.dataset = dataset
        self.output_dir = os.path.abspath(output_dir)
        self.metric_names = list(metric_names)
        fns = dict(BUILTIN_METRICS)
        fns.update(metric_functions or {})
        missing = [m for m in self.metric_names if m not in fns]
        if missing:
            raise ValueError(f"no metric function for {missing}")
        self.metric_functions = {m: fns[m] for m in self.metric_names}
        self.num_quantiles = num_quantiles

    def run(self, start: int = 0, end: Optional[int] = None) -> Dict[str, str]:
        n = len(self.dataset)
        end = n if end is None else min(end, n)
        out_paths = {}
        values = {m: np.zeros(end - start, dtype=np.int64)
                  for m in self.metric_names}
        for i in range(start, end):
            sample = self.dataset[i]
            for m, fn in self.metric_functions.items():
                values[m][i - start] = fn(sample)
        for m, vals in values.items():
            out_paths[m] = self._write_metric(m, vals, start)
        return out_paths

    def _write_metric(self, metric: str, vals: np.ndarray,
                      id_base: int) -> str:
        mdir = os.path.join(self.output_dir, metric)
        os.makedirs(mdir, exist_ok=True)
        if self.num_quantiles > 1:
            # bucket raw values into quantile bins → difficulty ∈ [0, Q)
            edges = np.quantile(vals, np.linspace(0, 1, self.num_quantiles + 1))
            diff = np.clip(np.searchsorted(edges, vals, side="right") - 1,
                           0, self.num_quantiles - 1)
        else:
            diff = vals
        np.save(os.path.join(mdir, "sample_values.npy"), vals)
        index: Dict[int, List[int]] = {}
        for sid, d in enumerate(diff):
            index.setdefault(int(d), []).append(sid + id_base)
        with open(os.path.join(mdir, "index_to_sample.json"), "w") as f:
            json.dump({str(k): v for k, v in sorted(index.items())}, f)
        with open(os.path.join(mdir, "metadata.json"), "w") as f:
            json.dump({"num_samples": int(vals.size),
                       "min": int(vals.min()) if vals.size else 0,
                       "max": int(vals.max()) if vals.size else 0,
                       "quantiles": self.num_quantiles}, f)
        return mdir


class DistributedDataAnalyzer(DataAnalyzer):
    """Each process scans its contiguous shard; rank 0 merges
    (reference DistributedDataAnalyzer.run_map_reduce — there over
    torch.distributed; here the merge is a host-filesystem reduce since
    every process writes shard files to shared storage)."""

    def run_map_reduce(self) -> Dict[str, str]:
        import jax

        n = len(self.dataset)
        nproc = jax.process_count()
        pid = jax.process_index()
        per = (n + nproc - 1) // nproc
        start, end = pid * per, min((pid + 1) * per, n)

        shard_vals = {m: np.zeros(max(end - start, 0), dtype=np.int64)
                      for m in self.metric_names}
        for i in range(start, end):
            sample = self.dataset[i]
            for m, fn in self.metric_functions.items():
                shard_vals[m][i - start] = fn(sample)
        sdir = os.path.join(self.output_dir, "shards")
        os.makedirs(sdir, exist_ok=True)
        for m, vals in shard_vals.items():
            np.save(os.path.join(sdir, f"{m}.rank{pid}.npy"), vals)

        if nproc > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("data_analyzer_map")
        out_paths = {}
        if pid == 0:
            for m in self.metric_names:
                parts = [np.load(os.path.join(sdir, f"{m}.rank{r}.npy"))
                         for r in range(nproc)]
                out_paths[m] = self._write_metric(m, np.concatenate(parts), 0)
        if nproc > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("data_analyzer_reduce")
        return out_paths
