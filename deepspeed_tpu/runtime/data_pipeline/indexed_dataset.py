"""Memory-mapped indexed dataset.

Reference: ``runtime/data_pipeline/data_sampling/indexed_dataset.py``
(the Megatron-style .bin/.idx pair). Same capability — O(1) random access
to variable-length token sequences far larger than RAM, zero-copy reads —
with a clean little-endian format of our own:

  <path>.idx : magic 'DSTPUIDX' | version u32 | dtype_code u32 | count u64
               | offsets u64[count+1]          (element offsets into .bin)
  <path>.bin : raw sample data, concatenated

Reads return numpy views straight off the memmap (no copies); the builder
streams appends and finalizes the index on close.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
           9: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Append samples, then ``finalize()`` writes the index."""

    def __init__(self, path_prefix: str, dtype=np.int32):
        self.prefix = path_prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        os.makedirs(os.path.dirname(os.path.abspath(path_prefix)),
                    exist_ok=True)
        self._data_f = open(data_file_path(path_prefix), "wb")
        self._lengths: List[int] = []

    def add_item(self, sample: Sequence) -> None:
        arr = np.ascontiguousarray(sample, dtype=self.dtype)
        self._data_f.write(arr.tobytes())
        self._lengths.append(arr.size)

    def add_items(self, samples) -> None:
        for s in samples:
            self.add_item(s)

    def merge_file(self, other_prefix: str) -> None:
        """Append another builder's output (reference merge_file_ — used by
        the distributed analyzer to stitch per-rank shards)."""
        other = MMapIndexedDataset(other_prefix)
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self) -> None:
        self._data_f.close()
        offsets = np.zeros(len(self._lengths) + 1, dtype=np.uint64)
        np.cumsum(self._lengths, out=offsets[1:])
        tmp = index_file_path(self.prefix) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, _DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(self._lengths)))
            f.write(offsets.tobytes())
        os.replace(tmp, index_file_path(self.prefix))

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.finalize()


class MMapIndexedDataset:
    """Zero-copy random access over the .bin/.idx pair."""

    def __init__(self, path_prefix: str):
        self.prefix = path_prefix
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(
                    f"{index_file_path(path_prefix)}: bad magic {magic!r}")
            version, dtype_code = struct.unpack("<II", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (count,) = struct.unpack("<Q", f.read(8))
            header = f.tell()
        self.dtype = np.dtype(_DTYPES[dtype_code])
        self._offsets = np.memmap(index_file_path(path_prefix),
                                  dtype=np.uint64, mode="r",
                                  offset=header, shape=(count + 1,))
        self._data = np.memmap(data_file_path(path_prefix),
                               dtype=self.dtype, mode="r")
        self._count = int(count)

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(self._count))]
        if idx < 0:
            idx += self._count
        if not 0 <= idx < self._count:
            raise IndexError(idx)
        lo, hi = int(self._offsets[idx]), int(self._offsets[idx + 1])
        return self._data[lo:hi]

    def get(self, idx: int, offset: int = 0,
            length: Optional[int] = None) -> np.ndarray:
        """Partial read of one sample (reference .get with offset/length —
        curriculum seqlen truncation reads only the prefix)."""
        lo = int(self._offsets[idx]) + offset
        hi = int(self._offsets[idx + 1])
        if length is not None:
            hi = min(hi, lo + length)
        return self._data[lo:hi]

    @property
    def sizes(self) -> np.ndarray:
        return np.diff(self._offsets).astype(np.int64)

    def close(self):
        del self._offsets
        del self._data
