"""Curriculum-aware distributed data sampler.

Reference: ``runtime/data_pipeline/data_sampling/data_sampler.py:36``
(``DeepSpeedDataSampler``) — at each step, draw the global batch from the
pool of samples whose difficulty (per the analyzer's index files) is
within the curriculum scheduler's current threshold; shard the batch
across dp ranks; deterministic under a seed and resumable from a step.

TPU note: the sampler is pure host-side numpy. It yields *global-batch*
index arrays; the engine's ``shard_batch`` handles device placement, so
no per-rank torch Sampler machinery is needed — each process slices its
rows of the global batch when multi-host.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import \
    CurriculumScheduler
from deepspeed_tpu.utils.logging import logger


class DeepSpeedDataSampler:
    """Iterator of global-batch sample-id arrays under a curriculum.

    Args:
      total_samples:   dataset length
      batch_size:      global train batch size (micro × GAS × dp)
      curriculum:      CurriculumScheduler or its config dict (difficulty
                       threshold per step), or None for plain shuffling
      difficulty_values: per-sample difficulty (analyzer sample_values.npy
                       or an array); required when curriculum is set
      curriculum_metric_dir: load difficulty_values from an analyzer dir
    """

    def __init__(self, total_samples: int, batch_size: int,
                 curriculum: Optional[Any] = None,
                 difficulty_values: Optional[np.ndarray] = None,
                 curriculum_metric_dir: Optional[str] = None,
                 shuffle: bool = True, seed: int = 1234,
                 drop_last: bool = True):
        self.total_samples = int(total_samples)
        self.batch_size = int(batch_size)
        if isinstance(curriculum, dict):
            curriculum = CurriculumScheduler(curriculum)
        self.curriculum: Optional[CurriculumScheduler] = curriculum
        if curriculum_metric_dir is not None:
            difficulty_values = np.load(
                os.path.join(curriculum_metric_dir, "sample_values.npy"))
        if self.curriculum is not None and difficulty_values is None:
            raise ValueError(
                "curriculum sampling needs difficulty_values (or "
                "curriculum_metric_dir)")
        self.difficulty_values = (None if difficulty_values is None
                                  else np.asarray(difficulty_values))
        if self.difficulty_values is not None and \
                self.difficulty_values.size != self.total_samples:
            raise ValueError(
                f"difficulty_values has {self.difficulty_values.size} "
                f"entries for {self.total_samples} samples")
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.consumed_batches = 0  # resumable position

    # -- state (reference sampler state_dict for resume) ----------------
    def state_dict(self) -> Dict[str, Any]:
        sd = {"consumed_batches": self.consumed_batches, "seed": self.seed}
        if self.curriculum is not None:
            sd["curriculum"] = self.curriculum.state_dict()
        return sd

    def load_state_dict(self, sd: Dict[str, Any]):
        self.consumed_batches = int(sd["consumed_batches"])
        if int(sd.get("seed", self.seed)) != int(self.seed):
            # the restored stream is seeded from the checkpoint, not the
            # (different) configured seed — say so, since "resumed" with
            # another seed silently means "another batch order"
            logger.warning(
                f"data sampler resume: adopting checkpoint seed "
                f"{sd['seed']} over configured seed {self.seed} so the "
                "replayed batch stream matches the original run")
        self.seed = int(sd.get("seed", self.seed))
        if self.curriculum is not None and "curriculum" in sd:
            self.curriculum.load_state_dict(sd["curriculum"])

    # -- sampling -------------------------------------------------------
    def _eligible(self, step: int) -> np.ndarray:
        if self.curriculum is None:
            return np.arange(self.total_samples)
        threshold = self.curriculum.get_difficulty(step)
        ids = np.nonzero(self.difficulty_values <= threshold)[0]
        if ids.size == 0:
            # nothing at or below the threshold yet: take the easiest bin
            # rather than deadlocking (reference warns similarly)
            easiest = self.difficulty_values.min()
            ids = np.nonzero(self.difficulty_values <= easiest)[0]
        return ids

    def batch_for_step(self, step: int) -> np.ndarray:
        """Global batch of sample ids at ``step`` (deterministic)."""
        ids = self._eligible(step)
        rng = np.random.default_rng(self.seed + step)
        if self.shuffle:
            pick = rng.choice(ids.size, size=self.batch_size,
                              replace=ids.size < self.batch_size)
        else:
            base = (step * self.batch_size) % ids.size
            pick = (base + np.arange(self.batch_size)) % ids.size
        return ids[pick]

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            batch = self.batch_for_step(self.consumed_batches)
            self.consumed_batches += 1
            yield batch

    @property
    def current_difficulty(self) -> Optional[int]:
        return (self.curriculum.current_difficulty
                if self.curriculum is not None else None)


class CurriculumDataLoader:
    """Wrap (dataset, sampler) into an engine-ready batch iterator.

    Applies curriculum *sequence truncation* when the metric is seqlen:
    samples are cut to the scheduler's current difficulty, and lengths
    are padded up to the difficulty so the compiled step sees at most
    one shape per difficulty value (recompiles bounded by the
    scheduler's difficulty_step quantization).
    """

    def __init__(self, dataset, sampler: DeepSpeedDataSampler,
                 key: str = "input_ids", truncate_to_difficulty: bool = True,
                 pad_id: int = 0):
        self.dataset = dataset
        self.sampler = sampler
        self.key = key
        self.truncate = truncate_to_difficulty
        self.pad_id = pad_id

    # -- resume (resilience/resume.py): position lives in the sampler ---
    def state_dict(self) -> Dict[str, Any]:
        return {"sampler": self.sampler.state_dict(), "offset_batches": 0}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.sampler.load_state_dict(sd.get("sampler", sd))

    def __iter__(self):
        for batch_ids in self.sampler:
            rows = [np.asarray(self.dataset[int(i)]) for i in batch_ids]
            if self.truncate and self.sampler.current_difficulty:
                seq = int(self.sampler.current_difficulty)
            else:
                seq = max(r.size for r in rows)
            out = np.full((len(rows), seq), self.pad_id, dtype=np.int32)
            for r_i, row in enumerate(rows):
                n = min(row.size, seq)
                out[r_i, :n] = row[:n]
            yield {self.key: out}
