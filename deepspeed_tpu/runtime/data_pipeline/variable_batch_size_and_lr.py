"""Variable batch size (token-budget packing) with LR scaling.

Reference: ``runtime/data_pipeline/data_sampling/
variable_batch_size_and_lr.py:226`` (``VariableBatchSizeLR``) — group
variable-length samples into batches bounded by a *token* budget instead
of a sample count, and scale the learning rate per batch so the update
magnitude matches the reference batch size (linear or sqrt scaling rule).

TPU note: batches are padded to the bucket's max length; bucketing by
``length_multiple`` (default 64) bounds the number of distinct compiled
shapes the same way the curriculum scheduler quantizes difficulty.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


def batch_by_tokens(seqlens: Sequence[int], max_tokens: int,
                    length_multiple: int = 64,
                    shuffle_seed: Optional[int] = None,
                    ) -> List[List[int]]:
    """Pack sample ids into batches with padded-token budget ≤ max_tokens.

    Sorting by length first minimizes padding waste (the reference sorts
    inside its dataloader_for_variable_batch_size too); a seeded shuffle
    of the *batches* keeps step-to-step diversity without unsorting the
    packing.
    """
    seqlens = np.asarray(seqlens)
    order = np.argsort(seqlens, kind="stable")
    batches: List[List[int]] = []
    cur: List[int] = []
    cur_maxlen = 0
    for sid in order:
        L = int(np.ceil(max(int(seqlens[sid]), 1) / length_multiple)
                ) * length_multiple
        new_max = max(cur_maxlen, L)
        if cur and new_max * (len(cur) + 1) > max_tokens:
            batches.append(cur)
            cur, cur_maxlen = [int(sid)], L
        else:
            cur.append(int(sid))
            cur_maxlen = new_max
        if cur_maxlen > max_tokens:
            raise ValueError(
                f"sample {sid} alone ({cur_maxlen} padded tokens) exceeds "
                f"max_tokens={max_tokens}")
    if cur:
        batches.append(cur)
    if shuffle_seed is not None:
        rng = np.random.default_rng(shuffle_seed)
        rng.shuffle(batches)
    return batches


def lr_scale_for_batch(batch_size: int, base_batch_size: int,
                       method: str = "linear") -> float:
    """Reference scale_lr: linear (Goyal et al.) or sqrt scaling."""
    if method == "linear":
        return batch_size / base_batch_size
    if method == "sqrt":
        return float(np.sqrt(batch_size / base_batch_size))
    if method in ("none", ""):
        return 1.0
    raise ValueError(f"unknown lr scaling method '{method}'")


class VariableBatchSizeLoader:
    """Iterate (batch dict, lr_scale) pairs over a token-budget packing.

    dataset[i] must be a 1-D token array. Each yielded batch is padded to
    its bucket length; ``lr_scale`` multiplies the scheduler LR for that
    step (reference VariableBatchSizeLR.step).
    """

    def __init__(self, dataset, max_tokens: int, base_batch_size: int,
                 lr_scaling_method: str = "linear",
                 length_multiple: int = 64, seed: int = 0,
                 pad_id: int = 0, key: str = "input_ids",
                 dp_world_size: int = 1):
        self.dataset = dataset
        sizes = getattr(dataset, "sizes", None)
        if sizes is None:
            sizes = np.asarray([np.asarray(dataset[i]).size
                                for i in range(len(dataset))])
        self.seqlens = np.asarray(sizes)
        self.batches = batch_by_tokens(self.seqlens, max_tokens,
                                       length_multiple, shuffle_seed=seed)
        if dp_world_size > 1:
            # pad each batch's sample count to a dp multiple so the global
            # batch shards evenly (duplicates wrap around inside the batch)
            for b in self.batches:
                while len(b) % dp_world_size:
                    b.append(b[len(b) % dp_world_size])
        self.base_batch_size = base_batch_size
        self.method = lr_scaling_method
        self.length_multiple = length_multiple
        self.pad_id = pad_id
        self.key = key

    def __len__(self) -> int:
        return len(self.batches)

    def __iter__(self) -> Iterator[Tuple[Dict[str, np.ndarray], float]]:
        for batch_ids in self.batches:
            rows = [np.asarray(self.dataset[int(i)]) for i in batch_ids]
            maxlen = int(np.ceil(max(r.size for r in rows)
                                 / self.length_multiple)
                         ) * self.length_multiple
            out = np.full((len(rows), maxlen), self.pad_id, dtype=np.int32)
            for r_i, row in enumerate(rows):
                out[r_i, : row.size] = row
            yield ({self.key: out},
                   lr_scale_for_batch(len(rows), self.base_batch_size,
                                      self.method))
