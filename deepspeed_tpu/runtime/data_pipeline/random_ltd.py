"""Random layerwise token dropping (random-LTD).

Reference: ``runtime/data_pipeline/data_routing/`` (+ ``csrc/random_ltd``
gather/scatter kernels) — during training, selected transformer layers
process only a random subset of the sequence; the skipped tokens bypass
the layer and are scattered back in place afterwards. A scheduler ramps
the kept-token count from ``start_seq`` to the full length.

TPU-native: the reference's CUDA gather/scatter kernels are one
``take_along_axis`` / one-hot scatter here — XLA fuses them into the
surrounding layer. Static shapes are preserved by making the kept count
a *schedule of python ints* (one compiled program per distinct count;
quantized by ``seq_step`` exactly like curriculum difficulty).

Usage inside a layer stack::

    keep = scheduler.kept_tokens(step)            # python int
    idx = random_ltd_sample(rng, batch, seqlen, keep)
    sub = random_ltd_gather(x, idx)               # [B, keep, H]
    sub = layer(sub)
    x = random_ltd_scatter(x, sub, idx)           # tokens restored
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


class RandomLTDScheduler:
    """Kept-token schedule (reference data_routing/scheduler.py).

    config keys: total_layer_num, random_ltd_layer_num,
    random_ltd_layer_id (optional explicit list), and a seq schedule
    {min_value (start kept), max_value (full seq), seq_step,
    require_steps (steps per increment)}.
    """

    def __init__(self, config: Dict[str, Any]):
        self.total_layer_num = int(config.get("total_layer_num", 0))
        self.random_ltd_layer_num = int(config.get("random_ltd_layer_num", 0))
        self.layer_ids = list(config.get(
            "random_ltd_layer_id",
            # default: the middle layers (first/last stay dense, matching
            # the reference's recommended usage)
            range(1, 1 + self.random_ltd_layer_num)))
        sched = config.get("schedule", config)
        self.min_value = int(sched.get("min_value", 128))
        self.max_value = int(sched.get("max_value", 512))
        self.seq_step = int(sched.get("seq_step", 16))
        self.require_steps = int(sched.get("require_steps", 100))
        self.current_seq = self.min_value

    def kept_tokens(self, global_steps: int) -> int:
        inc = (global_steps // max(self.require_steps, 1)) * self.seq_step
        self.current_seq = int(min(self.min_value + inc, self.max_value))
        return self.current_seq

    def is_dense(self, global_steps: int) -> bool:
        return self.kept_tokens(global_steps) >= self.max_value

    def state_dict(self):
        return {"current_seq": self.current_seq}

    def load_state_dict(self, sd):
        self.current_seq = int(sd["current_seq"])


def random_ltd_sample(rng, batch: int, seqlen: int, keep: int):
    """Per-row sorted random token indices [batch, keep] (sorted keeps
    relative order, as the reference's sampler does)."""
    import jax

    idx = jax.vmap(
        lambda k: jax.random.choice(k, seqlen, shape=(keep,), replace=False)
    )(jax.random.split(rng, batch))
    return jax.numpy.sort(idx, axis=-1)


def random_ltd_gather(x, idx):
    """[B, S, H] × [B, K] → [B, K, H] (reference gather kernel)."""
    import jax.numpy as jnp

    return jnp.take_along_axis(x, idx[:, :, None], axis=1)


def random_ltd_scatter(x, sub, idx):
    """Scatter [B, K, H] back into [B, S, H] at idx (reference scatter
    kernel). Unselected positions keep their input values."""
    import jax

    def per_row(row_x, row_sub, row_idx):
        return row_x.at[row_idx].set(row_sub)

    return jax.vmap(per_row)(x, sub, idx)
