"""Data-efficiency pipeline (reference: deepspeed/runtime/data_pipeline/).

Covers the reference's data-efficiency library: curriculum learning
(CurriculumScheduler + curriculum-aware sampler), the memmap indexed
dataset, per-sample difficulty analysis (DataAnalyzer), variable batch
size with LR scaling, and random layerwise token dropping (random-LTD).
"""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (  # noqa: F401
    CurriculumScheduler,
)
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (  # noqa: F401
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import (  # noqa: F401
    DeepSpeedDataSampler,
)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (  # noqa: F401
    DataAnalyzer,
    DistributedDataAnalyzer,
)
from deepspeed_tpu.runtime.data_pipeline.variable_batch_size_and_lr import (  # noqa: F401
    batch_by_tokens,
    VariableBatchSizeLoader,
)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (  # noqa: F401
    RandomLTDScheduler,
    random_ltd_gather,
    random_ltd_scatter,
)
