"""Curriculum difficulty schedules.

Reference: ``runtime/data_pipeline/curriculum_scheduler.py:11``
(``CurriculumScheduler``) — maps a global step to a difficulty value
(typically max sequence length) under fixed_linear / fixed_root /
fixed_discrete / custom schedules.

TPU note: difficulty usually controls sequence length, and every distinct
length is a distinct compiled program. ``rounding`` therefore defaults to
a power-of-2-friendly multiple (the reference uses ``difficulty_step`` the
same way) — keep it coarse (e.g. 64) to bound recompiles.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """Step → difficulty.

    config keys (reference schema):
      curriculum_type: fixed_linear | fixed_root | fixed_discrete | custom
      min_difficulty, max_difficulty
      schedule_config:
        fixed_linear/fixed_root: {total_curriculum_step, difficulty_step,
                                  root_degree (root only)}
        fixed_discrete: {difficulty: [..], max_step: [..]}  (len-1 steps)
    """

    def __init__(self, config: Dict[str, Any]):
        self.config = dict(config)
        self.curriculum_type = config.get("curriculum_type", FIXED_LINEAR)
        self.min_difficulty = int(config.get("min_difficulty", 1))
        self.max_difficulty = int(config.get("max_difficulty", 1))
        self.schedule_config = dict(config.get("schedule_config", {}))
        self._custom_fn: Optional[Callable[[int], int]] = None
        self.current_difficulty = self.min_difficulty

        if self.curriculum_type in (FIXED_LINEAR, FIXED_ROOT):
            sc = self.schedule_config
            if "total_curriculum_step" not in sc:
                raise ValueError(
                    f"{self.curriculum_type} schedule needs "
                    "schedule_config.total_curriculum_step")
            self.total_step = int(sc["total_curriculum_step"])
            self.difficulty_step = int(sc.get("difficulty_step", 1))
            self.root_degree = int(sc.get("root_degree", 2)) \
                if self.curriculum_type == FIXED_ROOT else 1
        elif self.curriculum_type == FIXED_DISCRETE:
            sc = self.schedule_config
            self.difficulties: List[int] = list(sc["difficulty"])
            self.max_steps: List[int] = list(sc.get("max_step", []))
            if len(self.max_steps) != len(self.difficulties) - 1:
                raise ValueError(
                    "fixed_discrete: len(max_step) must be "
                    "len(difficulty) - 1")
        elif self.curriculum_type == CUSTOM:
            pass  # set_custom_get_difficulty must be called
        else:
            raise ValueError(
                f"unknown curriculum_type '{self.curriculum_type}'")

    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        """Reference engine.set_custom_curriculum_learning_schedule."""
        self._custom_fn = fn

    def get_difficulty(self, global_steps: int) -> int:
        if self.curriculum_type == CUSTOM:
            if self._custom_fn is None:
                raise RuntimeError(
                    "custom curriculum: call set_custom_get_difficulty first")
            d = int(self._custom_fn(global_steps))
        elif self.curriculum_type == FIXED_DISCRETE:
            d = self.difficulties[-1]
            for diff, until in zip(self.difficulties, self.max_steps):
                if global_steps <= until:
                    d = diff
                    break
        else:
            frac = min(1.0, max(0.0, global_steps / max(self.total_step, 1)))
            if self.curriculum_type == FIXED_ROOT:
                frac = frac ** (1.0 / self.root_degree)
            span = self.max_difficulty - self.min_difficulty
            d = self.min_difficulty + frac * span
            # quantize to difficulty_step multiples (bounds recompiles)
            d = int(math.floor(d / self.difficulty_step)) * self.difficulty_step
            d = max(self.min_difficulty, d)
        self.current_difficulty = int(min(d, self.max_difficulty))
        return self.current_difficulty

    def update_difficulty(self, global_steps: int) -> int:
        return self.get_difficulty(global_steps)

    def is_fully_ramped(self, global_steps: int) -> bool:
        return self.get_difficulty(global_steps) >= self.max_difficulty

    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict[str, Any]):
        self.current_difficulty = int(sd["current_difficulty"])
