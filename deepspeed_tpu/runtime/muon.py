"""Muon optimizer: Newton-Schulz-orthogonalized momentum, from scratch.

Reference: ``runtime/zero/muon/{muon_optimizer,original_muon}.py`` — SGD
momentum whose update is orthogonalized by a quintic Newton-Schulz
iteration for hidden matrix weights, Adam for everything else, with the
NS step applied *inside* ZeRO partitioning
(``_apply_distributed_muon_update``, stage3.py:1537).

TPU-native design:
  * The NS iteration is plain matmuls on fp32 momentum, which is
    ZeRO-sharded by the engine's plan — GSPMD computes each X @ X^T
    cooperatively across the fsdp axis, which IS the distributed
    Newton-Schulz (no gather-orthogonalize-scatter round trip like the
    reference's stage-3 hook).
  * The model zoo stacks layer weights as [L, ...]; NS batches over the
    stack dim and head-split projections ([L, h, nh, hd]) reshape to
    [L, m, n] first. (optax.contrib.muon treats only exactly-2D leaves
    as matrices, silently running Adam on every stacked layer weight —
    the reason this is hand-rolled.)
  * Routing is path-aware via optax.multi_transform: hidden layer
    matrices get Muon; embeddings, unembed, norms, biases get Adam —
    the reference's parameter-group split (original_muon.py).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

# quintic Newton-Schulz coefficients (reference original_muon.py /
# Keller Jordan's Muon): tuned so the iteration contracts singular
# values toward 1 without full convergence
_NS_COEFFS = (3.4445, -4.7750, 2.0315)


def newton_schulz(G: jax.Array, steps: int = 5, eps: float = 1e-7
                  ) -> jax.Array:
    """Approximately orthogonalize the last two dims of ``G``.

    G: [..., m, n] (leading dims batched). fp32 math; returns UV^T-ish
    with singular values pushed toward 1.
    """
    a, b, c = _NS_COEFFS
    x = G.astype(jnp.float32)
    transposed = x.shape[-2] > x.shape[-1]
    if transposed:
        x = jnp.swapaxes(x, -1, -2)
    norm = jnp.sqrt(jnp.sum(x * x, axis=(-2, -1), keepdims=True)) + eps
    x = x / norm

    def body(x, _):
        A = x @ jnp.swapaxes(x, -1, -2)          # [..., m, m]
        B = b * A + c * (A @ A)
        return a * x + B @ x, None

    x, _ = jax.lax.scan(body, x, None, length=steps)
    if transposed:
        x = jnp.swapaxes(x, -1, -2)
    return x


def _is_matrix_path(path: str, ndim: int) -> bool:
    """Muon-eligible: stacked hidden layer matrices. Embeddings, the
    unembed projection, norms and biases stay on Adam (reference
    parameter-group split, original_muon.py)."""
    if "layers" not in path:
        return False
    for skip in ("ln1", "ln2", "norm", "bias", "['b"):  # norm/bias leaves
        if skip in path:
            return False
    return ndim >= 3  # [L, ...] stacked weight with >= 2 trailing dims


def _matricize(x: jax.Array) -> jax.Array:
    """[L, d1, ..., dk] → [L, m, n] for the NS matmuls, choosing the
    split of the trailing dims that yields the most balanced matrix.

    The zoo's head-split projections have OPPOSITE orientations — wq is
    [L, h, nh, hd] (in, out-split) while wo is [L, nh, hd, h] (in-split,
    out) — and a fixed "first trailing dim is m" rule would treat wo as
    a [nh, hd*h] matrix: Newton-Schulz would orthogonalize the wrong
    operand and the match_rms scale would inflate by ~sqrt(hd). The
    balanced split recovers (fan_in, fan_out) for every zoo layout:
    wq → (h, nh*hd), wo → (nh*hd, h), mlp [L, h, f] → (h, f).
    """
    dims = x.shape[1:]
    best_j, best_bal = 1, -1.0
    for j in range(1, len(dims)):
        m = 1
        for d in dims[:j]:
            m *= d
        n = 1
        for d in dims[j:]:
            n *= d
        bal = min(m, n) / max(m, n)
        if bal > best_bal:
            best_j, best_bal = j, bal
    m = 1
    for d in dims[:best_j]:
        m *= d
    return x.reshape(x.shape[0], m, -1)


class _MuonMatrixState(NamedTuple):
    momentum: Any
    count: jax.Array


def _muon_matrices(learning_rate, beta: float, ns_steps: int,
                   nesterov: bool, weight_decay: float,
                   lr_adjust: str) -> optax.GradientTransformation:
    """The matrix branch: every leaf this transform sees gets NS."""

    def init(params):
        return _MuonMatrixState(
            momentum=jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params),
            count=jnp.zeros((), jnp.int32))

    def one(g, mom, p, lr):
        g32 = g.astype(jnp.float32)
        mom = beta * mom + g32
        eff = beta * mom + g32 if nesterov else mom
        mats = _matricize(eff)                   # [L, m, n]
        m, n = mats.shape[-2], mats.shape[-1]
        ortho = newton_schulz(mats, ns_steps)
        if lr_adjust == "match_rms":
            # one lr drives both groups: scale the orthogonal update so
            # its RMS matches Adam's typical step (reference/Moonlight)
            ortho = ortho * (0.2 * jnp.sqrt(jnp.float32(max(m, n))))
        upd = ortho.reshape(eff.shape)
        if weight_decay and p is not None:
            upd = upd + weight_decay * p.astype(jnp.float32)
        return (-lr * upd).astype(g.dtype), mom

    def update(grads, state: _MuonMatrixState, params=None):
        lr = (learning_rate(state.count)
              if callable(learning_rate) else learning_rate)
        lr = jnp.asarray(lr, jnp.float32)
        if params is None:
            params = jax.tree.map(lambda g: None, grads)
        flat_g, treedef = jax.tree.flatten(grads)
        flat_m = treedef.flatten_up_to(state.momentum)
        flat_p = treedef.flatten_up_to(params)
        outs = [one(g, m, p, lr) for g, m, p in zip(flat_g, flat_m, flat_p)]
        updates = jax.tree.unflatten(treedef, [u for u, _ in outs])
        momentum = jax.tree.unflatten(treedef, [m for _, m in outs])
        return updates, _MuonMatrixState(momentum, state.count + 1)

    return optax.GradientTransformation(init, update)


def muon(learning_rate, *, beta: float = 0.95, ns_steps: int = 5,
         nesterov: bool = True, weight_decay: float = 0.0,
         adam_b1: float = 0.9, adam_b2: float = 0.999,
         adam_eps: float = 1e-8,
         lr_adjust: str = "match_rms") -> optax.GradientTransformation:
    """Muon as an optax GradientTransformation (drop-in for the engine's
    mixed-precision plumbing; state shards with the ZeRO plan like any
    optimizer state)."""
    from jax.tree_util import keystr, tree_map_with_path

    def label_fn(params):
        return tree_map_with_path(
            lambda kp, p: ("muon" if _is_matrix_path(keystr(kp),
                                                     jnp.ndim(p))
                           else "adam"), params)

    return optax.multi_transform(
        {"muon": _muon_matrices(learning_rate, beta, ns_steps, nesterov,
                                weight_decay, lr_adjust),
         "adam": optax.adamw(learning_rate, b1=adam_b1, b2=adam_b2,
                             eps=adam_eps, weight_decay=weight_decay)},
        label_fn)
