"""ZeRO++ train step: quantized gradient reduce (qgZ) + quantized weight
all-gather (qwZ).

Reference: ZeRO++ (docs/_tutorials/zeropp.md — "4x less communication"):
``all_to_all_quant_reduce`` (runtime/comm/coalesced_collectives.py:31,
int8 two-level gradient reduce) and quantized weight all-gather
(``_allgather_params`` with quantizer kernels, csrc/quantization/). The
engine flags are ``zero_optimization.zero_quantized_gradients`` and
``zero_quantized_weights``.

TPU-native expression (same pattern as the 1-bit optimizers,
runtime/onebit.py): GSPMD's automatically inserted collectives cannot be
quantized, so the train step runs inside a ``jax.shard_map`` MANUAL over
the dp axis. Per step and per parameter:

  local grads → blockwise-int8 quantize → all-to-all → local dequant+sum
  (= the qgZ reduce-scatter, ops/pallas/quantization.quantized_psum_scatter)
  → Adam on this rank's fp32 master shard (the ZeRO-1/2 partition)
  → [int8-quantized] all-gather of the updated shards back to params.

Gradient-sync wire volume drops 4x (bf16→int8 both directions) — the
reference's headline — at the cost of quantization noise bounded by the
blockwise scales.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils import jaxcompat

QUANT_BLOCK = 256


class ZeroppState(NamedTuple):
    master: Any  # dict leaf-path → [dp, shard] fp32 (P('dp') on dim 0)
    m: Any
    v: Any
    step: jax.Array


def _pad_len(n: int, dp: int) -> int:
    unit = dp * QUANT_BLOCK
    return int(np.ceil(n / unit)) * unit


def _masters_from_leaves(leaves, dp: int):
    """Param leaves → fp32 master layout [dp, shard] (the single home of
    the pad/reshape invariant; used at init and at checkpoint re-seed)."""
    out = []
    for x in leaves:
        n = int(np.prod(x.shape))
        n_pad = _pad_len(n, dp)
        f = jnp.pad(x.reshape(-1).astype(jnp.float32), (0, n_pad - n))
        out.append(f.reshape(dp, n_pad // dp))
    return out


def build_zeropp_step(model, mesh, gas: int, base_lr: float,
                      lr_schedule: Optional[Callable], betas, eps: float,
                      weight_decay: float, grad_clip: float,
                      qg_enabled: bool, qg_bits: int, qw_enabled: bool,
                      qw_bits: int, compute_dtype, param_shardings,
                      qar_enabled: bool = False, qar_bits: int = 8):
    """Returns (init_fn(rng) → (params, state), jit step_fn)."""
    from deepspeed_tpu.ops.pallas.quantization import (
        quantized_all_gather, quantized_all_reduce, quantized_psum_scatter)

    for ax in ("fsdp", "sp", "ep", "pp"):
        if mesh.shape.get(ax, 1) > 1:
            raise ValueError(
                f"ZeRO++ quantized step is manual over 'dp' only; mesh "
                f"axis {ax}={mesh.shape[ax]} is unsupported (grads would "
                "not reduce across it)")
    # tp composes: the region is manual over dp ONLY (partial-manual
    # shard_map), so GSPMD still shards the model over tp inside —
    # activation constraints stay live with the dp axis stripped
    # (sharding.manual_axes). Caveat: the flat [dp, shard] master layout
    # keeps optimizer state replicated over tp, and the per-leaf flatten
    # regathers tp-sharded grads — correct, with extra intra-slice wire;
    # acceptable because qgZ targets the dp (DCN) axis.
    dp = mesh.shape["dp"]
    b1, b2 = betas

    # shapes fixed at build: trace the model's abstract params
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    leaves, treedef = jax.tree_util.tree_flatten(abstract)
    shapes = [x.shape for x in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    pads = [_pad_len(n, dp) for n in sizes]

    def _flat_pad(g, n, n_pad):
        flat = g.reshape(-1).astype(jnp.float32)
        return jnp.pad(flat, (0, n_pad - n))

    # -- init ------------------------------------------------------------
    def init_fn(rng):
        p32 = model.init(rng)
        master = jax.tree.unflatten(
            treedef, _masters_from_leaves(jax.tree.leaves(p32), dp))
        zeros = jax.tree.map(jnp.zeros_like, master)
        params = jax.tree.map(lambda x: x.astype(compute_dtype), p32)
        return params, ZeroppState(master=master, m=zeros,
                                   v=jax.tree.map(jnp.zeros_like, zeros),
                                   step=jnp.zeros((), jnp.int32))

    # -- manual region ---------------------------------------------------
    def local_step(params, master, m, v, step, lr_over, batches):
        from deepspeed_tpu.runtime import sharding as shard_lib

        with shard_lib.manual_axes({"dp"}):
            return _local_step_inner(params, master, m, v, step, lr_over,
                                     batches)

    def _local_step_inner(params, master, m, v, step, lr_over, batches):
        def total_loss(p):
            def body(carry, mb):
                loss, _aux = model.loss(p, mb)
                return carry + loss / gas, loss

            total, losses = lax.scan(body, jnp.asarray(0.0, jnp.float32),
                                     batches)
            return total, losses

        (_, losses), grads = jax.value_and_grad(
            total_loss, has_aux=True)(params)

        # qgZ: quantized reduce-scatter per leaf → this rank's grad shard.
        # The collective quantizes the last dim and scatters dim 0, so the
        # flat vector goes in as [rows, QUANT_BLOCK] (rows divisible by dp
        # by construction of _pad_len).
        g_shards = []
        for g, n, n_pad in zip(jax.tree.leaves(grads), sizes, pads):
            flat = _flat_pad(g, n, n_pad).reshape(-1, QUANT_BLOCK)
            if qar_enabled:
                # qar: EQuARX-style quantized all-reduce (int8
                # reduce-scatter + int8 all-gather with fp32 accumulation)
                # yields the full mean everywhere; this rank then slices
                # its ZeRO partition for the sharded Adam below. Rows are
                # divisible by dp by construction of _pad_len, so the
                # collective's internal padding never triggers.
                full = quantized_all_reduce(flat, "dp", bits=qar_bits,
                                            block=QUANT_BLOCK)
                rows = flat.shape[0] // jaxcompat.axis_size("dp")
                red = lax.dynamic_slice_in_dim(
                    full, lax.axis_index("dp") * rows, rows, axis=0)
            elif qg_enabled:
                red = quantized_psum_scatter(flat, "dp", bits=qg_bits,
                                             block=QUANT_BLOCK)
            else:  # qwZ-only config: exact (unquantized) grad reduce
                red = lax.psum_scatter(flat, "dp", scatter_dimension=0,
                                       tiled=True) / jaxcompat.axis_size("dp")
            g_shards.append(red.reshape(-1))

        sq = sum(jnp.sum(gs.astype(jnp.float32) ** 2) for gs in g_shards)
        gnorm = jnp.sqrt(lax.psum(sq, "dp"))
        scale = (jnp.minimum(1.0, grad_clip / (gnorm + 1e-6))
                 if grad_clip and grad_clip > 0 else jnp.asarray(1.0))

        step = step + 1
        lr = (lr_schedule(step) if lr_schedule is not None
              else jnp.asarray(base_lr, jnp.float32))
        # runtime lr override (engine.set_lr): NaN sentinel = use schedule
        lr = jnp.where(jnp.isnan(lr_over), lr, lr_over)
        master_l = jax.tree.leaves(master)
        m_l = jax.tree.leaves(m)
        v_l = jax.tree.leaves(v)
        new_master, new_m, new_v, new_params = [], [], [], []
        for i, gs in enumerate(g_shards):
            g_ = gs.astype(jnp.float32) * scale
            mm = master_l[i][0]  # local [shard]
            mi = b1 * m_l[i][0] + (1 - b1) * g_
            vi = b2 * v_l[i][0] + (1 - b2) * g_ * g_
            mhat = mi / (1 - b1 ** step.astype(jnp.float32))
            vhat = vi / (1 - b2 ** step.astype(jnp.float32))
            upd = lr * (mhat / (jnp.sqrt(vhat) + eps)
                        + weight_decay * mm)
            mm = mm - upd
            # qwZ: the "allgather updated partitions" collective, int8
            if qw_enabled:
                full = quantized_all_gather(
                    mm.reshape(-1, QUANT_BLOCK), "dp", bits=qw_bits,
                    block=QUANT_BLOCK).reshape(-1)
            else:
                full = lax.all_gather(mm, "dp", axis=0, tiled=True)
            new_params.append(full[: sizes[i]].reshape(shapes[i])
                              .astype(compute_dtype))
            new_master.append(mm[None])
            new_m.append(mi[None])
            new_v.append(vi[None])
        loss_avg = lax.pmean(jnp.mean(losses), "dp")
        unf = lambda ls: jax.tree.unflatten(treedef, ls)
        return (unf(new_params), unf(new_master), unf(new_m), unf(new_v),
                step, loss_avg, gnorm, lr)

    batch_spec = P(None, "dp")
    rep = P()
    shard_spec = P("dp")

    mapped = jaxcompat.shard_map(
        local_step, mesh=mesh,
        in_specs=(rep, shard_spec, shard_spec, shard_spec, rep, rep,
                  batch_spec),
        out_specs=(rep, shard_spec, shard_spec, shard_spec, rep, rep, rep,
                   rep),
        axis_names=frozenset({"dp"}),
        check_vma=False)

    def step_fn(params, state: ZeroppState, batches, lr_over=None):
        if lr_over is None:
            lr_over = jnp.asarray(float("nan"), jnp.float32)
        (new_p, master, m, v, step, loss, gnorm, lr) = mapped(
            params, state.master, state.m, state.v, state.step, lr_over,
            batches)
        metrics = {"loss": loss, "grad_norm": gnorm, "lr": lr,
                   "overflow": jnp.asarray(False)}
        return new_p, ZeroppState(master, m, v, step), metrics

    log_dist(
        f"ZeRO++ step: dp={dp}, "
        + (f"qar=int{qar_bits}" if qar_enabled
           else (f"qgZ=int{qg_bits}" if qg_enabled else "qgZ=off"))
        + (f", qwZ=int{qw_bits}" if qw_enabled else ", qwZ=off"),
        ranks=[0])
    return init_fn, step_fn


def reseed_state_from_params(params, state: ZeroppState, dp: int
                             ) -> ZeroppState:
    """Rebuild fp32 masters (zeroed moments) from restored params — the
    recovery path when a checkpoint lacks (or skips) optimizer state, so
    the next step's all-gather doesn't roll the model back to init
    (mirrors the offload reinit_masters hazard guard)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    master = jax.tree_util.tree_unflatten(treedef,
                                          _masters_from_leaves(leaves, dp))
    zeros = jax.tree.map(jnp.zeros_like, master)
    return ZeroppState(master=master, m=zeros,
                       v=jax.tree.map(jnp.zeros_like, zeros),
                       step=state.step)


def zeropp_enabled(config) -> bool:
    z = config.zero_optimization
    return (z.stage in (1, 2)
            and (z.zero_quantized_gradients or z.zero_quantized_weights
                 or getattr(z, "zero_quantized_allreduce", False)))
