"""Hybrid engine: one parameter copy serving training AND generation.

Reference: ``deepspeed/runtime/hybrid_engine.py:30``
(``DeepSpeedHybridEngine``) — RLHF actors alternate train steps with
rollout generation on the same weights; the reference switches a ZeRO-3
model into inference mode (gather partitioned params, fuse LoRA, borrow
inference kernels/KV-cache) and back.

TPU-native: both modes are jit programs over the *same* global arrays —
"mode switching" is a cached resharding jit from the training plan's
shardings (fsdp/tp) to the inference TP shardings, re-run only when the
train step count has advanced (XLA compiles the reshard into a single
all-gather over ICI, the `_zero3_forward` gather analog, hybrid_engine.py
:362). Generation then runs the dense-KV inference path
(inference/engine.py) under the same mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

GENERATE_TIMER = "generate"


class HybridEngine:
    """Wrap a training Engine with a parameter-sharing generate path.

    Args:
      engine: deepspeed_tpu Engine (any ZeRO stage)
      max_batch: generation batch bound (KV cache allocation)
      param_transform: optional fn(params) -> params applied at sync
        (e.g. LoRA merge — reference fuse_lora before generate)
    """

    def __init__(self, engine, max_batch: int = 8,
                 max_seq_len: Optional[int] = None,
                 param_transform: Optional[Callable] = None):
        from deepspeed_tpu.inference.engine import InferenceEngine

        self.engine = engine
        self.param_transform = param_transform
        self._synced_at = -1
        self.timers = SynchronizedWallClockTimer()
        self._infer = InferenceEngine(
            engine.model, mesh=engine.mesh, params=engine.params,
            max_batch=max_batch, max_seq_len=max_seq_len)
        self._reshard = jax.jit(
            lambda p: p,
            out_shardings=jax.tree.map(lambda a: a.sharding,
                                       self._infer.params))
        self._sync()

    # -- mode switch (reference eval()/train() transitions) -------------
    def _sync(self):
        """Refresh inference params iff training stepped since last sync."""
        if self._synced_at == self.engine.global_steps:
            return
        params = self.engine.params
        if self.param_transform is not None:
            params = self.param_transform(params)
        self._infer.params = self._reshard(params)
        self._synced_at = self.engine.global_steps
        log_dist(f"hybrid engine: params synced at step {self._synced_at}",
                 ranks=[0])

    # -- generation (reference generate :168) ----------------------------
    def generate(self, tokens, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 eos_token_id: Optional[int] = None):
        self._sync()
        self.timers(GENERATE_TIMER).start()
        out = self._infer.generate(
            tokens, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, seed=seed, eos_token_id=eos_token_id)
        self.timers(GENERATE_TIMER).stop()
        return out

    # -- training passthrough -------------------------------------------
    def train_batch(self, data_iter=None):
        loss = self.engine.train_batch(data_iter)
        return loss

    def forward(self, *a, **kw):
        return self.engine.forward(*a, **kw)

    def backward(self, *a, **kw):
        return self.engine.backward(*a, **kw)

    def step(self):
        return self.engine.step()

    def eval(self):
        self._sync()
        return self

    def train(self, mode: bool = True):
        return self

    @property
    def params(self):
        return self.engine.params

    @property
    def global_steps(self):
        return self.engine.global_steps

    def save_checkpoint(self, *a, **kw):
        return self.engine.save_checkpoint(*a, **kw)

    def load_checkpoint(self, *a, **kw):
        out = self.engine.load_checkpoint(*a, **kw)
        self._synced_at = -1  # force re-sync after restore
        return out
