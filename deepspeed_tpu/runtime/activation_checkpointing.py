"""Activation checkpointing (rematerialization) subsystem.

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
— Megatron-compatible ``checkpoint()`` (:948, ``CheckpointFunction``
:488) with activation *partitioning* across TP ranks
(``partition_activations`` :377), CPU checkpointing (saved activations
moved to host), contiguous buffers, and RNG-state tracking
(``CudaRNGStatesTracker`` :124); configured by ``configure()`` :1029.

TPU mapping (each reference knob → an XLA-native mechanism):

  * checkpoint()                 → ``jax.checkpoint`` (remat): recompute
    in backward instead of saving; policies choose what to keep.
  * partition_activations        → saved residuals carry a sharding
    constraint over the tp axis, so each rank stores 1/tp of every
    checkpointed activation (GSPMD all-gathers on recompute — the same
    gather the reference does by hand).
  * cpu_checkpointing            → offload policies: checkpointed dot
    outputs spill to pinned host memory and stream back in backward.
  * contiguous_memory_optimization → XLA's allocator already packs
    remat buffers; no user-level pooling exists to configure (no-op).
  * RNG tracking                 → JAX RNG is functional: a dropout key
    threaded through the forward is *by construction* replayed bit-
    identically in recompute, which is everything CudaRNGStatesTracker
    exists to guarantee. ``model_parallel_rng`` derives distinct
    per-tp-rank streams (the tracker's other job).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger

# remat policy registry (config activation_checkpointing.policy)
POLICIES = {
    # save nothing, recompute all (reference default checkpoint behavior)
    "nothing_saveable": "nothing_saveable",
    # keep matmul outputs (cheap recompute elsewhere, no matmul replay)
    "dots_saveable": "dots_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    # cpu_checkpointing analog: saved dots live in pinned host memory
    "offload_dots_host": "offload_dots_host",
    # disable remat entirely
    "none": "everything",
    "everything": "everything",
    # named selective saves (checkpoint_name annotations in
    # models/transformer.py _layer): trade HBM for skipped recompute of
    # just those projections — the [S,S] score transient is never saved
    "save_qkv_proj": ("names", ("qkv_proj",)),
    "save_attn_out": ("names", ("attn_out",)),
    "save_qkv_attn_out": ("names", ("qkv_proj", "attn_kernel_out",
                                    "attn_out")),
    "save_attn_mlp": ("names", ("qkv_proj", "attn_kernel_out", "attn_out",
                                "mlp_out")),
}

_GLOBAL_CONFIG: dict = {}


def configure(config=None, partition_activations: Optional[bool] = None,
              cpu_checkpointing: Optional[bool] = None,
              contiguous_memory_optimization: Optional[bool] = None,
              number_checkpoints: Optional[int] = None,
              synchronize_checkpoint_boundary: Optional[bool] = None,
              profile: Optional[bool] = None,
              policy: Optional[str] = None):
    """Reference ``configure`` (checkpointing.py:1029): set module-level
    defaults from an ActivationCheckpointingConfig or keyword overrides."""
    global _GLOBAL_CONFIG
    if config is not None:
        _GLOBAL_CONFIG = {
            "partition_activations": getattr(config, "partition_activations",
                                             False),
            "cpu_checkpointing": getattr(config, "cpu_checkpointing", False),
            "policy": getattr(config, "policy", "nothing_saveable"),
        }
        if getattr(config, "contiguous_memory_optimization", False):
            logger.info("activation checkpointing: "
                        "contiguous_memory_optimization is inherent in "
                        "XLA's allocator (no-op)")
    for k, v in [("partition_activations", partition_activations),
                 ("cpu_checkpointing", cpu_checkpointing),
                 ("policy", policy)]:
        if v is not None:
            _GLOBAL_CONFIG[k] = v
    return dict(_GLOBAL_CONFIG)


def is_configured() -> bool:
    return bool(_GLOBAL_CONFIG)


def resolve_policy(name: Optional[str] = None,
                   cpu_checkpointing: bool = False):
    """Policy name → jax.checkpoint policy object (or the sentinels
    None = save-nothing, 'everything' = no remat)."""
    name = name or _GLOBAL_CONFIG.get("policy", "nothing_saveable")
    canonical = POLICIES.get(name)
    if canonical is None:
        raise ValueError(f"unknown activation checkpointing policy "
                         f"'{name}' (choose from {sorted(POLICIES)})")
    if canonical == "everything":
        return "everything"  # remat explicitly disabled: offload n/a
    if isinstance(canonical, tuple) and canonical[0] == "names":
        names = canonical[1]
        if cpu_checkpointing or _GLOBAL_CONFIG.get("cpu_checkpointing"):
            # honor the host-offload request for named saves too
            offload = getattr(jax.checkpoint_policies,
                              "save_and_offload_only_these_names", None)
            if offload is not None:
                return offload(names_which_can_be_saved=[],
                               names_which_can_be_offloaded=list(names),
                               offload_src="device",
                               offload_dst="pinned_host")
            logger.warning(
                "cpu_checkpointing requested but this JAX lacks "
                "save_and_offload_only_these_names; named saves stay in HBM")
        return jax.checkpoint_policies.save_only_these_names(*names)
    if cpu_checkpointing or _GLOBAL_CONFIG.get("cpu_checkpointing"):
        canonical = "offload_dots_host"
    if canonical == "nothing_saveable":
        return None
    if canonical == "offload_dots_host":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    return getattr(jax.checkpoint_policies, canonical)


def _partition_constraint(x, mesh):
    """Shard a saved activation's trailing (hidden) dim over tp — the
    partition_activations memory saving (checkpointing.py:377)."""
    if not hasattr(x, "ndim") or x.ndim < 1 or mesh is None \
            or mesh.shape.get("tp", 1) == 1:
        return x
    spec = [None] * x.ndim
    if x.shape[-1] % mesh.shape["tp"] == 0:
        spec[-1] = "tp"
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


def checkpoint_wrapper(function: Callable,
                       policy: Optional[str] = None,
                       partition_activations: Optional[bool] = None,
                       cpu_checkpointing: bool = False) -> Callable:
    """Wrap ``function`` with the configured remat behavior."""
    resolved = resolve_policy(policy, cpu_checkpointing)
    part = (_GLOBAL_CONFIG.get("partition_activations", False)
            if partition_activations is None else partition_activations)

    if resolved == "everything":
        inner = function
    elif resolved is None:
        inner = jax.checkpoint(function)
    else:
        inner = jax.checkpoint(function, policy=resolved)

    if not part:
        return inner

    def wrapped(*args, **kwargs):
        from deepspeed_tpu.parallel import topology

        mesh = topology._GLOBAL_MESH
        # constrain only the first argument — the residual stream whose
        # save is the memory cost; index/aux args must keep their layout
        if args and isinstance(args[0], jax.Array):
            args = (_partition_constraint(args[0], mesh),) + args[1:]
        return inner(*args, **kwargs)

    return wrapped


def checkpoint(function: Callable, *args, **kwargs) -> Any:
    """Reference-parity direct call (checkpointing.py:948): run
    ``function(*args)`` under the configured remat policy."""
    return checkpoint_wrapper(function)(*args, **kwargs)


def model_parallel_rng(key: jax.Array, axis: str = "tp") -> jax.Array:
    """Distinct RNG stream per model-parallel rank (the
    CudaRNGStatesTracker 'model-parallel-rng' stream, checkpointing.py
    :124): fold the axis index into the key. Use inside shard_map; under
    plain GSPMD, dropout on sharded activations is already
    rank-decorrelated by position."""
    return jax.random.fold_in(key, jax.lax.axis_index(axis))
