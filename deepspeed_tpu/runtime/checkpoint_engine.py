"""Pluggable checkpoint engines.

Analog of the reference's checkpoint-engine layer
(runtime/checkpoint_engine/checkpoint_engine.py:21 ``CheckpointEngine``
ABC with create/save/load/commit; TorchCheckpointEngine;
FastCheckpointEngine over the aio writer; DecoupledCheckpointEngine whose
save returns immediately and commits at the next gradient-accumulation
boundary, engine.py:3273).

Here the tensor payload is orbax (global sharded arrays), so the engines
differ in *when* the write happens and blocks:

  * ``SyncCheckpointEngine``      — blocking save (TorchCheckpointEngine).
  * ``DecoupledCheckpointEngine`` — orbax async save: device→host copy is
    synchronous, serialization+fsync run in a background thread;
    ``maybe_finalize`` is polled by the training loop at GAS boundaries
    and ``commit`` blocks until the write is durable.
  * ``FastCheckpointEngine``      — host-side state (offload optimizer
    shards, metadata blobs) goes through the double-buffered native AIO
    writer (deepspeed_tpu/io/fast_file_writer.py; reference
    deepspeed/io/fast_file_writer.py:44).
"""

from __future__ import annotations

import abc
import os
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine(abc.ABC):
    """Reference ABC: checkpoint_engine.py:21."""

    def __init__(self, config_params=None):
        self.config_params = config_params

    def create(self, tag: str):
        """Log/prepare for a save under ``tag`` (reference: create)."""

    @abc.abstractmethod
    def save(self, path: str, state_tree: Any):
        """Persist a pytree of (sharded) arrays at ``path``."""

    @abc.abstractmethod
    def load(self, path: str, abstract_tree: Any = None):
        """Restore a pytree saved by ``save``; ``abstract_tree`` carries
        target shapes/dtypes/shardings (resharding on topology change)."""

    def commit(self, tag: str) -> bool:
        """Make the save durable / visible (reference: commit). Blocking."""
        return True

    def maybe_finalize(self) -> bool:
        """Non-blocking poll: True when no save is in flight."""
        return True


def _restore(ckptr, path: str, abstract_tree: Any):
    if abstract_tree is None:
        return ckptr.restore(path)
    return ckptr.restore(path, abstract_tree)


def load_partial(path: str, subset_tree: Any):
    """Restore only the entries named by ``subset_tree`` (which may omit
    top-level keys the checkpoint holds — optimizer payloads skipped on
    load). StandardRestore has no partial mode; the PyTree layer does."""
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        rargs = ocp.checkpoint_utils.construct_restore_args(subset_tree)
        return ckptr.restore(path, args=ocp.args.PyTreeRestore(
            item=subset_tree, restore_args=rargs, partial_restore=True))


class SyncCheckpointEngine(CheckpointEngine):
    """Blocking orbax save/restore (TorchCheckpointEngine analog)."""

    def save(self, path: str, state_tree: Any):
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, state_tree, force=True)

    def load(self, path: str, abstract_tree: Any = None):
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            return _restore(ckptr, path, abstract_tree)


class DecoupledCheckpointEngine(CheckpointEngine):
    """Async save: returns after the device→host snapshot; the file write
    completes in the background (DecoupledCheckpointEngine /
    FastCheckpointEngine double-buffering semantics, commit at the next
    GAS boundary engine.py:3273)."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        self._ckptr = None
        self._done = None  # threading.Event set when the write finishes

    def _checkpointer(self):
        import orbax.checkpoint as ocp

        if self._ckptr is None:
            self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        return self._ckptr

    def save(self, path: str, state_tree: Any):
        import threading

        import orbax.checkpoint as ocp

        ckptr = self._checkpointer()
        ckptr.save(path, args=ocp.args.StandardSave(state_tree), force=True)
        # orbax has no non-blocking "done?" probe, so watch the write from
        # a side thread: maybe_finalize stays truly non-blocking and the
        # training loop never stalls on an unfinished save
        done = threading.Event()

        def watch():
            try:
                ckptr.wait_until_finished()
            finally:
                done.set()

        self._done = done
        threading.Thread(target=watch, name="ckpt-commit-watch",
                         daemon=True).start()

    def load(self, path: str, abstract_tree: Any = None):
        import orbax.checkpoint as ocp

        # loads never race an in-flight save of the same tree
        self._checkpointer().wait_until_finished()
        with ocp.StandardCheckpointer() as ckptr:
            return _restore(ckptr, path, abstract_tree)

    def commit(self, tag: str) -> bool:
        self._checkpointer().wait_until_finished()
        self._done = None
        log_dist(f"async checkpoint committed: {tag}", ranks=[0])
        return True

    def maybe_finalize(self) -> bool:
        if self._done is not None and not self._done.is_set():
            return False  # write still in flight — do not block the step
        self._checkpointer().check_for_errors()
        return True

    def __del__(self):  # pragma: no cover - gc timing
        try:
            if self._ckptr is not None:
                self._ckptr.wait_until_finished()
        except Exception:
            pass


class FastCheckpointEngine(SyncCheckpointEngine):
    """Sync device payload + double-buffered AIO for host blobs.

    The orbax payload path is identical to Sync; what changes is
    ``save_host_blob``: offload-optimizer shards and other host-resident
    byte streams go through FastFileWriter (O_DIRECT-friendly, pipelined
    — reference deepspeed/io/fast_file_writer.py:44).
    """

    def save_host_blob(self, data, path: str):
        """Write host bytes through the pipelined AIO writer.

        ``data`` is either ``bytes`` or a callable taking a write-only
        file-like object (e.g. ``lambda f: np.savez(f, **arrays)``) — the
        callable form streams through the double buffer instead of
        materializing the whole blob in RAM first. The write lands at a
        tmp path and is os.replace'd on success so a crash mid-write
        never corrupts a previously-published file.
        """
        tmp = f"{path}.{os.getpid()}.tmp"
        from deepspeed_tpu.io.fast_file_writer import FastFileWriter

        with FastFileWriter(tmp) as w:
            if callable(data):
                data(_WriteStream(w))
            else:
                w.write(data)
        os.replace(tmp, path)


class _WriteStream:
    """Minimal write-only file object over FastFileWriter (zipfile/np.savez
    compatible: unseekable streams get zipfile's _Tellable wrapper; the
    ``read`` stub makes numpy's zipfile_factory treat it as a file object
    rather than a path)."""

    def __init__(self, writer):
        self._w = writer

    def write(self, b) -> int:
        return self._w.write(bytes(b))

    def flush(self):
        pass

    def seekable(self) -> bool:
        return False

    def read(self, *args):
        import io

        raise io.UnsupportedOperation("write-only stream")


_ENGINES = {
    "": SyncCheckpointEngine,
    "torch": SyncCheckpointEngine,
    "sync": SyncCheckpointEngine,
    "decoupled": DecoupledCheckpointEngine,
    "async": DecoupledCheckpointEngine,
    "fast": FastCheckpointEngine,
}


def make_checkpoint_engine(checkpoint_config) -> CheckpointEngine:
    """Select the engine from the config block (reference
    engine.py:1462 _configure_checkpointing)."""
    async_save = getattr(checkpoint_config, "async_save", False)
    fast = getattr(checkpoint_config, "parallel_write_pipeline", False)
    if async_save and fast:
        logger.warning(
            "checkpoint: both async_save and parallel_write_pipeline set; "
            "async_save (decoupled engine) wins — the pipelined host-blob "
            "writer only applies to the synchronous engine")
    name = "decoupled" if async_save else ("fast" if fast else "")
    cls = _ENGINES[name]
    return cls(checkpoint_config)
