"""Logical-axis sharding rules: ZeRO stages as sharding declarations.

This module replaces the reference's runtime partitioning machinery —
ZeRO stage 1/2 optimizer partitioning (runtime/zero/stage_1_and_2.py:134),
ZeRO-3 parameter partitioning + fetch coordinator
(runtime/zero/stage3.py:148, partitioned_param_coordinator.py:73), and
AutoTP layer surgery (module_inject/auto_tp.py:194) — with t5x-style
logical-axis annotations compiled by GSPMD:

  * every model parameter carries a tuple of *logical* axis names
    ("embed", "mlp", "heads", ...);
  * a rule table maps logical axes → mesh axes depending on the configured
    ZeRO stage / TP / EP degrees;
  * XLA inserts the all-gathers (ZeRO-3 fetch), reduce-scatters (ZeRO-2
    grad partitioning) and all-reduces (TP) that DeepSpeed performs by hand,
    and its latency-hiding scheduler overlaps them (the prefetch window of
    partitioned_param_coordinator.py:310 for free).

ZeRO stage → sharding plan:

  stage 0: params/grads/opt replicated over data axes.
  stage 1: optimizer state + fp32 master weights shard over ("fsdp",)
           [+ ("dp","fsdp") when hpZ shrinks fsdp — see below].
  stage 2: + gradients shard over fsdp (reduce-scatter instead of
           all-reduce; same comm volume as stage_1_and_2.py:1615).
  stage 3: + parameters shard over fsdp (all-gather on use = stage3.py
           fetch_sub_module; XLA schedules the prefetch).

hpZ (ZeRO++ hierarchical partition, partition_parameters.py:1806): set
``zero_hpz_partition_size=k`` → mesh fsdp=k (intra-slice, ICI), dp=N/k
(inter-slice, DCN). Params shard only over fsdp (gathers stay on ICI);
optimizer state shards over ("dp","fsdp") so state is still split N ways.
MiCS (runtime/zero/mics.py) is the same construction with the shard group
chosen by ``mics_shard_size``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import Config
from deepspeed_tpu.utils.logging import warning_once

# Logical axis vocabulary used by the model zoo (models/layers.py).
LOGICAL_AXES = (
    "batch", "seq", "embed", "mlp", "heads", "kv_heads", "head_dim",
    "vocab", "layers", "expert", "norm", "stack",
)

# Tensor-parallel rule table (AutoTP analog): column-parallel dims.
TP_RULES: Tuple[Tuple[str, Any], ...] = (
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
)

# Fully-sharded-data-parallel rule: shard the embed (d_model) dim.
FSDP_RULES: Tuple[Tuple[str, Any], ...] = (("embed", "fsdp"),)

# Expert parallel: experts shard over ep.
EP_RULES: Tuple[Tuple[str, Any], ...] = (("expert", "ep"),)

# Pipeline: the stacked-layer dim shards over pp (GSPMD spatial pipeline).
PP_RULES: Tuple[Tuple[str, Any], ...] = (("layers", "pp"),)

# Activation rules.
ACT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp", "ep")),
    ("seq", "sp"),
)


def spec_from_logical(
    logical_axes: Sequence[Optional[str]],
    rules: Sequence[Tuple[str, Any]],
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec.

    First matching rule wins per dim; a mesh axis already used by an earlier
    dim is skipped (GSPMD forbids reuse within one spec).
    """
    used: set = set()
    out = []
    for name in logical_axes:
        entry: Any = None
        if name is not None:
            for lname, maxes in rules:
                if lname != name:
                    continue
                cand = (maxes,) if isinstance(maxes, str) else tuple(maxes)
                cand = tuple(a for a in cand if a not in used)
                if cand:
                    entry = cand[0] if len(cand) == 1 else cand
                    used.update(cand)
                break
        out.append(entry)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Per-role rule tables for one (config, mesh) pair."""

    mesh: Mesh
    param_rules: Tuple[Tuple[str, Any], ...]
    grad_rules: Tuple[Tuple[str, Any], ...]
    opt_rules: Tuple[Tuple[str, Any], ...]
    act_rules: Tuple[Tuple[str, Any], ...] = ACT_RULES

    def param_spec(self, logical_axes) -> PartitionSpec:
        return spec_from_logical(logical_axes, self.param_rules)

    def grad_spec(self, logical_axes) -> PartitionSpec:
        return spec_from_logical(logical_axes, self.grad_rules)

    def opt_spec(self, logical_axes) -> PartitionSpec:
        return spec_from_logical(logical_axes, self.opt_rules)

    # tree-level helpers ----------------------------------------------------
    def param_shardings(self, spec_tree):
        from jax.tree_util import keystr, tree_map_with_path

        # z3-leaf-marked paths keep params replicated over data axes
        # (grad/opt shardings are unaffected, like the reference where
        # leaf modules change fetch behavior, not partitioning of state)
        return tree_map_with_path(
            lambda kp, ax: NamedSharding(
                self.mesh, z3_leaf_spec(keystr(kp), self.param_spec(ax))),
            spec_tree,
            is_leaf=_is_axes_leaf,
        )

    def grad_shardings(self, spec_tree):
        return jax.tree.map(
            lambda ax: NamedSharding(self.mesh, self.grad_spec(ax)),
            spec_tree,
            is_leaf=_is_axes_leaf,
        )

    def opt_shardings(self, spec_tree):
        return jax.tree.map(
            lambda ax: NamedSharding(self.mesh, self.opt_spec(ax)),
            spec_tree,
            is_leaf=_is_axes_leaf,
        )


def _is_axes_leaf(x) -> bool:
    return isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x)


# ---------------------------------------------------------------------------
# z3 leaf modules (reference deepspeed/utils/z3_leaf_module.py:149)
# ---------------------------------------------------------------------------
# The reference marks modules whose params must be fetched/released as one
# unit instead of per-submodule (granularity control for the ZeRO-3
# coordinator). The GSPMD analog: params under a marked subtree are kept
# REPLICATED over the data axes (dp/fsdp) instead of fully sharded — the
# "always resident as a unit" behavior — while tp/ep sharding still
# applies. Patterns are substrings of the param path (jax.tree keystr).

_Z3_LEAF_PATTERNS: list = []
_DATA_AXES = ("dp", "fsdp")


def set_z3_leaf_modules(patterns) -> list:
    """Mark param-path substrings as leaf units (reference
    set_z3_leaf_modules takes module classes; paths are the tree-world
    handle). Returns the active pattern list."""
    if isinstance(patterns, str):
        patterns = [patterns]
    for p in patterns:
        if p not in _Z3_LEAF_PATTERNS:
            _Z3_LEAF_PATTERNS.append(p)
    return list(_Z3_LEAF_PATTERNS)


def unset_z3_leaf_modules(patterns=None) -> list:
    if patterns is None:
        _Z3_LEAF_PATTERNS.clear()
    else:
        for p in ([patterns] if isinstance(patterns, str) else patterns):
            if p in _Z3_LEAF_PATTERNS:
                _Z3_LEAF_PATTERNS.remove(p)
    return list(_Z3_LEAF_PATTERNS)


def get_z3_leaf_modules() -> list:
    return list(_Z3_LEAF_PATTERNS)


def z3_leaf_spec(path: str, spec: PartitionSpec) -> PartitionSpec:
    """Strip data axes from a spec when ``path`` matches a leaf pattern."""
    if not _Z3_LEAF_PATTERNS or not any(p in path for p in _Z3_LEAF_PATTERNS):
        return spec
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, str):
            out.append(None if entry in _DATA_AXES else entry)
        else:
            kept = tuple(a for a in entry if a not in _DATA_AXES)
            out.append(kept[0] if len(kept) == 1 else (kept or None))
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def make_sharding_plan(config: Config, mesh: Mesh) -> ShardingPlan:
    """Compile the config's ZeRO/TP/EP choices into rule tables."""
    stage = config.zero_optimization.stage

    base: list = list(TP_RULES) + list(EP_RULES) + list(PP_RULES)

    param_rules = list(base)
    if stage >= 3:
        param_rules += list(FSDP_RULES)

    grad_rules = list(base)
    if stage >= 2:
        grad_rules += list(FSDP_RULES)

    # Optimizer state / fp32 master weights: stage >= 1 shards over fsdp;
    # with hpZ (dp axis > 1 while fsdp carries the intra-slice shard) the
    # state additionally shards over dp so it is still split N ways.
    opt_rules = list(base)
    if stage >= 1:
        if mesh.shape["dp"] > 1 and config.zero_optimization.zero_hpz_partition_size > 1:
            opt_rules += [("embed", ("dp", "fsdp"))]
        else:
            opt_rules += list(FSDP_RULES)

    if stage >= 1 and mesh.shape["fsdp"] == 1 and mesh.shape["dp"] > 1:
        warning_once(
            "ZeRO stage >= 1 configured but mesh fsdp axis is 1; state will "
            "not shard. Put your data-parallel degree on the fsdp axis "
            "(TopologyConfig(fsdp=-1)) to enable partitioning."
        )

    return ShardingPlan(
        mesh=mesh,
        param_rules=tuple(param_rules),
        grad_rules=tuple(grad_rules),
        opt_rules=tuple(opt_rules),
    )


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


# trace-time switch: inside partial-manual shard_map regions (the pp
# pipeline) sharding constraints on auto axes trip an XLA SPMD bug
# ("Invalid binary instruction opcode copy"); the pipeline disables them
# while tracing its body — batch/tp shardings still propagate from inputs.
_CONSTRAINTS_DISABLED = False
_FORCE_F32 = False


class disable_constraints:
    def __enter__(self):
        global _CONSTRAINTS_DISABLED
        self._prev = _CONSTRAINTS_DISABLED
        _CONSTRAINTS_DISABLED = True

    def __exit__(self, *a):
        global _CONSTRAINTS_DISABLED
        _CONSTRAINTS_DISABLED = self._prev
        return False


class force_f32:
    """Trace-time override: model bodies compute in f32 (CPU shard_map
    bf16 workaround — see parallel/pipeline.py)."""

    def __enter__(self):
        global _FORCE_F32
        self._prev = _FORCE_F32
        _FORCE_F32 = True

    def __exit__(self, *a):
        global _FORCE_F32
        _FORCE_F32 = self._prev
        return False


def effective_dtype(requested):
    import jax.numpy as jnp

    return jnp.float32 if _FORCE_F32 else requested


def constrain_activation(x, logical_axes: Sequence[Optional[str]]):
    """Apply the activation sharding rules to an intermediate value.

    Usable inside jit-compiled model code; a no-op when no global mesh is
    set (e.g. plain single-device unit tests). This is how models declare
    batch/sequence sharding (dp/fsdp/ep × sp) without knowing the topology.
    """
    from deepspeed_tpu.parallel import topology

    if _CONSTRAINTS_DISABLED:
        return x
    mesh = topology._GLOBAL_MESH
    if mesh is None or all(s == 1 for s in mesh.shape.values()):
        return x
    spec = spec_from_logical(logical_axes, ACT_RULES + TP_RULES)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
