"""Logical-axis sharding rules: ZeRO stages as sharding declarations.

This module replaces the reference's runtime partitioning machinery —
ZeRO stage 1/2 optimizer partitioning (runtime/zero/stage_1_and_2.py:134),
ZeRO-3 parameter partitioning + fetch coordinator
(runtime/zero/stage3.py:148, partitioned_param_coordinator.py:73), and
AutoTP layer surgery (module_inject/auto_tp.py:194) — with t5x-style
logical-axis annotations compiled by GSPMD:

  * every model parameter carries a tuple of *logical* axis names
    ("embed", "mlp", "heads", ...);
  * a rule table maps logical axes → mesh axes depending on the configured
    ZeRO stage / TP / EP degrees;
  * XLA inserts the all-gathers (ZeRO-3 fetch), reduce-scatters (ZeRO-2
    grad partitioning) and all-reduces (TP) that DeepSpeed performs by hand,
    and its latency-hiding scheduler overlaps them (the prefetch window of
    partitioned_param_coordinator.py:310 for free).

ZeRO stage → sharding plan:

  stage 0: params/grads/opt replicated over data axes.
  stage 1: optimizer state + fp32 master weights shard over ("fsdp",)
           [+ ("dp","fsdp") when hpZ shrinks fsdp — see below].
  stage 2: + gradients shard over fsdp (reduce-scatter instead of
           all-reduce; same comm volume as stage_1_and_2.py:1615).
  stage 3: + parameters shard over fsdp (all-gather on use = stage3.py
           fetch_sub_module; XLA schedules the prefetch).

hpZ (ZeRO++ hierarchical partition, partition_parameters.py:1806): set
``zero_hpz_partition_size=k`` → mesh fsdp=k (intra-slice, ICI), dp=N/k
(inter-slice, DCN). Params shard only over fsdp (gathers stay on ICI);
optimizer state shards over ("dp","fsdp") so state is still split N ways.
MiCS (runtime/zero/mics.py) is the same construction with the shard group
chosen by ``mics_shard_size``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import Config
from deepspeed_tpu.utils.logging import warning_once
from deepspeed_tpu.utils import jaxcompat

# Logical axis vocabulary used by the model zoo (models/layers.py).
LOGICAL_AXES = (
    "batch", "seq", "embed", "mlp", "heads", "kv_heads", "head_dim",
    "vocab", "layers", "expert", "norm", "stack",
)

# Tensor-parallel rule table (AutoTP analog): column-parallel dims.
TP_RULES: Tuple[Tuple[str, Any], ...] = (
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
)

# Fully-sharded-data-parallel rule: shard the embed (d_model) dim.
FSDP_RULES: Tuple[Tuple[str, Any], ...] = (("embed", "fsdp"),)

# Expert parallel: experts shard over ep.
EP_RULES: Tuple[Tuple[str, Any], ...] = (("expert", "ep"),)

# Pipeline: the stacked-layer dim shards over pp (GSPMD spatial pipeline).
PP_RULES: Tuple[Tuple[str, Any], ...] = (("layers", "pp"),)

# Activation rules.
ACT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp", "ep")),
    ("seq", "sp"),
)


def spec_from_logical(
    logical_axes: Sequence[Optional[str]],
    rules: Sequence[Tuple[str, Any]],
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec.

    First matching rule wins per dim; a mesh axis already used by an earlier
    dim is skipped (GSPMD forbids reuse within one spec).
    """
    used: set = set()
    out = []
    for name in logical_axes:
        entry: Any = None
        if name is not None:
            for lname, maxes in rules:
                if lname != name:
                    continue
                cand = (maxes,) if isinstance(maxes, str) else tuple(maxes)
                cand = tuple(a for a in cand if a not in used)
                if cand:
                    entry = cand[0] if len(cand) == 1 else cand
                    used.update(cand)
                break
        out.append(entry)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Per-role rule tables for one (config, mesh) pair."""

    mesh: Mesh
    param_rules: Tuple[Tuple[str, Any], ...]
    grad_rules: Tuple[Tuple[str, Any], ...]
    opt_rules: Tuple[Tuple[str, Any], ...]
    act_rules: Tuple[Tuple[str, Any], ...] = ACT_RULES

    def param_spec(self, logical_axes) -> PartitionSpec:
        return spec_from_logical(logical_axes, self.param_rules)

    def grad_spec(self, logical_axes) -> PartitionSpec:
        return spec_from_logical(logical_axes, self.grad_rules)

    def opt_spec(self, logical_axes) -> PartitionSpec:
        return spec_from_logical(logical_axes, self.opt_rules)

    # tree-level helpers ----------------------------------------------------
    def param_shardings(self, spec_tree):
        from jax.tree_util import keystr, tree_map_with_path

        # z3-leaf-marked paths keep params replicated over data axes
        # (grad/opt shardings are unaffected, like the reference where
        # leaf modules change fetch behavior, not partitioning of state)
        return tree_map_with_path(
            lambda kp, ax: NamedSharding(
                self.mesh, z3_leaf_spec(keystr(kp), self.param_spec(ax))),
            spec_tree,
            is_leaf=_is_axes_leaf,
        )

    def grad_shardings(self, spec_tree):
        return jax.tree.map(
            lambda ax: NamedSharding(self.mesh, self.grad_spec(ax)),
            spec_tree,
            is_leaf=_is_axes_leaf,
        )

    def opt_shardings(self, spec_tree):
        return jax.tree.map(
            lambda ax: NamedSharding(self.mesh, self.opt_spec(ax)),
            spec_tree,
            is_leaf=_is_axes_leaf,
        )


def _is_axes_leaf(x) -> bool:
    return isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x)


# ---------------------------------------------------------------------------
# z3 leaf modules (reference deepspeed/utils/z3_leaf_module.py:149)
# ---------------------------------------------------------------------------
# The reference marks modules whose params must be fetched/released as one
# unit instead of per-submodule (granularity control for the ZeRO-3
# coordinator). The GSPMD analog: params under a marked subtree are kept
# REPLICATED over the data axes (dp/fsdp) instead of fully sharded — the
# "always resident as a unit" behavior — while tp/ep sharding still
# applies. Patterns are substrings of the param path (jax.tree keystr).

_Z3_LEAF_PATTERNS: list = []
_DATA_AXES = ("dp", "fsdp")


def set_z3_leaf_modules(patterns) -> list:
    """Mark param-path substrings as leaf units (reference
    set_z3_leaf_modules takes module classes; paths are the tree-world
    handle). Returns the active pattern list."""
    if isinstance(patterns, str):
        patterns = [patterns]
    for p in patterns:
        if p not in _Z3_LEAF_PATTERNS:
            _Z3_LEAF_PATTERNS.append(p)
    return list(_Z3_LEAF_PATTERNS)


def unset_z3_leaf_modules(patterns=None) -> list:
    if patterns is None:
        _Z3_LEAF_PATTERNS.clear()
    else:
        for p in ([patterns] if isinstance(patterns, str) else patterns):
            if p in _Z3_LEAF_PATTERNS:
                _Z3_LEAF_PATTERNS.remove(p)
    return list(_Z3_LEAF_PATTERNS)


def get_z3_leaf_modules() -> list:
    return list(_Z3_LEAF_PATTERNS)


def z3_leaf_spec(path: str, spec: PartitionSpec) -> PartitionSpec:
    """Strip data axes from a spec when ``path`` matches a leaf pattern."""
    if not _Z3_LEAF_PATTERNS or not any(p in path for p in _Z3_LEAF_PATTERNS):
        return spec
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, str):
            out.append(None if entry in _DATA_AXES else entry)
        else:
            kept = tuple(a for a in entry if a not in _DATA_AXES)
            out.append(kept[0] if len(kept) == 1 else (kept or None))
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def make_sharding_plan(config: Config, mesh: Mesh) -> ShardingPlan:
    """Compile the config's ZeRO/TP/EP choices into rule tables."""
    stage = config.zero_optimization.stage

    base: list = list(TP_RULES) + list(EP_RULES) + list(PP_RULES)

    param_rules = list(base)
    if stage >= 3:
        param_rules += list(FSDP_RULES)

    grad_rules = list(base)
    if stage >= 2:
        grad_rules += list(FSDP_RULES)

    # Optimizer state / fp32 master weights: stage >= 1 shards over fsdp;
    # with hpZ (dp axis > 1 while fsdp carries the intra-slice shard) the
    # state additionally shards over dp so it is still split N ways.
    opt_rules = list(base)
    if stage >= 1:
        if mesh.shape["dp"] > 1 and config.zero_optimization.zero_hpz_partition_size > 1:
            opt_rules += [("embed", ("dp", "fsdp"))]
        else:
            opt_rules += list(FSDP_RULES)

    if stage >= 1 and mesh.shape["fsdp"] == 1 and mesh.shape["dp"] > 1:
        warning_once(
            "ZeRO stage >= 1 configured but mesh fsdp axis is 1; state will "
            "not shard. Put your data-parallel degree on the fsdp axis "
            "(TopologyConfig(fsdp=-1)) to enable partitioning."
        )

    return ShardingPlan(
        mesh=mesh,
        param_rules=tuple(param_rules),
        grad_rules=tuple(grad_rules),
        opt_rules=tuple(opt_rules),
    )


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


# trace-time switch: inside partial-manual shard_map regions (the pp
# pipeline) sharding constraints on auto axes trip an XLA SPMD bug
# ("Invalid binary instruction opcode copy"); the pipeline disables them
# while tracing its body — batch/tp shardings still propagate from inputs.
_CONSTRAINTS_DISABLED = False
_FORCE_F32 = False


class disable_constraints:
    def __enter__(self):
        global _CONSTRAINTS_DISABLED
        self._prev = _CONSTRAINTS_DISABLED
        _CONSTRAINTS_DISABLED = True

    def __exit__(self, *a):
        global _CONSTRAINTS_DISABLED
        _CONSTRAINTS_DISABLED = self._prev
        return False


_MANUAL_AXES: frozenset = frozenset()


class manual_axes:
    """Trace-scoped marker for partial-manual shard_map regions: the
    named axes are MANUAL inside (not addressable by
    with_sharding_constraint), so activation constraints strip them while
    the auto axes (tp/sp) stay live. Contrast disable_constraints, which
    kills everything — needed only where the XLA bug above applies."""

    def __init__(self, axes):
        self._axes = frozenset(axes)

    def __enter__(self):
        global _MANUAL_AXES
        self._prev = _MANUAL_AXES
        _MANUAL_AXES = _MANUAL_AXES | self._axes

    def __exit__(self, *a):
        global _MANUAL_AXES
        _MANUAL_AXES = self._prev
        return False


_VMAPPED_AXES: frozenset = frozenset()


class vmapped_axes:
    """Trace-scoped marker for explicit per-shard-group vmaps (the qgZ
    per-group gradient construction, engine.py): the named mesh axes are
    carried by the vmapped group dimension, so activation constraints
    inside the mapped trace must not re-pin body dims to them — the
    conflicting pair trips XLA's SPMD grouped-sharding CHECK
    (spmd_partitioner_util.cc num_groups mismatch) once another axis
    (sp) is in play. Unlike manual_axes this strips ONLY activation
    constraints; the qwZ parameter-fetch constraints keep fsdp (params
    are not vmapped)."""

    def __init__(self, axes):
        self._axes = frozenset(axes)

    def __enter__(self):
        global _VMAPPED_AXES
        self._prev = _VMAPPED_AXES
        _VMAPPED_AXES = _VMAPPED_AXES | self._axes

    def __exit__(self, *a):
        global _VMAPPED_AXES
        _VMAPPED_AXES = self._prev
        return False


def _strip_axes_spec(spec, axes) -> PartitionSpec:
    out = []
    for e in spec:
        if e is None:
            out.append(None)
        elif isinstance(e, str):
            out.append(None if e in axes else e)
        else:
            kept = tuple(a for a in e if a not in axes)
            out.append(kept[0] if len(kept) == 1 else (kept or None))
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


class force_f32:
    """Trace-time override: model bodies compute in f32 (CPU shard_map
    bf16 workaround — see parallel/pipeline.py)."""

    def __enter__(self):
        global _FORCE_F32
        self._prev = _FORCE_F32
        _FORCE_F32 = True

    def __exit__(self, *a):
        global _FORCE_F32
        _FORCE_F32 = self._prev
        return False


def effective_dtype(requested):
    import jax.numpy as jnp

    return jnp.float32 if _FORCE_F32 else requested


# ---------------------------------------------------------------------------
# ZeRO++ qwZ for stage 3: quantized parameter all-gather
# ---------------------------------------------------------------------------
# Reference: the stage-3 fetch path gathers INT8-quantized parameters
# (partition_parameters.py:1446 ``all_gather_coalesced`` with
# quantization, kernels csrc/quantization/swizzled_quantize.cu),
# halving all-gather wire volume vs fp16/bf16.
#
# GSPMD expression: inside the train step, each fsdp-sharded weight is
# blockwise int8-quantized *on its shard* (local op), the int8 payload +
# scales are forced through the fsdp gather by a pair of sharding
# constraints (sharded → fsdp-stripped), and dequantized after. XLA's
# latency-hiding scheduler still prefetches per layer inside the scan,
# and with hpZ meshes the gather stays intra-fsdp-group by construction.
# Backward is straight-through: grads flow as if the bf16 weight had
# been used directly (matching the reference, which quantizes only the
# gather wire, not the backward).

_QWZ_BITS: Optional[int] = None
QWZ_BLOCK = 128


def configure_qwz(bits: Optional[int]) -> None:
    """Arm/disarm the quantized stage-3 fetch for model code traced
    while armed. Engines arm it only around their own traces (via
    qwz_context) so two engines in one process can't contaminate each
    other's programs."""
    global _QWZ_BITS
    if bits is not None and bits != 8:
        raise ValueError(f"qwZ stage-3 fetch supports int8 only, got {bits}")
    _QWZ_BITS = bits


class qwz_context:
    """Trace-scoped qwZ arming: ``with qwz_context(8): model.loss(...)``."""

    def __init__(self, bits: Optional[int]):
        self._bits = bits

    def __enter__(self):
        global _QWZ_BITS
        self._prev = _QWZ_BITS
        configure_qwz(self._bits)

    def __exit__(self, *a):
        global _QWZ_BITS
        _QWZ_BITS = self._prev
        return False


def qwz_active() -> bool:
    return _QWZ_BITS is not None


def _has_fsdp(entry) -> bool:
    return entry == "fsdp" or (isinstance(entry, tuple) and "fsdp" in entry)


def _strip_fsdp(entries):
    out = []
    for e in entries:
        if e is None or e == "fsdp":
            out.append(None)
        elif isinstance(e, tuple):
            kept = tuple(a for a in e if a != "fsdp")
            out.append(kept[0] if len(kept) == 1 else (kept or None))
        else:
            out.append(e)
    return out


def _straight_through(fn):
    f = jax.custom_vjp(fn)
    f.defvjp(lambda p: (fn(p), None), lambda _, ct: (ct,))
    return f


def quantized_param_fetch(x, logical_axes: Sequence[Optional[str]],
                          path: str = ""):
    """qwZ stage-3 fetch of one weight: int8 all-gather over fsdp.

    No-op unless a qwz_context is armed, a multi-device mesh with
    fsdp > 1 is active, and the weight actually shards over fsdp with at
    least one non-fsdp dim to carry the quantization blocks (1-D norm
    scales/biases stay on the exact bf16 gather — negligible bytes).
    ``path`` lets z3-leaf-marked params (kept replicated by the plan)
    opt out — they have no fsdp gather to quantize.
    """
    import math

    from jax import numpy as jnp

    from deepspeed_tpu.parallel import topology

    mesh = topology._GLOBAL_MESH
    if (_QWZ_BITS is None or _CONSTRAINTS_DISABLED or mesh is None
            or mesh.shape.get("fsdp", 1) <= 1):
        return x
    rules = TP_RULES + EP_RULES + PP_RULES + FSDP_RULES  # stage-3 params
    spec = z3_leaf_spec(path, spec_from_logical(logical_axes, rules))
    if _MANUAL_AXES:
        # inside a partial-manual region (pipeline stages: pp) the fetch
        # constraints may only name auto axes
        spec = _strip_axes_spec(spec, _MANUAL_AXES)
    entries = list(spec) + [None] * (len(x.shape) - len(spec))
    if not any(_has_fsdp(e) for e in entries):
        return x  # not fsdp-partitioned: nothing to win
    candidates = [i for i, e in enumerate(entries) if not _has_fsdp(e)]
    if not candidates:
        return x
    unsharded = [i for i in candidates if entries[i] is None]
    ax = unsharded[-1] if unsharded else candidates[-1]
    n = x.shape[ax]
    # blocks must tile evenly within the chosen dim's own sharding
    div = 1
    if entries[ax] is not None:
        axes_ = (entries[ax],) if isinstance(entries[ax], str) \
            else tuple(entries[ax])
        for a in axes_:
            div *= mesh.shape.get(a, 1)
    if n % max(div, 1) != 0:
        return x
    block = math.gcd(n // max(div, 1), QWZ_BLOCK)
    if block <= 1:
        return x

    spec_blocked = PartitionSpec(
        *(entries[:ax] + [entries[ax], None] + entries[ax + 1:]))
    spec_gathered = PartitionSpec(
        *_strip_fsdp(entries[:ax] + [entries[ax], None] + entries[ax + 1:]))
    sh_blocked = NamedSharding(mesh, spec_blocked)
    sh_gathered = NamedSharding(mesh, spec_gathered)
    shape = x.shape
    blocked_shape = shape[:ax] + (n // block, block) + shape[ax + 1:]

    def qdq(p):
        from deepspeed_tpu.comm import comm as _comm

        f = p.reshape(blocked_shape).astype(jnp.float32)
        s = jnp.max(jnp.abs(f), axis=ax + 1, keepdims=True) / 127.0
        s = jnp.where(s == 0.0, 1.0, s)
        # scales: compute on the shard, gather (tiny fp32), then re-slice
        # the local part for the quantize step. The re-slice makes the
        # int8 gather data-depend on the scales gather, serializing the
        # pair — XLA CPU's in-process communicator deadlocks on too many
        # concurrent all-gathers, and one-outstanding-per-weight is also
        # the right schedule on TPU (scales ride along, payload follows).
        # Both gathers ride comm.traced_span so the flight ring and
        # Perfetto comm lanes account WIRE bytes (int8 payload + fp32
        # scales), not the logical bf16 tensor.
        s = jax.lax.with_sharding_constraint(s, sh_blocked)
        with _comm.traced_span("all_gather", s, "fsdp", "qwz_scales"):
            s_g = jax.lax.with_sharding_constraint(s, sh_gathered)
        s_local = jax.lax.with_sharding_constraint(s_g, sh_blocked)
        q = jnp.round(f / s_local).astype(jnp.int8)
        # quantize on the shard, gather the int8 payload over fsdp
        q = jax.lax.with_sharding_constraint(q, sh_blocked)
        with _comm.traced_span("all_gather", q, "fsdp",
                               "qwz_param_fetch"):
            q = jax.lax.with_sharding_constraint(q, sh_gathered)
        return (q.astype(jnp.float32) * s_g).reshape(shape).astype(p.dtype)

    return _straight_through(qdq)(x)


def qwz_sequence_barrier(weight, value):
    """Schedule a qwZ fetch of ``weight`` after ``value`` is computed.

    Identity for both operands. On the single-process CPU simulator the
    in-process communicator deadlocks when too many all-gathers block
    concurrently (8 virtual devices share one core's thread pool), so
    independent fetches are chained behind the computation that precedes
    them. On TPU the barrier is skipped — overlapping the gather with
    upstream compute is exactly what the latency-hiding scheduler should
    do."""
    if _QWZ_BITS is None or jax.default_backend() == "tpu":
        return weight, value
    return jax.lax.optimization_barrier((weight, value))


def vocab_parallel_lookup(table, ids, axis: str = "tp"):
    """Embedding lookup on a vocab-sharded table without GSPMD's
    replicate-then-partition fallback.

    A plain ``table[ids]`` gathers along the tp-sharded vocab dim; XLA's
    SPMD partitioner handles that by all-gathering the FULL table to every
    device first ("SPMD will replicate the tensor and then partition it"
    — the warning the round-2 multichip dryrun logged). At 128k vocab ×
    8k hidden that is a 2 GB per-step gather that scales with vocab.

    TPU-first construction (reference bar: the vocab/column-parallel
    embedding in module_inject/layers.py:678): a shard_map manual ONLY
    over the vocab axis — each shard masks ids to its own vocab range,
    gathers locally, zeroes out-of-range rows, and a psum over ``axis``
    assembles the row each token actually hit. Wire cost: one [*, H]
    activation psum (the same volume any tp row-parallel matmul pays)
    instead of a [V, H] table gather. The backward is the mirrored
    masked scatter-add into the LOCAL shard — no replicated-table grad.

    Falls back to the plain gather when no mesh is set, the axis is
    unsharded, vocab doesn't tile, or tracing happens inside a manual
    region (pipeline / 1-bit / zeropp shard_maps).
    """
    from deepspeed_tpu.parallel import topology

    mesh = topology._GLOBAL_MESH
    k = 1 if mesh is None else mesh.shape.get(axis, 1)
    V = table.shape[0]
    if _CONSTRAINTS_DISABLED or _MANUAL_AXES or k <= 1 or V % k != 0:
        return table[ids]  # (nested shard_map in a manual region: no)
    import jax.numpy as jnp
    from jax import lax

    shard = V // k
    # XLA's CPU backend miscompiles bf16 inside partial-manual shard_map
    # regions ("Invalid binary instruction opcode copy" — see
    # parallel/pipeline.py); the lookup is exact row selection, so an f32
    # round-trip on the simulator changes nothing numerically.
    cast = (jax.default_backend() == "cpu" and table.dtype == jnp.bfloat16)
    out_dtype = table.dtype
    if cast:
        table = table.astype(jnp.float32)

    def body(tbl, tok):
        # XLA SPMD-partitioner CHECK workaround (spmd_partitioner_util.cc
        # ExpandDeviceGroupsWithIota): a gather whose operand stays
        # auto-sharded over fsdp inside this partial-manual (tp) region
        # crashes the partitioner on pp×fsdp×tp meshes (the 70B class).
        # Fetch the embed dim up front there — at stage 3 this is
        # exactly the ZeRO-3 all-gather of the local vocab shard the
        # lookup needs anyway. Scoped to meshes WITH a pp axis: on
        # pp-free fsdp×tp meshes the gather partitions fine, and the
        # unconditional fetch would add an fsdp all-gather of the table
        # shard per forward where none is needed.
        if mesh.shape.get("fsdp", 1) > 1 and mesh.shape.get("pp", 1) > 1:
            tbl = jax.lax.with_sharding_constraint(
                tbl, NamedSharding(mesh, PartitionSpec(*([None] * tbl.ndim))))
        start = lax.axis_index(axis) * shard
        local = tok - start
        valid = (local >= 0) & (local < shard)
        rows = tbl[jnp.where(valid, local, 0)]
        rows = rows * valid[..., None].astype(tbl.dtype)
        return lax.psum(rows, axis)

    # clamp like XLA's gather does, so out-of-range ids embed to the same
    # row with or without tp instead of silently zeroing under tp
    ids = jnp.clip(ids, 0, V - 1)
    out = jaxcompat.shard_map(
        body, mesh=mesh,
        in_specs=(PartitionSpec(axis), PartitionSpec()),
        out_specs=PartitionSpec(),
        axis_names=frozenset({axis}),
        check_vma=False,
    )(table, ids)
    return out.astype(out_dtype) if cast else out


# ---------------------------------------------------------------------------
# Stage-3 per-layer overlap engine hooks (PR 6)
# ---------------------------------------------------------------------------
# The ZeRO-Infinity path streams layers host->device; the stage-3 path
# has the same shape of problem one tier up: fsdp-sharded resident layer
# stacks whose per-layer all-gather XLA schedules however it likes.
# These two hooks plug the fsdp gather / grad reduce-scatter into
# runtime/param_stream.py::streamed_layers_prefetch as its ``fetch`` /
# ``grad_sink``, so the SAME staged-carry overlap engine (pin_stage
# optimization barriers) sequences per-layer collectives: layer i+k's
# all-gather issues while layer i computes, and layer i's gradient
# reduce-scatter issues inside the backward scan where it overlaps layer
# i-1's recompute. Reference: the reference's stage-3 prefetch +
# reduce-scatter-inside-backward (partition_parameters.py fetch on
# pre-forward, stage3.py reduce_scatter hooks), and T3's fused
# track-and-trigger overlap (PAPERS.md).


def gathered_layer_spec(logical_axes: Sequence[Optional[str]]
                        ) -> PartitionSpec:
    """Spec of ONE layer's weight after the stage-3 fsdp gather: the
    full param rules minus fsdp (tp/ep stay sharded — only the ZeRO
    partition is gathered, matching the reference's stage-3 fetch)."""
    rules = TP_RULES + EP_RULES + PP_RULES + FSDP_RULES
    spec = spec_from_logical(logical_axes, rules)
    return PartitionSpec(*_strip_fsdp(list(spec)))


def _walk_with_logical(params, logical, fn, path=""):
    # logical_axes leaves are TUPLES of axis names, so jax.tree.map
    # would descend into them; walk the dict tree by hand (same pattern
    # as models/transformer.py::_qwz_fetch_tree)
    if isinstance(logical, tuple):
        return fn(params, logical, path)
    return {k: _walk_with_logical(params[k], logical[k], fn,
                                  f"{path}['{k}']")
            for k in params}


def fsdp_gather_slice(stacked_tree: Any, i, logical_tree: Any) -> Any:
    """``fetch`` hook for the overlap engine on the stage-3 path: slice
    layer ``i`` out of the fsdp-sharded resident ``[L, ...]`` stack and
    constrain it to the fsdp-GATHERED spec, so GSPMD emits that layer's
    all-gather at the point in the staged scan where the engine issues
    it. ``logical_tree`` is ``logical_axes(cfg)["layers"]`` (each leaf a
    tuple starting with "layers", dropped for the per-layer slice).

    Falls back to a plain dynamic slice (gather left to GSPMD's default
    placement) when no mesh / fsdp==1 / constraints disabled / inside a
    manual region.
    """
    from jax import lax

    from deepspeed_tpu.parallel import topology

    mesh = topology._GLOBAL_MESH
    passthrough = (_CONSTRAINTS_DISABLED or mesh is None
                   or mesh.shape.get("fsdp", 1) <= 1)

    def slice_one(stack, axes, path):
        sl = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, keepdims=False),
            stack)
        if passthrough:
            return sl
        spec = gathered_layer_spec(axes[1:])  # drop the "layers" dim
        if _MANUAL_AXES:
            spec = _strip_axes_spec(spec, _MANUAL_AXES)
        return jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, spec)), sl)

    return _walk_with_logical(stacked_tree, logical_tree, slice_one)


def fsdp_scatter_grads(grads: Any, logical_tree: Any) -> Any:
    """``grad_sink`` hook for the overlap engine on the stage-3 path:
    constrain one layer's parameter cotangent back to the fsdp-SHARDED
    spec inside the backward scan, so GSPMD emits the per-layer gradient
    reduce-scatter right there — overlapping the previous layer's
    recompute instead of coalescing at the scan epilogue. This is the
    GSPMD expression of the reference's reduce-scatter-inside-backward
    (stage3.py gradient hooks)."""
    from deepspeed_tpu.parallel import topology

    mesh = topology._GLOBAL_MESH
    if (_CONSTRAINTS_DISABLED or mesh is None
            or mesh.shape.get("fsdp", 1) <= 1):
        return grads
    rules = TP_RULES + EP_RULES + PP_RULES + FSDP_RULES

    def scatter_one(dp, axes, path):
        spec = spec_from_logical(axes[1:], rules)  # drop "layers"
        if _MANUAL_AXES:
            spec = _strip_axes_spec(spec, _MANUAL_AXES)
        return jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, spec)), dp)

    return _walk_with_logical(grads, logical_tree, scatter_one)


def constrain_activation(x, logical_axes: Sequence[Optional[str]]):
    """Apply the activation sharding rules to an intermediate value.

    Usable inside jit-compiled model code; a no-op when no global mesh is
    set (e.g. plain single-device unit tests). This is how models declare
    batch/sequence sharding (dp/fsdp/ep × sp) without knowing the topology.
    """
    from deepspeed_tpu.parallel import topology

    if _CONSTRAINTS_DISABLED:
        return x
    mesh = topology._GLOBAL_MESH
    if mesh is None or all(s == 1 for s in mesh.shape.values()):
        return x
    spec = spec_from_logical(logical_axes, ACT_RULES + TP_RULES)
    if _MANUAL_AXES or _VMAPPED_AXES:
        spec = _strip_axes_spec(spec, _MANUAL_AXES | _VMAPPED_AXES)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
