"""Optimizer construction + mixed-precision master-weight semantics.

Covers the reference's optimizer stack the TPU way:

  * ``get_base_optimizer`` = _configure_basic_optimizer (engine.py:1960):
    name → optax transform. FusedAdam/CPUAdam distinctions disappear —
    XLA fuses the update math (the multi_tensor_apply of
    csrc/adam/multi_tensor_adam.cu is what the compiler does by default).
    Muon (runtime/zero/muon/) maps to optax.contrib.muon, whose
    Newton-Schulz orthogonalization runs sharded under GSPMD — the
    _apply_distributed_muon_update machinery (stage3.py:1537) is implicit.
  * ``MixedPrecisionState`` = BF16_Optimizer semantics
    (runtime/bf16_optimizer.py:37): bf16 compute params + fp32 master
    weights and fp32 optimizer state, updated from fp32-accumulated grads.
    The master tree is sharded per the ZeRO plan (opt rules), which *is*
    ZeRO-1 partitioning.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.config.config import Config, OptimizerConfig
from deepspeed_tpu.utils.logging import logger

ADAM_ALIASES = {"adam", "fusedadam", "cpuadam"}
ADAMW_ALIASES = {"adamw", "fusedadamw"}


def get_base_optimizer(
    opt_config: Optional[OptimizerConfig],
    lr_schedule: Optional[Callable] = None,
) -> Tuple[optax.GradientTransformation, float]:
    """Name → optax transform (reference engine.py:1960). Returns
    (transform, base_lr)."""
    if opt_config is None:
        opt_config = OptimizerConfig(type="adamw", params={})
    name = opt_config.type.lower().replace("_", "")
    p = dict(opt_config.params or {})
    lr = p.pop("lr", 1e-3)
    lr_arg = lr_schedule if lr_schedule is not None else lr

    betas = p.pop("betas", (0.9, 0.999))
    eps = p.pop("eps", 1e-8)
    weight_decay = p.pop("weight_decay", 0.01 if name in ADAMW_ALIASES else 0.0)
    p.pop("torch_adam", None)
    p.pop("adam_w_mode", None)
    muon_extra = {k: p.pop(k) for k in
                  ("ns_steps", "nesterov", "adam_b1", "adam_b2")
                  if k in p} if name == "muon" else {}
    if p:
        logger.warning(f"optimizer '{opt_config.type}': ignoring params {sorted(p)}")

    if name in ADAMW_ALIASES:
        tx = optax.adamw(lr_arg, b1=betas[0], b2=betas[1], eps=eps,
                         weight_decay=weight_decay)
    elif name in ADAM_ALIASES:
        tx = optax.adam(lr_arg, b1=betas[0], b2=betas[1], eps=eps)
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    elif name in ("sgd", "momentum"):
        tx = optax.sgd(lr_arg, momentum=betas[0] if name == "momentum" else 0.0)
    elif name in ("lion", "fusedlion", "cpulion"):
        tx = optax.lion(lr_arg, b1=betas[0], b2=betas[1],
                        weight_decay=weight_decay)
    elif name in ("adagrad", "cpuadagrad"):
        tx = optax.adagrad(lr_arg, eps=eps)
    elif name in ("lamb", "fusedlamb"):
        tx = optax.lamb(lr_arg, b1=betas[0], b2=betas[1], eps=eps,
                        weight_decay=weight_decay)
    elif name == "adafactor":
        tx = optax.adafactor(lr_arg)
    elif name == "muon":
        # from-scratch NS-orthogonalized momentum (runtime/muon.py):
        # path-aware routing covers the zoo's STACKED [L, ...] layer
        # weights (optax.contrib.muon only treats exactly-2D leaves as
        # matrices) and the NS matmuls run on ZeRO-sharded momentum
        # under GSPMD — the distributed Newton-Schulz of the reference
        # (_apply_distributed_muon_update, stage3.py:1537) without its
        # gather/scatter hooks
        from deepspeed_tpu.runtime.muon import muon as _muon

        tx = _muon(
            lr_arg, beta=betas[0],
            weight_decay=weight_decay,
            ns_steps=int(muon_extra.get("ns_steps", 5)),
            nesterov=bool(muon_extra.get("nesterov", True)),
            adam_b1=muon_extra.get("adam_b1", 0.9),
            adam_b2=muon_extra.get("adam_b2", 0.999),
            adam_eps=eps)
    else:
        raise ValueError(f"unknown optimizer type '{opt_config.type}'")
    return tx, lr


class MixedPrecisionState(NamedTuple):
    """fp32 master weights + inner optax state (BF16_Optimizer analog)."""

    master: Any  # fp32 param tree (ZeRO-sharded per opt rules)
    inner: Any  # optax state (same sharding as master)


def init_mixed_precision(params_fp32, tx: optax.GradientTransformation
                         ) -> MixedPrecisionState:
    master = jax.tree.map(lambda p: p.astype(jnp.float32), params_fp32)
    return MixedPrecisionState(master=master, inner=tx.init(master))


def apply_mixed_precision_update(
    state: MixedPrecisionState,
    grads_fp32,
    tx: optax.GradientTransformation,
    compute_dtype,
    grad_clip: float = 0.0,
    grad_scale: Optional[jax.Array] = None,
    skip: Optional[jax.Array] = None,
) -> Tuple[Any, MixedPrecisionState, jax.Array]:
    """One optimizer step (reference BF16_Optimizer.step bf16_optimizer.py:303).

    Returns (new compute-dtype params, new state, global grad norm).
    ``grad_scale`` divides grads (loss-scale unscaling); ``skip`` (bool
    scalar) makes the whole update a no-op (overflow step, reference
    fp16/fused_optimizer.py overflow path).
    """
    if grad_scale is not None:
        grads_fp32 = jax.tree.map(lambda g: g / grad_scale, grads_fp32)

    gnorm = optax.global_norm(grads_fp32)
    if grad_clip and grad_clip > 0:
        scale = jnp.minimum(1.0, grad_clip / (gnorm + 1e-6))
        grads_fp32 = jax.tree.map(lambda g: g * scale, grads_fp32)

    updates, new_inner = tx.update(grads_fp32, state.inner, state.master)
    new_master = optax.apply_updates(state.master, updates)

    if skip is not None:
        new_master = jax.tree.map(
            lambda new, old: jnp.where(skip, old, new), new_master, state.master)
        new_inner = jax.tree.map(
            lambda new, old: jnp.where(skip, old, new) if isinstance(new, jax.Array)
            and new.shape == getattr(old, "shape", None) else new,
            new_inner, state.inner)

    new_params = jax.tree.map(lambda m: m.astype(compute_dtype), new_master)
    return new_params, MixedPrecisionState(new_master, new_inner), gnorm
