"""Learning-rate schedules.

Parity with the reference's runtime/lr_schedules.py (901 LoC): the same
five schedule families with the same config names and params, implemented
as pure ``step -> lr`` callables (optax-style) so they trace into the
compiled train step — no mutable scheduler object stepping outside jit.

  LRRangeTest        lr_schedules.py:LRRangeTest
  OneCycle           lr_schedules.py:OneCycle
  WarmupLR           lr_schedules.py:WarmupLR
  WarmupDecayLR      lr_schedules.py:WarmupDecayLR
  WarmupCosineLR     lr_schedules.py:WarmupCosineLR
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[Any], Any]  # step -> lr (traceable)

VALID_SCHEDULES = (
    "LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR", "WarmupCosineLR",
)


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Schedule:
    def schedule(step):
        s = step / lr_range_test_step_size
        if lr_range_test_staircase:
            s = jnp.floor(s)
        return lr_range_test_min_lr * (1.0 + s * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: Optional[int] = None,
              **_unused) -> Schedule:
    second = cycle_second_step_size or cycle_first_step_size

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (up - down)
        post = step - (cycle_first_step_size + second)
        if decay_step_size > 0:
            decayed = cycle_min_lr / (1.0 + jnp.maximum(post, 0.0)
                                      / decay_step_size * decay_lr_rate)
        else:
            decayed = jnp.asarray(cycle_min_lr, jnp.float32)
        return jnp.where(post > 0, decayed, in_cycle)

    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = "log", **_unused) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip((step + 1.0) / warmup_num_steps, 0.0, 1.0)
        if warmup_type == "log":
            gamma = jnp.log(frac * (math.e - 1.0) + 1.0)
        else:
            gamma = frac
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma

    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_unused) -> Schedule:
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (total_num_steps - step) /
            jnp.maximum(total_num_steps - warmup_num_steps, 1.0),
            0.0, 1.0,
        )
        return jnp.where(step < warmup_num_steps, warm(step),
                         warmup_max_lr * decay)

    return schedule


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.01,
                     warmup_num_steps: int = 1000,
                     cos_min_ratio: float = 0.0001,
                     warmup_max_lr: float = 0.001, **_unused) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = warmup_min_ratio + (1.0 - warmup_min_ratio) * jnp.clip(
            step / jnp.maximum(warmup_num_steps, 1), 0.0, 1.0)
        progress = jnp.clip(
            (step - warmup_num_steps) /
            jnp.maximum(total_num_steps - warmup_num_steps, 1.0), 0.0, 1.0)
        cos = cos_min_ratio + (1.0 - cos_min_ratio) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * progress))
        ratio = jnp.where(step < warmup_num_steps, warm, cos)
        return warmup_max_lr * ratio

    return schedule


_FACTORIES = {
    "LRRangeTest": lr_range_test,
    "OneCycle": one_cycle,
    "WarmupLR": warmup_lr,
    "WarmupDecayLR": warmup_decay_lr,
    "WarmupCosineLR": warmup_cosine_lr,
}


def get_lr_schedule(scheduler_config, base_lr: float = 0.001) -> Optional[Schedule]:
    """Build a schedule from the config block (reference engine
    _configure_lr_scheduler engine.py:1446)."""
    if scheduler_config is None or scheduler_config.type is None:
        return None
    name = scheduler_config.type
    if name not in _FACTORIES:
        raise ValueError(
            f"unknown scheduler '{name}'; valid: {sorted(_FACTORIES)}")
    params = dict(scheduler_config.params or {})
    if name in ("WarmupLR", "WarmupDecayLR", "WarmupCosineLR"):
        params.setdefault("warmup_max_lr", base_lr)
    return _FACTORIES[name](**params)
