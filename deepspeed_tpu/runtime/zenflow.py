"""ZenFlow: stall-free offloaded optimization by importance splitting.

Reference: ``deepspeed/runtime/zenflow/`` (``ZenFlowZeroOptimizer``
zenflow_stage_1_and_2.py:47, ``ZenFlowSelectiveAdamW``
ops/adam/zenflow_torch_adam.py:43) and ``runtime/superoffload/``
(``SuperOffloadOptimizer_Stage3`` :27 with its CPU-side optimizer worker
process superoffload_utils.py:165). ZeRO-Offload stalls the accelerator
>60% of each step waiting for the host optimizer; ZenFlow removes the
stall by splitting coordinates by gradient importance:

  * the top-k fraction of coordinates (per parameter) update **on
    device every step** with a compact Adam whose state covers only
    those coordinates;
  * the rest accumulate on device and flow through the **host optimizer
    asynchronously every ``update_interval`` steps** — the device never
    waits (SuperOffload's worker-process overlap, done with a thread +
    the native CPU optimizer here).

TPU mapping: the selective update is a gather → Adam → scatter jit
(static k, MXU-free VPU work fused by XLA); accumulators live on device
so the per-step host traffic of plain offload disappears; the async host
pass uses the same vectorized native CPU Adam as the offload tier.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.native.cpu_optimizer import CPUAdam
from deepspeed_tpu.utils.logging import log_dist, logger


@dataclasses.dataclass
class ZenFlowConfig:
    """Reference zenflow config block (zenflow_config.py): topk_ratio,
    update_interval, select_strategy/interval, overlap_step."""

    topk_ratio: float = 0.01
    update_interval: int = 4
    select_interval: int = 16  # re-pick important coords every N steps
    overlap_step: bool = True  # async host pass (False = blocking)
    workers: int = 1  # threads splitting the host pass across leaves
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0


class _AsyncWorker:
    """One in-flight host-optimizer pass (SuperOffload worker analog)."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._result = None
        self._error: Optional[BaseException] = None

    def submit(self, fn, *args):
        assert not self.busy, "previous host pass still in flight"
        self._result, self._error = None, None

        def run():
            try:
                self._result = fn(*args)
            except BaseException as e:  # surfaced at collect()
                self._error = e

        self._thread = threading.Thread(target=run, name="zenflow-host-opt",
                                        daemon=True)
        self._thread.start()

    @property
    def busy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def collect(self, block: bool = True):
        if self._thread is None:
            return None
        if not block and self._thread.is_alive():
            return None
        self._thread.join()
        self._thread = None
        if self._error is not None:
            raise self._error
        return self._result


class ZenFlowOptimizer:
    """Importance-split optimizer over a parameter pytree.

    step(grads, params, lr) → new params (same structure/dtype). The
    host fp32 masters are the source of truth for the non-selected
    coordinates; selected coordinates run ahead on device and are folded
    back into the masters at each async-pass boundary.
    """

    def __init__(self, params, config: Optional[ZenFlowConfig] = None,
                 lr: float = 1e-3, param_dtype=None):
        """``params`` seeds the fp32 masters (pass the fp32 init so master
        precision is real, not rounded); ``param_dtype`` overrides the
        dtype of emitted params (the engine's compute dtype)."""
        self.cfg = config or ZenFlowConfig()
        self.lr = float(lr)
        self.steps = 0
        leaves, self._treedef = jax.tree.flatten(params)
        self._shapes = [x.shape for x in leaves]
        self._dtypes = [param_dtype if param_dtype is not None else x.dtype
                        for x in leaves]
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self._ks = [max(1, int(np.ceil(self.cfg.topk_ratio * n)))
                    for n in self._sizes]
        # host fp32 masters + native CPU Adam per leaf. Explicit copies:
        # on CPU backends np.asarray(jax_array) can ALIAS the device
        # buffer, and the host optimizer mutates masters in place — an
        # aliased master would corrupt the caller's (immutable) params.
        self._masters = [np.array(x, np.float32).reshape(-1)
                         for x in leaves]
        self._host_opts = [CPUAdam(n, lr=self.lr, betas=self.cfg.betas,
                                   eps=self.cfg.eps,
                                   weight_decay=self.cfg.weight_decay)
                           for n in self._sizes]
        # device state: accumulators [n], selected idx [k], m/v [k]
        self._acc = [jnp.zeros(n, jnp.float32) for n in self._sizes]
        self._idx = [jnp.arange(k, dtype=jnp.int32) for k in self._ks]
        self._m = [jnp.zeros(k, jnp.float32) for k in self._ks]
        self._v = [jnp.zeros(k, jnp.float32) for k in self._ks]
        self._sel_step = [0] * len(self._ks)
        self._worker = _AsyncWorker()
        self._host_pool = None  # lazy N-worker pool (cfg.workers > 1)
        self._pending_upload: Optional[List[np.ndarray]] = None
        # every coordinate selected since the last fold-in: their grads
        # never reach the host (zeroed at shipment for the current
        # selection, dropped from the accumulator at reselection for past
        # ones), so the device value is authoritative and must survive
        # fold-in even after reselections change self._idx
        self._protected: List[Optional[jnp.ndarray]] = [None] * len(self._ks)
        # device applied selective updates since the last fold-in: only
        # then can the masters be stale for a reselected-away coordinate
        self._updated_since_foldin = [False] * len(self._ks)
        log_dist(
            f"ZenFlow: {len(leaves)} tensors, topk={self.cfg.topk_ratio:.2%}"
            f", update_interval={self.cfg.update_interval}", ranks=[0])

    # -- jitted pieces ---------------------------------------------------
    @staticmethod
    @jax.jit
    def _accumulate(acc, g):
        return acc + g

    @staticmethod
    @jax.jit
    def _selective_adam(flat_param, g, idx, m, v, step, lr, b1, b2, eps):
        """Adam on the selected coordinates only (ZenFlowSelectiveAdamW)."""
        sel_g = g[idx]
        m = b1 * m + (1 - b1) * sel_g
        v = b2 * v + (1 - b2) * sel_g * sel_g
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        upd = lr * mhat / (jnp.sqrt(vhat) + eps)
        new = flat_param.astype(jnp.float32).at[idx].add(-upd)
        return new.astype(flat_param.dtype), m, v

    # -- selection -------------------------------------------------------
    def _reselect(self, i: int, initial: bool = False):
        """Re-pick the top-k coordinates of leaf i by |accumulated grad|
        (reference select_strategy='auto': gradient magnitude). The old
        selection's accumulated grads are dropped — the device already
        applied those updates — and the old coords join the protected set
        until the next fold-in syncs them into the masters."""
        k = self._ks[i]
        if not initial:
            old = self._idx[i]
            self._acc[i] = self._acc[i].at[old].set(0.0)
            if self._updated_since_foldin[i]:
                # masters lack the device updates applied to ``old`` since
                # the last fold-in — protect them until the next fold-in
                # re-syncs. (If a fold-in just ran this step, masters
                # already equal the device values and protection would
                # wrongly revert the host's later updates.)
                self._protected[i] = (old if self._protected[i] is None
                                      else jnp.concatenate(
                                          [self._protected[i], old]))
        _, idx = jax.lax.top_k(jnp.abs(self._acc[i]), k)
        self._idx[i] = idx.astype(jnp.int32)
        self._m[i] = jnp.zeros(k, jnp.float32)
        self._v[i] = jnp.zeros(k, jnp.float32)
        self._sel_step[i] = 0

    # -- host pass -------------------------------------------------------
    def _host_pass(self, host_grads: List[np.ndarray], lr: float,
                   denom: float) -> List[np.ndarray]:
        """One host optimizer pass over all leaves. With workers > 1 the
        leaves split across a thread pool (SuperOffload's N-worker host
        optimizer, superoffload_utils.py:165 — worker *threads* here:
        the native CPUAdam releases the GIL, so threads scale across
        cores without the reference's process plumbing)."""
        def one(i, hg):
            self._host_opts[i].step(self._masters[i], hg / denom, lr=lr)
            return self._masters[i].copy()

        if self.cfg.workers <= 1 or len(host_grads) <= 1:
            return [one(i, hg) for i, hg in enumerate(host_grads)]
        if self._host_pool is None:  # one pool for the whole run
            import concurrent.futures as _fut

            self._host_pool = _fut.ThreadPoolExecutor(
                max_workers=self.cfg.workers,
                thread_name_prefix="zenflow-host")
        return list(self._host_pool.map(one, range(len(host_grads)),
                                        host_grads))

    # -- main ------------------------------------------------------------
    def step(self, grads, params, lr: Optional[float] = None):
        lr = self.lr if lr is None else float(lr)
        self.steps += 1
        g_leaves = jax.tree.leaves(grads)
        p_leaves, treedef = jax.tree.flatten(params)
        cfg = self.cfg

        # fold a finished async host pass into the device params: masters
        # own the non-selected coords; device-selected coords stay ahead.
        # Fold-in only runs with the worker idle (a running pass reads the
        # master arrays), and a newer snapshot supersedes a deferred one —
        # masters mutate cumulatively, so the latest copy is complete.
        done = self._worker.collect(block=not cfg.overlap_step)
        if done is None and not self._worker.busy and \
                self._pending_upload is not None:
            done = self._pending_upload
        if done is not None:
            self._pending_upload = None  # fresh result supersedes deferred
            new_leaves = []
            for i, (pl_, master) in enumerate(zip(p_leaves, done)):
                flat = jnp.asarray(master)
                # device values survive for every coordinate selected
                # since the last fold-in (masters never saw their grads)
                keep = self._idx[i]
                if self._protected[i] is not None:
                    keep = jnp.concatenate([keep, self._protected[i]])
                dev_flat = pl_.reshape(-1).astype(jnp.float32)
                flat = flat.at[keep].set(dev_flat[keep])
                self._masters[i] = np.array(flat)  # copy: host opt mutates
                self._protected[i] = None
                self._updated_since_foldin[i] = False
                new_leaves.append(
                    flat.reshape(self._shapes[i]).astype(self._dtypes[i]))
            p_leaves = new_leaves

        new_p = []
        for i, (pl_, gl) in enumerate(zip(p_leaves, g_leaves)):
            g_flat = gl.reshape(-1).astype(jnp.float32)
            self._acc[i] = self._accumulate(self._acc[i], g_flat)
            if (self.steps - 1) % cfg.select_interval == 0:
                self._reselect(i, initial=self.steps == 1)
            self._sel_step[i] += 1
            flat, self._m[i], self._v[i] = self._selective_adam(
                pl_.reshape(-1), g_flat, self._idx[i], self._m[i],
                self._v[i], jnp.asarray(self._sel_step[i], jnp.float32),
                jnp.asarray(lr, jnp.float32), cfg.betas[0], cfg.betas[1],
                cfg.eps)
            self._updated_since_foldin[i] = True
            new_p.append(flat.reshape(self._shapes[i]))

        if self.steps % cfg.update_interval == 0:
            # ship accumulated (averaged) grads to the host optimizer,
            # zeroing the selected coords (already applied on device)
            host_grads = []
            for i in range(len(new_p)):
                acc = self._acc[i].at[self._idx[i]].set(0.0)
                host_grads.append(np.asarray(acc))
                self._acc[i] = jnp.zeros_like(self._acc[i])
            if self._worker.busy:  # previous pass still running: wait
                self._pending_upload = self._worker.collect(block=True)
            if cfg.overlap_step:
                self._worker.submit(self._host_pass, host_grads, lr,
                                    float(cfg.update_interval))
            else:
                self._pending_upload = self._host_pass(
                    host_grads, lr, float(cfg.update_interval))

        return jax.tree.unflatten(treedef, new_p)

    def finalize(self):
        """Block on any in-flight host pass and fold it in (end of
        training / before checkpoint)."""
        done = self._worker.collect(block=True)
        if done is not None:
            self._pending_upload = done
        return self._pending_upload is not None

    def close(self):
        """Shut the worker pool down (idempotent; gc-safe)."""
        if self._host_pool is not None:
            self._host_pool.shutdown(wait=True)
            self._host_pool = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:
            pass

    def state_dict(self) -> Dict[str, Any]:
        # never snapshot mid-host-pass: the worker mutates masters and
        # CPUAdam moments in place (a torn copy would restore garbage)
        self.finalize()
        def copy_opt(sd):
            return {k: (v.copy() if isinstance(v, np.ndarray) else v)
                    for k, v in sd.items()}

        return {
            "steps": self.steps,
            "masters": [m.copy() for m in self._masters],
            # deep-copy moments: CPUAdam.state_dict returns live buffers
            # the next step mutates in place (a torn async serialization
            # would pair step-N masters with step-N+k moments)
            "host_opt": [copy_opt(o.state_dict()) for o in self._host_opts],
            "idx": [np.asarray(i) for i in self._idx],
            "m": [np.asarray(m) for m in self._m],
            "v": [np.asarray(v) for v in self._v],
            "acc": [np.asarray(a) for a in self._acc],
            "sel_step": list(self._sel_step),
            "protected": [None if p is None else np.asarray(p)
                          for p in self._protected],
            "updated_since_foldin": list(self._updated_since_foldin),
        }

    def load_state_dict(self, sd: Dict[str, Any]):
        self.steps = int(sd["steps"])
        self._masters = [np.array(m, np.float32) for m in sd["masters"]]
        for o, os_ in zip(self._host_opts, sd["host_opt"]):
            o.load_state_dict(os_)
        self._idx = [jnp.asarray(i) for i in sd["idx"]]
        self._m = [jnp.asarray(m) for m in sd["m"]]
        self._v = [jnp.asarray(v) for v in sd["v"]]
        self._acc = [jnp.asarray(a) for a in sd["acc"]]
        self._sel_step = [int(s) for s in sd["sel_step"]]
        self._protected = [None if p is None else jnp.asarray(p)
                           for p in sd.get("protected",
                                           [None] * len(self._acc))]
        # missing in old checkpoints: assume True (protect) — a spurious
        # protection is harmless, a missed one reverts device updates
        self._updated_since_foldin = [bool(b) for b in sd.get(
            "updated_since_foldin", [True] * len(self._acc))]
