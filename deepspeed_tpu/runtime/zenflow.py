"""ZenFlow: stall-free offloaded optimization by importance splitting.

Reference: ``deepspeed/runtime/zenflow/`` (``ZenFlowZeroOptimizer``
zenflow_stage_1_and_2.py:47, ``ZenFlowSelectiveAdamW``
ops/adam/zenflow_torch_adam.py:43) and ``runtime/superoffload/``
(``SuperOffloadOptimizer_Stage3`` :27 with its CPU-side optimizer worker
process superoffload_utils.py:165). ZeRO-Offload stalls the accelerator
>60% of each step waiting for the host optimizer; ZenFlow removes the
stall by splitting coordinates by gradient importance:

  * the top-k fraction of coordinates (per parameter) update **on
    device every step** with a compact Adam whose state covers only
    those coordinates;
  * the rest accumulate on device and flow through the **host optimizer
    asynchronously every ``update_interval`` steps** — the device never
    waits (SuperOffload's worker-process overlap, done with a thread +
    the native CPU optimizer here).

TPU mapping: the selective update is a gather → Adam → scatter jit
(static k, MXU-free VPU work fused by XLA); accumulators live on device
so the per-step host traffic of plain offload disappears; the async host
pass uses the same vectorized native CPU Adam as the offload tier.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.native.cpu_optimizer import CPUAdam
from deepspeed_tpu.utils import memspace
from deepspeed_tpu.utils.logging import log_dist, logger


@dataclasses.dataclass
class ZenFlowConfig:
    """Reference zenflow config block (zenflow_config.py): topk_ratio,
    update_interval, select_strategy/interval, overlap_step."""

    topk_ratio: float = 0.01
    update_interval: int = 4
    select_interval: int = 16  # re-pick important coords every N steps
    overlap_step: bool = True  # async host pass (False = blocking)
    workers: int = 1  # threads splitting the host pass across leaves
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0


class _AsyncWorker:
    """One in-flight host-optimizer pass (SuperOffload worker analog)."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._result = None
        self._error: Optional[BaseException] = None

    def submit(self, fn, *args):
        assert not self.busy, "previous host pass still in flight"
        self._result, self._error = None, None

        def run():
            try:
                self._result = fn(*args)
            except BaseException as e:  # surfaced at collect()
                self._error = e

        self._thread = threading.Thread(target=run, name="zenflow-host-opt",
                                        daemon=True)
        self._thread.start()

    @property
    def busy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def collect(self, block: bool = True):
        if self._thread is None:
            return None
        if not block and self._thread.is_alive():
            return None
        self._thread.join()
        self._thread = None
        if self._error is not None:
            raise self._error
        return self._result


def _unique_local_shards(x):
    """Yield (index, [devices], host_data) per DISTINCT addressable shard
    slice of a jax.Array (plain arrays yield one full-shape shard)."""
    shards = getattr(x, "addressable_shards", None)
    if not shards:
        yield (tuple(slice(None) for _ in np.shape(x)), [None],
               np.asarray(x))
        return
    by_index: Dict[Any, Tuple[List, Any]] = {}
    for s in shards:
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        if key in by_index:
            by_index[key][0].append(s.device)
        else:
            by_index[key] = ([s.device], s.data)
    for key, (devs, data) in sorted(by_index.items()):
        index = tuple(slice(*k) for k in key)
        yield index, devs, data


def _rebuild_global(shape, sharding, metas, flat_bufs):
    """Per-shard host buffers → one global jax.Array with the leaf's
    original sharding (offload.py's multi-host reassembly pattern)."""
    if sharding is None or metas[0][1][0] is None:
        return jnp.asarray(flat_bufs[0].reshape(shape))
    arrays = []
    for (index, devs), buf in zip(metas, flat_bufs):
        shard_shape = tuple(
            len(range(*sl.indices(dim))) for sl, dim in zip(index, shape))
        piece = buf.reshape(shard_shape)
        for d in devs:
            arrays.append(jax.device_put(piece, d))
    return jax.make_array_from_single_device_arrays(shape, sharding, arrays)


class ZenFlowOptimizer:
    """Importance-split optimizer over a parameter pytree.

    step(grads, params, lr) → new params (same structure/dtype). The
    host fp32 masters are the source of truth for the non-selected
    coordinates; selected coordinates run ahead on device and are folded
    back into the masters at each async-pass boundary.
    """

    def __init__(self, params, config: Optional[ZenFlowConfig] = None,
                 lr: float = 1e-3, param_dtype=None):
        """``params`` seeds the fp32 masters (pass the fp32 init so master
        precision is real, not rounded); ``param_dtype`` overrides the
        dtype of emitted params (the engine's compute dtype)."""
        self.cfg = config or ZenFlowConfig()
        self.lr = float(lr)
        self.steps = 0
        leaves, self._treedef = jax.tree.flatten(params)
        self._shapes = [x.shape for x in leaves]
        self._dtypes = [param_dtype if param_dtype is not None else x.dtype
                        for x in leaves]
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self._ks = [max(1, int(np.ceil(self.cfg.topk_ratio * n)))
                    for n in self._sizes]
        # host fp32 masters + native CPU Adam PER LOCAL SHARD of each
        # leaf: each process touches only the slices its devices hold, so
        # multi-host never flattens a full leaf host-side (the reference
        # SuperOffload worker owns its rank's partition the same way,
        # superoffload_utils.py:165). Shards dedupe by index — replicated
        # leaves run one host optimizer per distinct slice. Explicit
        # copies: on CPU backends np.asarray(jax_array) can ALIAS the
        # device buffer, and the host optimizer mutates masters in place.
        # normalize to device memory kind: the offload tier may hand us
        # pinned-host fp32 masters (engine host-side init), but fold-ins
        # rebuild/consume masters as device arrays
        def _dev_sharding(x):
            s = getattr(x, "sharding", None)
            if s is not None and getattr(s, "memory_kind", None) == \
                    "pinned_host":
                s = memspace.with_memory_kind(s, "device")
            return s

        self._shardings = [_dev_sharding(x) for x in leaves]
        self._shard_meta: List[List[Tuple]] = []  # per leaf: (index, devs)
        self._masters: List[List[np.ndarray]] = []
        self._host_opts: List[List[CPUAdam]] = []
        for x in leaves:
            metas, bufs, opts = [], [], []
            for idx, devs, data in _unique_local_shards(x):
                metas.append((idx, devs))
                buf = np.array(data, np.float32).reshape(-1)
                bufs.append(buf)
                opts.append(CPUAdam(buf.size, lr=self.lr,
                                    betas=self.cfg.betas, eps=self.cfg.eps,
                                    weight_decay=self.cfg.weight_decay))
            self._shard_meta.append(metas)
            self._masters.append(bufs)
            self._host_opts.append(opts)
        # device state: accumulators [n], selected idx [k], m/v [k]
        self._acc = [jnp.zeros(n, jnp.float32) for n in self._sizes]
        self._idx = [jnp.arange(k, dtype=jnp.int32) for k in self._ks]
        self._m = [jnp.zeros(k, jnp.float32) for k in self._ks]
        self._v = [jnp.zeros(k, jnp.float32) for k in self._ks]
        self._sel_step = [0] * len(self._ks)
        self._worker = _AsyncWorker()
        self._host_pool = None  # lazy N-worker pool (cfg.workers > 1)
        self._pending_upload: Optional[List[np.ndarray]] = None
        # every coordinate selected since the last fold-in: their grads
        # never reach the host (zeroed at shipment for the current
        # selection, dropped from the accumulator at reselection for past
        # ones), so the device value is authoritative and must survive
        # fold-in even after reselections change self._idx
        self._protected: List[Optional[jnp.ndarray]] = [None] * len(self._ks)
        # device applied selective updates since the last fold-in: only
        # then can the masters be stale for a reselected-away coordinate
        self._updated_since_foldin = [False] * len(self._ks)
        log_dist(
            f"ZenFlow: {len(leaves)} tensors, topk={self.cfg.topk_ratio:.2%}"
            f", update_interval={self.cfg.update_interval}", ranks=[0])

    # -- jitted pieces (explicit jit: eager ops on multi-host global
    # arrays are not generally allowed, and every process runs these in
    # the same order — plain SPMD). Per-STEP device work batches the
    # whole leaf tree into ONE jit call: per-leaf dispatch loops issue
    # dozens of tiny programs per step, and in multi-process runs every
    # dispatch is a cross-process rendezvous — on a loaded host the gap
    # between two of them can exceed the transport's pair timeout (the
    # gloo "Application timeout caused pair closure" failure the 2-process
    # parity test kept hitting). One program per step also dispatches
    # ~15x less work host-side — the same reason the reference fuses its
    # selective-Adam loop (zenflow_torch_adam.py). -----------------------
    @staticmethod
    @jax.jit
    def _accumulate(acc, g):
        return acc + g.reshape(-1).astype(jnp.float32)

    @staticmethod
    @jax.jit
    def _device_step_batch(p_leaves, g_leaves, accs, idxs, ms, vs,
                           sel_steps, lr, b1, b2, eps):
        """One program for the whole tree: accumulate + selective Adam.

        Lists are pytrees of same-length leaves; shapes are static per
        position, so this traces once per engine."""
        new_accs, new_p, new_m, new_v = [], [], [], []
        for p, g, acc, idx, m, v, step in zip(
                p_leaves, g_leaves, accs, idxs, ms, vs, sel_steps):
            g32 = g.reshape(-1).astype(jnp.float32)
            acc = acc + g32
            sel_g = g32[idx]
            m = b1 * m + (1 - b1) * sel_g
            v = b2 * v + (1 - b2) * sel_g * sel_g
            mhat = m / (1 - b1 ** step)
            vhat = v / (1 - b2 ** step)
            upd = lr * mhat / (jnp.sqrt(vhat) + eps)
            new = p.reshape(-1).astype(jnp.float32).at[idx].add(-upd)
            new_p.append(new.reshape(p.shape).astype(p.dtype))
            new_accs.append(acc)
            new_m.append(m)
            new_v.append(v)
        return new_p, new_accs, new_m, new_v

    @staticmethod
    @jax.jit
    def _selective_adam(p, g, idx, m, v, step, lr, b1, b2, eps):
        """Adam on the selected coordinates only (ZenFlowSelectiveAdamW)."""
        sel_g = g.reshape(-1).astype(jnp.float32)[idx]
        m = b1 * m + (1 - b1) * sel_g
        v = b2 * v + (1 - b2) * sel_g * sel_g
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        upd = lr * mhat / (jnp.sqrt(vhat) + eps)
        new = p.reshape(-1).astype(jnp.float32).at[idx].add(-upd)
        return new.reshape(p.shape).astype(p.dtype), m, v

    @staticmethod
    @jax.jit
    def _zero_at(acc, idx):
        return acc.at[idx].set(0.0)

    @staticmethod
    @functools.partial(jax.jit, static_argnames=("shape",))
    def _ship_acc(acc, idx, shape):
        return acc.at[idx].set(0.0).reshape(shape)

    @staticmethod
    @jax.jit
    def _cat(a, b):
        return jnp.concatenate([a, b])

    @staticmethod
    @functools.partial(jax.jit, static_argnames=("k",))
    def _topk_idx(acc, k):
        _, idx = jax.lax.top_k(jnp.abs(acc), k)
        return idx.astype(jnp.int32)

    @staticmethod
    @jax.jit
    def _fold(master, p, keep):
        """Masters own non-selected coords; device values survive for
        ``keep`` (selected/protected since the last fold-in)."""
        flat = master.reshape(-1)
        dev = p.reshape(-1).astype(jnp.float32)
        return flat.at[keep].set(dev[keep]).reshape(master.shape)

    @staticmethod
    @jax.jit
    def _fold_batch(masters, p_leaves, keeps):
        """_fold over the whole tree in one program (one dispatch)."""
        out = []
        for master, p, keep in zip(masters, p_leaves, keeps):
            flat = master.reshape(-1)
            dev = p.reshape(-1).astype(jnp.float32)
            out.append(flat.at[keep].set(dev[keep]).reshape(master.shape))
        return out

    @staticmethod
    @functools.partial(jax.jit, static_argnames=("shapes",))
    def _ship_batch(accs, idxs, shapes):
        """Ship-prep over the whole tree: zero the selected coords,
        reshape to leaf shape, and return zeroed accumulators."""
        shipped = [acc.at[idx].set(0.0).reshape(shape)
                   for acc, idx, shape in zip(accs, idxs, shapes)]
        return shipped, [jnp.zeros_like(a) for a in accs]

    # -- selection -------------------------------------------------------
    def _reselect(self, i: int, initial: bool = False):
        """Re-pick the top-k coordinates of leaf i by |accumulated grad|
        (reference select_strategy='auto': gradient magnitude). The old
        selection's accumulated grads are dropped — the device already
        applied those updates — and the old coords join the protected set
        until the next fold-in syncs them into the masters."""
        k = self._ks[i]
        if not initial:
            old = self._idx[i]
            self._acc[i] = self._zero_at(self._acc[i], old)
            if self._updated_since_foldin[i]:
                # masters lack the device updates applied to ``old`` since
                # the last fold-in — protect them until the next fold-in
                # re-syncs. (If a fold-in just ran this step, masters
                # already equal the device values and protection would
                # wrongly revert the host's later updates.)
                self._protected[i] = (old if self._protected[i] is None
                                      else self._cat(self._protected[i],
                                                     old))
        self._idx[i] = self._topk_idx(self._acc[i], k)
        self._m[i] = jnp.zeros(k, jnp.float32)
        self._v[i] = jnp.zeros(k, jnp.float32)
        self._sel_step[i] = 0

    # -- host pass -------------------------------------------------------
    def _host_pass(self, host_grads: List[List[np.ndarray]], lr: float,
                   denom: float) -> List[List[np.ndarray]]:
        """One host optimizer pass over every (leaf, local shard). With
        workers > 1 the shards split across a thread pool (SuperOffload's
        N-worker host optimizer, superoffload_utils.py:165 — worker
        *threads* here: the native CPUAdam releases the GIL, so threads
        scale across cores without the reference's process plumbing).
        Each process steps only its local shards — multi-host splits the
        host work the way the reference splits it across ranks."""
        def one(pair):
            i, s = pair
            self._host_opts[i][s].step(self._masters[i][s],
                                       host_grads[i][s] / denom, lr=lr)
            return self._masters[i][s].copy()

        pairs = [(i, s) for i in range(len(host_grads))
                 for s in range(len(host_grads[i]))]
        if self.cfg.workers <= 1 or len(pairs) <= 1:
            flat = [one(p) for p in pairs]
        else:
            if self._host_pool is None:  # one pool for the whole run
                import concurrent.futures as _fut

                self._host_pool = _fut.ThreadPoolExecutor(
                    max_workers=self.cfg.workers,
                    thread_name_prefix="zenflow-host")
            flat = list(self._host_pool.map(one, pairs))
        out: List[List[np.ndarray]] = [[] for _ in host_grads]
        for (i, _), buf in zip(pairs, flat):
            out[i].append(buf)
        return out

    # -- main ------------------------------------------------------------
    def step(self, grads, params, lr: Optional[float] = None):
        lr = self.lr if lr is None else float(lr)
        self.steps += 1
        g_leaves = jax.tree.leaves(grads)
        p_leaves, treedef = jax.tree.flatten(params)
        cfg = self.cfg

        # fold a finished async host pass into the device params: masters
        # own the non-selected coords; device-selected coords stay ahead.
        # Fold-in only runs with the worker idle (a running pass reads the
        # master arrays), and a newer snapshot supersedes a deferred one —
        # masters mutate cumulatively, so the latest copy is complete.
        # The fold schedule is STEP-DETERMINISTIC and identical for every
        # process count: the fold runs jitted SPMD collectives, so in
        # multi-host every process must fold at the same step, and the
        # single-process run must follow the SAME rule or its loss stream
        # diverges from the N-process one at the first fold (the r4
        # multi-host branch folded at 2·interval while single-process
        # folded at interval+1 — exactly the parity break the xfail'd
        # 2-process test recorded).
        #   overlap_step=False: the host pass ran synchronously at the
        #   ship (end of step k·interval) — fold at the next step.
        #   overlap_step=True: give the async pass a full interval; fold
        #   at the next interval boundary with a blocking collect (the
        #   pass overlapped the interior steps; the block covers only
        #   the tail).
        done = None
        if cfg.overlap_step:
            if self.steps % cfg.update_interval == 0:
                done = self._worker.collect(block=True)
                if done is None:
                    done = self._pending_upload
        elif self.steps > 1 and (self.steps - 1) % cfg.update_interval == 0:
            done = self._pending_upload
        if done is not None:
            self._pending_upload = None  # fresh result supersedes deferred
            masters_g, keeps = [], []
            for i, shard_bufs in enumerate(done):
                masters_g.append(_rebuild_global(
                    self._shapes[i], self._shardings[i],
                    self._shard_meta[i], shard_bufs))
                # device values survive for every coordinate selected
                # since the last fold-in (masters never saw their grads)
                keep = self._idx[i]
                if self._protected[i] is not None:
                    keep = self._cat(keep, self._protected[i])
                keeps.append(keep)
            folded = self._fold_batch(masters_g, p_leaves, keeps)
            new_leaves = []
            for i, master_new in enumerate(folded):
                if self._shardings[i] is not None:
                    master_new = jax.device_put(master_new,
                                                self._shardings[i])
                # refresh per-shard masters (copies: host opt mutates)
                self._masters[i] = [
                    np.array(data, np.float32).reshape(-1)
                    for _, _, data in _unique_local_shards(master_new)]
                self._protected[i] = None
                self._updated_since_foldin[i] = False
                new_leaves.append(master_new.astype(self._dtypes[i]))
            p_leaves = new_leaves

        if (self.steps - 1) % cfg.select_interval == 0:
            # reselect step (rare): per-leaf path — accumulate, re-pick
            # top-k, then the selective update with the fresh selection
            new_p = []
            for i, (pl_, gl) in enumerate(zip(p_leaves, g_leaves)):
                self._acc[i] = self._accumulate(self._acc[i], gl)
                self._reselect(i, initial=self.steps == 1)
                self._sel_step[i] += 1
                new_pl, self._m[i], self._v[i] = self._selective_adam(
                    pl_, gl, self._idx[i], self._m[i],
                    self._v[i], jnp.asarray(self._sel_step[i], jnp.float32),
                    jnp.asarray(lr, jnp.float32), cfg.betas[0],
                    cfg.betas[1], cfg.eps)
                self._updated_since_foldin[i] = True
                new_p.append(new_pl)
        else:
            # steady step: the WHOLE tree in one device program (one
            # dispatch, one cross-process rendezvous)
            self._sel_step = [s + 1 for s in self._sel_step]
            sel_steps = [jnp.asarray(s, jnp.float32) for s in self._sel_step]
            new_p, self._acc, self._m, self._v = self._device_step_batch(
                p_leaves, g_leaves, self._acc, self._idx, self._m,
                self._v, sel_steps, jnp.asarray(lr, jnp.float32),
                cfg.betas[0], cfg.betas[1], cfg.eps)
            self._updated_since_foldin = [True] * len(new_p)

        if self.steps % cfg.update_interval == 0:
            # ship accumulated (averaged) grads to the host optimizer,
            # zeroing the selected coords (already applied on device);
            # each process extracts only its local shards
            shipped, self._acc = self._ship_batch(
                self._acc, self._idx, tuple(self._shapes))
            host_grads = []
            for i, acc in enumerate(shipped):
                if self._shardings[i] is not None:
                    acc = jax.device_put(acc, self._shardings[i])
                host_grads.append([
                    np.asarray(data, np.float32).reshape(-1)
                    for _, _, data in _unique_local_shards(acc)])
            if self._worker.busy:  # previous pass still running: wait
                self._pending_upload = self._worker.collect(block=True)
            if cfg.overlap_step:
                self._worker.submit(self._host_pass, host_grads, lr,
                                    float(cfg.update_interval))
            else:
                self._pending_upload = self._host_pass(
                    host_grads, lr, float(cfg.update_interval))

        return jax.tree.unflatten(treedef, new_p)

    def finalize(self):
        """Block on any in-flight host pass and fold it in (end of
        training / before checkpoint)."""
        done = self._worker.collect(block=True)
        if done is not None:
            self._pending_upload = done
        return self._pending_upload is not None

    def close(self):
        """Shut the worker pool down (idempotent; gc-safe)."""
        if self._host_pool is not None:
            self._host_pool.shutdown(wait=True)
            self._host_pool = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:
            pass

    def state_dict(self) -> Dict[str, Any]:
        # never snapshot mid-host-pass: the worker mutates masters and
        # CPUAdam moments in place (a torn copy would restore garbage)
        self.finalize()
        def copy_opt(sd):
            return {k: (v.copy() if isinstance(v, np.ndarray) else v)
                    for k, v in sd.items()}

        return {
            "steps": self.steps,
            # per-(leaf, local shard) with the shard's slice recorded as
            # (start, stop) pairs (slice objects don't serialize), so a
            # restore under a different shard layout can reslice
            "masters": [[m.copy() for m in ms] for ms in self._masters],
            "shard_index": [
                [tuple((sl.start or 0,
                        sl.stop if sl.stop is not None else dim)
                       for sl, dim in zip(idx, self._shapes[i]))
                 for idx, _ in self._shard_meta[i]]
                for i in range(len(self._shard_meta))],
            # deep-copy moments: CPUAdam.state_dict returns live buffers
            # the next step mutates in place (a torn async serialization
            # would pair step-N masters with step-N+k moments)
            "host_opt": [[copy_opt(o.state_dict()) for o in os_]
                         for os_ in self._host_opts],
            "idx": [np.asarray(i) for i in self._idx],
            "m": [np.asarray(m) for m in self._m],
            "v": [np.asarray(v) for v in self._v],
            "acc": [np.asarray(a) for a in self._acc],
            "sel_step": list(self._sel_step),
            "protected": [None if p is None else np.asarray(p)
                          for p in self._protected],
            "updated_since_foldin": list(self._updated_since_foldin),
        }

    def load_state_dict(self, sd: Dict[str, Any]):
        self.steps = int(sd["steps"])
        if sd["masters"] and isinstance(sd["masters"][0], np.ndarray):
            # legacy (single-process) checkpoint: one flat master per
            # leaf — reslice to this run's local shards
            for i, flat in enumerate(sd["masters"]):
                full = np.asarray(flat, np.float32).reshape(self._shapes[i])
                self._masters[i] = [full[idx].reshape(-1).copy()
                                    for idx, _ in self._shard_meta[i]]
            for i, os_ in enumerate(sd["host_opt"]):
                full_m = np.asarray(os_["exp_avg"]).reshape(self._shapes[i])
                full_v = np.asarray(
                    os_["exp_avg_sq"]).reshape(self._shapes[i])
                for s, (idx, _) in enumerate(self._shard_meta[i]):
                    shard_sd = dict(os_)
                    shard_sd["exp_avg"] = full_m[idx].reshape(-1).copy()
                    shard_sd["exp_avg_sq"] = full_v[idx].reshape(-1).copy()
                    self._host_opts[i][s].load_state_dict(shard_sd)
        else:
            for i, (ms, os_) in enumerate(zip(sd["masters"],
                                              sd["host_opt"])):
                cur_idx = [
                    tuple((sl.start or 0,
                           sl.stop if sl.stop is not None else dim)
                          for sl, dim in zip(idx, self._shapes[i]))
                    for idx, _ in self._shard_meta[i]]
                saved_all = sd.get("shard_index")
                saved_idx = (cur_idx if saved_all is None else
                             [tuple(tuple(int(x) for x in p) for p in e)
                              for e in saved_all[i]])
                cur_idx = [tuple(tuple(int(x) for x in p) for p in e)
                           for e in cur_idx]
                if saved_idx == cur_idx:
                    self._masters[i] = [np.array(m, np.float32) for m in ms]
                    for s, shard_sd in enumerate(os_):
                        self._host_opts[i][s].load_state_dict(shard_sd)
                    continue
                # layout changed (different process count / sharding):
                # reassemble the full leaf from the saved shards, reslice.
                # Requires the saved shards to cover the leaf — a
                # per-process partial checkpoint can't restore here.
                full_m = np.zeros(self._shapes[i], np.float32)
                full_ea = np.zeros(self._shapes[i], np.float32)
                full_es = np.zeros(self._shapes[i], np.float32)
                covered = np.zeros(self._shapes[i], bool)
                step_count = 0
                for e, buf, shard_sd in zip(saved_idx, ms, os_):
                    sl = tuple(slice(a, b) for a, b in e)
                    shp = tuple(b - a for a, b in e)
                    full_m[sl] = np.asarray(buf).reshape(shp)
                    full_ea[sl] = np.asarray(
                        shard_sd["exp_avg"]).reshape(shp)
                    full_es[sl] = np.asarray(
                        shard_sd["exp_avg_sq"]).reshape(shp)
                    covered[sl] = True
                    step_count = int(shard_sd["step"])
                if not covered.all():
                    raise ValueError(
                        "zenflow restore: saved shards do not cover leaf "
                        f"{i} — a per-process partial checkpoint cannot "
                        "restore under a different shard layout; save a "
                        "full checkpoint (all processes) or restore with "
                        "the original topology")
                self._masters[i] = []
                for s, (idx, _) in enumerate(self._shard_meta[i]):
                    piece = full_m[idx].reshape(-1).copy()
                    self._masters[i].append(piece)
                    self._host_opts[i][s].load_state_dict({
                        "exp_avg": full_ea[idx].reshape(-1).copy(),
                        "exp_avg_sq": full_es[idx].reshape(-1).copy(),
                        "step": step_count})
        self._idx = [jnp.asarray(i) for i in sd["idx"]]
        self._m = [jnp.asarray(m) for m in sd["m"]]
        self._v = [jnp.asarray(v) for v in sd["v"]]
        self._acc = [jnp.asarray(a) for a in sd["acc"]]
        self._sel_step = [int(s) for s in sd["sel_step"]]
        self._protected = [None if p is None else jnp.asarray(p)
                           for p in sd.get("protected",
                                           [None] * len(self._acc))]
        # missing in old checkpoints: assume True (protect) — a spurious
        # protection is harmless, a missed one reverts device updates
        self._updated_since_foldin = [bool(b) for b in sd.get(
            "updated_since_foldin", [True] * len(self._acc))]
