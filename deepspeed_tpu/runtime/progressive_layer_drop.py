"""Progressive layer drop (PLD).

Reference: ``deepspeed/runtime/progressive_layer_drop.py`` (40 LoC) —
anneal a keep probability theta(t) = (1 - theta_0)·exp(-gamma·t) ... the
published schedule keeps theta(t) = theta_0 + (1 - theta_0)·exp(-gamma·t)
falling toward theta_0, and each transformer layer is executed with
probability p_l = theta(t) scaled by depth. Speeds pretraining ~24%
(PLD paper).

TPU note: data-dependent layer skipping breaks the scanned layer stack,
so the functional form here returns per-layer *gate* values the model
multiplies into each layer's residual branch — with a Bernoulli draw
under ``lax.select`` the compiled program is shape-stable (FLOPs are
spent but the statistical effect of PLD — stochastic depth — is exact).
"""

from __future__ import annotations

import math
from typing import Dict

import numpy as np


class ProgressiveLayerDrop:
    """theta schedule + per-layer keep gates (reference API: .update_state
    (global_step), .get_state(), .get_theta())."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta_0 = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = (1.0 - self.theta_0) * math.exp(
            -self.gamma * global_step) + self.theta_0
        return self.current_theta

    def get_state(self) -> Dict[str, float]:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def layer_keep_probs(self, num_layers: int) -> np.ndarray:
        """Per-layer keep probability: deeper layers drop more
        (stochastic-depth linear scaling i/L, as the PLD paper does)."""
        i = np.arange(1, num_layers + 1)
        return 1.0 - (i / num_layers) * (1.0 - self.current_theta)

    def layer_gates(self, rng, num_layers: int):
        """Bernoulli keep gates [L] (float 0/1 ÷ keep-prob for unbiased
        expectation) — multiply into each layer's residual branch."""
        import jax

        probs = self.layer_keep_probs(num_layers)
        import jax.numpy as jnp

        keep = jax.random.bernoulli(rng, jnp.asarray(probs))
        return jnp.where(keep, 1.0 / jnp.asarray(probs), 0.0)
