"""ZeRO-Infinity parameter streaming — the model-agnostic protocol.

The reference's ``offload_param`` works on any module tree: the param
swapper intercepts each submodule's parameters on use
(deepspeed/runtime/zero/partitioned_param_swapper.py,
partition_parameters.py:1188 fetch on pre-forward). The XLA analog
cannot hook arbitrary Python modules — the compiled program must contain
the host→device copies — so the contract is a *protocol* instead:

  * the engine pins a model's declared stacked-parameter subtrees to
    pinned host memory (``Engine._setup_param_host_offload``), and
  * the model's ``apply`` runs those stacks through
    :func:`scan_streamed` (or fetches slices with :func:`fetch_slice`),
    so one layer's params occupy HBM at a time and the remat replay
    re-fetches them for the backward (the cotangent of the fetch is a
    device→host copy, landing gradients host-side).

A model opts in one of two ways:

  1. TransformerLM family: ``config.param_host_offload`` (the engine
     flips it on and the model's own scan streams — models/
     transformer.py:505).
  2. Any other model: expose ``host_param_paths`` — an iterable of
     top-level parameter-tree keys whose leaves are ``[L, ...]`` stacks.
     The engine pins those subtrees and sets
     ``model.param_host_offload = True``; the model consults that flag
     in ``apply`` and wraps its layer scan in :func:`scan_streamed`.

See tests/test_offload.py::test_offload_param_protocol_custom_model for
a complete non-TransformerLM example.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils import memspace


def fetch_slice(stacked_tree: Any, i) -> Any:
    """Fetch layer ``i`` of a host-pinned ``[L, ...]`` stacked tree to
    device memory. Usable inside jit/scan bodies; under remat the
    backward replay re-issues the copy instead of saving the layer."""
    return jax.tree.map(
        lambda a: memspace.put(
            lax.dynamic_index_in_dim(a, i, keepdims=False), "device"),
        stacked_tree)


def pin_stage(anchor: Any, pinned: Any):
    """Explicit sequencing for the overlap engine: tie the in-flight
    transfer values ``pinned`` (h2d layer fetches, d2h grad streams,
    fsdp gathers) and the stage's compute ``anchor`` into one scheduling
    stage via ``lax.optimization_barrier``.

    Identity on every value — the barrier only forbids the scheduler
    from sinking a transfer issued in stage ``i`` toward the stage that
    consumes it (where it would land on the critical path) or hoisting
    later compute above it. ``tools/latency_hiding_probe.py`` measured
    that XLA's own latency-hiding pass does NOT keep these copies off
    the critical path in the default scan schedule on v5e-1; pinning
    the issue order into the program is the control that works on every
    backend. No differentiation rule exists for the barrier on jax
    0.4.x, so callers must keep it inside custom-VJP fwd/bwd bodies
    (streamed_layers_prefetch does), never in a differentiated trace.
    """
    return lax.optimization_barrier((anchor, pinned))


def scan_streamed(body: Callable[[Any, Any], Any], carry: Any,
                  stacked_tree: Any, *, length: Optional[int] = None,
                  remat: bool = True,
                  remat_policy: Optional[str] = None) -> Any:
    """``lax.scan`` over a host-pinned layer stack, fetching one slice
    per step inside the (optionally rematerialized) body.

    body(carry, layer_params) -> carry. Returns the final carry.
    ``remat=True`` is required for the memory win: without it every
    fetched layer would be saved as a backward residual and the full
    stack would materialize in HBM anyway.
    """
    if length is None:
        length = jax.tree.leaves(stacked_tree)[0].shape[0]

    def fetched(carry, i):
        return body(carry, fetch_slice(stacked_tree, i))

    if remat:
        from deepspeed_tpu.runtime.activation_checkpointing import \
            checkpoint_wrapper

        fetched = checkpoint_wrapper(fetched, policy=remat_policy)

    def scan_body(carry, i):
        return fetched(carry, i), None

    carry, _ = lax.scan(scan_body, carry, jnp.arange(length))
    return carry


def streamed_layers_prefetch(layer_fn: Callable[..., Any],
                             stacked_tree: Any, x: Any,
                             length: Optional[int] = None,
                             extra: tuple = (),
                             prefetch_depth: int = 1,
                             grads_to_host: bool = True,
                             overlap_depth: int = 0,
                             fetch: Optional[Callable[[Any, Any], Any]]
                             = None,
                             grad_sink: Optional[Callable[[Any], Any]]
                             = None) -> Any:
    """Double-buffered ZeRO-Infinity layer streaming with EXPLICIT
    prefetch — the DeepCompile-prefetch analog (reference
    deepspeed/compile/passes/prefetch.py and the round-3/4 claim that
    XLA's scheduler would hide the fetches, which measurement refuted:
    on v5e-1 the default scan's host→device layer fetches overlap
    compute not at all — tools/latency_hiding_probe.py measured the
    barrier-serialized program *faster* than XLA's default schedule,
    while compute-only is ~1.7x faster than either).

    Structure: the forward scan carries (x, params_of_layer_i); each
    step issues the fetch of layer i+1 FIRST (data-independent of this
    layer's compute, so the DMA overlaps the layer's matmuls) and saves
    only the layer-input activations. The custom VJP runs the mirrored
    reverse pipeline — fetch layer i-1 while recomputing+differentiating
    layer i — and lands each layer's parameter cotangent in host memory
    (`lax.scan(reverse=True)` stacks them in forward layout). Per-layer
    recompute == the nothing_saveable remat policy; HBM holds at most
    two fp32 layers (current + inflight) plus one layer's transient
    grads.

    layer_fn(x, layer_params, *extra) -> x, differentiable in (x,
    layer_params); ``extra`` carries traced non-differentiable values
    the layer needs (e.g. rope positions) — they must be threaded
    explicitly because a custom-vjp backward cannot close over tracers
    from the primal trace. Requires a host-resident ``[L, ...]``
    stacked tree (pin_to_host).

    ``prefetch_depth`` layers ride in flight ahead of the compute (depth
    2 absorbs fetch-time jitter a single buffer exposes; HBM cost is one
    extra fp32 layer). ``grads_to_host=True`` streams each layer's
    parameter cotangent to pinned host memory INSIDE the backward scan —
    the d2h copy of layer i's grads overlaps layer i-1's recompute, and
    the [L, ...] fp32 gradient stack never materializes in HBM (it lands
    where the offload tier's host optimizer reads it anyway). Reference
    analog: the overlapped grad offload of zenflow/superoffload
    (zenflow_stage_1_and_2.py) and DeepCompile's offload_adam_states
    passes.

    ``overlap_depth`` arms the per-layer overlap engine: the K newest
    in-flight transfers — the h2d fetches riding ahead of the forward,
    plus the h2d fetch AND the per-layer grad stream in the backward —
    are pinned into the issuing layer's scheduling stage with
    :func:`pin_stage` (an optimization barrier on the scan carry), so
    the transfer provably issues while that layer computes instead of
    drifting to wherever XLA's scheduler parks it (measured: on v5e-1
    the default schedule hides none of it — the probe's
    barrier-serialized control ran *faster* than XLA's own order).
    0 (default) emits today's program bit-for-bit, barrier-free; any K
    is identity on values — only the schedule changes.

    ``fetch`` overrides the per-layer fetch (default
    :func:`fetch_slice`, the ZeRO-Infinity h2d copy); the stage-3 path
    passes ``runtime/sharding.py::fsdp_gather_slice`` so the same
    engine staged-carries per-layer fsdp all-gathers. ``grad_sink``
    overrides the per-layer cotangent landing (default: pinned-host put
    when ``grads_to_host``); the stage-3 path passes
    ``fsdp_scatter_grads`` so each layer's grad reduce-scatter issues
    inside the backward scan, overlapping the previous layer's
    recompute.
    """
    import numpy as np

    if length is None:
        length = jax.tree.leaves(stacked_tree)[0].shape[0]
    L = length
    D = max(1, min(int(prefetch_depth), L))
    K = max(0, min(int(overlap_depth), D))
    fetch = fetch_slice if fetch is None else fetch

    if grad_sink is None and grads_to_host:
        def grad_sink(dp):
            # per-layer d2h INSIDE the scan: overlaps the next layer's
            # recompute, and the stacked cotangent lives in host memory
            # (matching the host-pinned primal stack)
            return jax.tree.map(
                lambda a: memspace.put(a, "pinned_host"), dp)

    @jax.custom_vjp
    def run(stack, x, extra):
        y, _ = _fwd(stack, x, extra)
        return y

    def _fwd(stack, x, extra):
        bufs = tuple(fetch(stack, i) for i in range(D))

        def body(carry, i):
            x, bufs = carry
            # prefetch BEFORE compute: the copy has no data dependence
            # on this layer's output, so it can ride the DMA engine
            # while the MXU runs layer i
            nxt = fetch(stack, jnp.minimum(i + D, L - 1))
            y = layer_fn(x, bufs[0], *extra)
            bufs = bufs[1:] + (nxt,)
            if K:
                # overlap engine: pin the K newest in-flight fetches
                # into THIS stage — issued alongside layer i's compute,
                # not sunk toward the layer that consumes them
                y, pinned = pin_stage(y, bufs[D - K:])
                bufs = bufs[:D - K] + tuple(pinned)
            return (y, bufs), x  # save the layer INPUT

        (y, _), xs = lax.scan(body, (x, bufs), jnp.arange(L))
        return y, xs

    def run_fwd(stack, x, extra):
        y, xs = _fwd(stack, x, extra)
        return y, (stack, xs, extra)

    def run_bwd(res, g):
        stack, xs, extra = res
        bufs = tuple(fetch(stack, max(L - 1 - i, 0))
                     for i in range(D))

        def body(carry, i):
            gy, bufs = carry  # bufs[0] = params of layer i
            prv = fetch(stack, jnp.maximum(i - D, 0))
            _, vjp_fn = jax.vjp(
                lambda xx, pp: layer_fn(xx, pp, *extra), xs[i], bufs[0])
            dx, dp = vjp_fn(gy)
            if grad_sink is not None:
                dp = grad_sink(dp)
            bufs = bufs[1:] + (prv,)
            if K:
                # pin layer i's grad stream (d2h / reduce-scatter) and
                # the K newest in-flight fetches into this stage: both
                # overlap this layer's recompute instead of queueing at
                # the scan epilogue behind L layers of compute
                dx, (pinned, dp) = pin_stage(dx, (bufs[D - K:], dp))
                bufs = bufs[:D - K] + tuple(pinned)
            return (dx, bufs), dp

        # reverse=True: iterate L-1..0, outputs stacked in FORWARD
        # layout — the cotangent tree matches the stack with no flip
        (gx, _), dstack = lax.scan(body, (g, bufs), jnp.arange(L),
                                   reverse=True)
        dextra = jax.tree.map(
            lambda a: np.zeros(np.shape(a), jax.dtypes.float0), extra)
        return dstack, gx, dextra

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_tree, x, tuple(extra))


def pin_to_host(tree: Any) -> Any:
    """Place a parameter subtree in pinned host memory, staged fp32
    (sub-32-bit host→device streaming is unsupported on current TPU
    runtimes; fp32 is the master precision anyway)."""
    def pin(a):
        if memspace.is_on_host(a) and a.dtype == jnp.float32:
            return a  # already staged (init pins the fp32 masters)
        return jax.device_put(
            a.astype(jnp.float32),
            memspace.with_memory_kind(a.sharding, "pinned_host"))

    return jax.tree.map(pin, tree)
