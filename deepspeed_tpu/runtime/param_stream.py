"""ZeRO-Infinity parameter streaming — the model-agnostic protocol.

The reference's ``offload_param`` works on any module tree: the param
swapper intercepts each submodule's parameters on use
(deepspeed/runtime/zero/partitioned_param_swapper.py,
partition_parameters.py:1188 fetch on pre-forward). The XLA analog
cannot hook arbitrary Python modules — the compiled program must contain
the host→device copies — so the contract is a *protocol* instead:

  * the engine pins a model's declared stacked-parameter subtrees to
    pinned host memory (``Engine._setup_param_host_offload``), and
  * the model's ``apply`` runs those stacks through
    :func:`scan_streamed` (or fetches slices with :func:`fetch_slice`),
    so one layer's params occupy HBM at a time and the remat replay
    re-fetches them for the backward (the cotangent of the fetch is a
    device→host copy, landing gradients host-side).

A model opts in one of two ways:

  1. TransformerLM family: ``config.param_host_offload`` (the engine
     flips it on and the model's own scan streams — models/
     transformer.py:505).
  2. Any other model: expose ``host_param_paths`` — an iterable of
     top-level parameter-tree keys whose leaves are ``[L, ...]`` stacks.
     The engine pins those subtrees and sets
     ``model.param_host_offload = True``; the model consults that flag
     in ``apply`` and wraps its layer scan in :func:`scan_streamed`.

See tests/test_offload.py::test_offload_param_protocol_custom_model for
a complete non-TransformerLM example.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils import memspace


def fetch_slice(stacked_tree: Any, i) -> Any:
    """Fetch layer ``i`` of a host-pinned ``[L, ...]`` stacked tree to
    device memory. Usable inside jit/scan bodies; under remat the
    backward replay re-issues the copy instead of saving the layer."""
    return jax.tree.map(
        lambda a: memspace.put(
            lax.dynamic_index_in_dim(a, i, keepdims=False), "device"),
        stacked_tree)


def scan_streamed(body: Callable[[Any, Any], Any], carry: Any,
                  stacked_tree: Any, *, length: Optional[int] = None,
                  remat: bool = True,
                  remat_policy: Optional[str] = None) -> Any:
    """``lax.scan`` over a host-pinned layer stack, fetching one slice
    per step inside the (optionally rematerialized) body.

    body(carry, layer_params) -> carry. Returns the final carry.
    ``remat=True`` is required for the memory win: without it every
    fetched layer would be saved as a backward residual and the full
    stack would materialize in HBM anyway.
    """
    if length is None:
        length = jax.tree.leaves(stacked_tree)[0].shape[0]

    def fetched(carry, i):
        return body(carry, fetch_slice(stacked_tree, i))

    if remat:
        from deepspeed_tpu.runtime.activation_checkpointing import \
            checkpoint_wrapper

        fetched = checkpoint_wrapper(fetched, policy=remat_policy)

    def scan_body(carry, i):
        return fetched(carry, i), None

    carry, _ = lax.scan(scan_body, carry, jnp.arange(length))
    return carry


def streamed_layers_prefetch(layer_fn: Callable[..., Any],
                             stacked_tree: Any, x: Any,
                             length: Optional[int] = None,
                             extra: tuple = (),
                             prefetch_depth: int = 1,
                             grads_to_host: bool = True) -> Any:
    """Double-buffered ZeRO-Infinity layer streaming with EXPLICIT
    prefetch — the DeepCompile-prefetch analog (reference
    deepspeed/compile/passes/prefetch.py and the round-3/4 claim that
    XLA's scheduler would hide the fetches, which measurement refuted:
    on v5e-1 the default scan's host→device layer fetches overlap
    compute not at all — tools/latency_hiding_probe.py measured the
    barrier-serialized program *faster* than XLA's default schedule,
    while compute-only is ~1.7x faster than either).

    Structure: the forward scan carries (x, params_of_layer_i); each
    step issues the fetch of layer i+1 FIRST (data-independent of this
    layer's compute, so the DMA overlaps the layer's matmuls) and saves
    only the layer-input activations. The custom VJP runs the mirrored
    reverse pipeline — fetch layer i-1 while recomputing+differentiating
    layer i — and lands each layer's parameter cotangent in host memory
    (`lax.scan(reverse=True)` stacks them in forward layout). Per-layer
    recompute == the nothing_saveable remat policy; HBM holds at most
    two fp32 layers (current + inflight) plus one layer's transient
    grads.

    layer_fn(x, layer_params, *extra) -> x, differentiable in (x,
    layer_params); ``extra`` carries traced non-differentiable values
    the layer needs (e.g. rope positions) — they must be threaded
    explicitly because a custom-vjp backward cannot close over tracers
    from the primal trace. Requires a host-resident ``[L, ...]``
    stacked tree (pin_to_host).

    ``prefetch_depth`` layers ride in flight ahead of the compute (depth
    2 absorbs fetch-time jitter a single buffer exposes; HBM cost is one
    extra fp32 layer). ``grads_to_host=True`` streams each layer's
    parameter cotangent to pinned host memory INSIDE the backward scan —
    the d2h copy of layer i's grads overlaps layer i-1's recompute, and
    the [L, ...] fp32 gradient stack never materializes in HBM (it lands
    where the offload tier's host optimizer reads it anyway). Reference
    analog: the overlapped grad offload of zenflow/superoffload
    (zenflow_stage_1_and_2.py) and DeepCompile's offload_adam_states
    passes.
    """
    import numpy as np

    if length is None:
        length = jax.tree.leaves(stacked_tree)[0].shape[0]
    L = length
    D = max(1, min(int(prefetch_depth), L))

    @jax.custom_vjp
    def run(stack, x, extra):
        y, _ = _fwd(stack, x, extra)
        return y

    def _fwd(stack, x, extra):
        bufs = tuple(fetch_slice(stack, i) for i in range(D))

        def body(carry, i):
            x, bufs = carry
            # prefetch BEFORE compute: the copy has no data dependence
            # on this layer's output, so it can ride the DMA engine
            # while the MXU runs layer i
            nxt = fetch_slice(stack, jnp.minimum(i + D, L - 1))
            y = layer_fn(x, bufs[0], *extra)
            return (y, bufs[1:] + (nxt,)), x  # save the layer INPUT

        (y, _), xs = lax.scan(body, (x, bufs), jnp.arange(L))
        return y, xs

    def run_fwd(stack, x, extra):
        y, xs = _fwd(stack, x, extra)
        return y, (stack, xs, extra)

    def run_bwd(res, g):
        stack, xs, extra = res
        bufs = tuple(fetch_slice(stack, max(L - 1 - i, 0))
                     for i in range(D))

        def body(carry, i):
            gy, bufs = carry  # bufs[0] = params of layer i
            prv = fetch_slice(stack, jnp.maximum(i - D, 0))
            _, vjp_fn = jax.vjp(
                lambda xx, pp: layer_fn(xx, pp, *extra), xs[i], bufs[0])
            dx, dp = vjp_fn(gy)
            if grads_to_host:
                # per-layer d2h INSIDE the scan: overlaps the next
                # layer's recompute, and the stacked cotangent lives in
                # host memory (matching the host-pinned primal stack)
                dp = jax.tree.map(
                    lambda a: memspace.put(a, "pinned_host"), dp)
            return (dx, bufs[1:] + (prv,)), dp

        # reverse=True: iterate L-1..0, outputs stacked in FORWARD
        # layout — the cotangent tree matches the stack with no flip
        (gx, _), dstack = lax.scan(body, (g, bufs), jnp.arange(L),
                                   reverse=True)
        dextra = jax.tree.map(
            lambda a: np.zeros(np.shape(a), jax.dtypes.float0), extra)
        return dstack, gx, dextra

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_tree, x, tuple(extra))


def pin_to_host(tree: Any) -> Any:
    """Place a parameter subtree in pinned host memory, staged fp32
    (sub-32-bit host→device streaming is unsupported on current TPU
    runtimes; fp32 is the master precision anyway)."""
    def pin(a):
        if memspace.is_on_host(a) and a.dtype == jnp.float32:
            return a  # already staged (init pins the fp32 masters)
        return jax.device_put(
            a.astype(jnp.float32),
            memspace.with_memory_kind(a.sharding, "pinned_host"))

    return jax.tree.map(pin, tree)
