"""ZeRO-Infinity parameter streaming — the model-agnostic protocol.

The reference's ``offload_param`` works on any module tree: the param
swapper intercepts each submodule's parameters on use
(deepspeed/runtime/zero/partitioned_param_swapper.py,
partition_parameters.py:1188 fetch on pre-forward). The XLA analog
cannot hook arbitrary Python modules — the compiled program must contain
the host→device copies — so the contract is a *protocol* instead:

  * the engine pins a model's declared stacked-parameter subtrees to
    pinned host memory (``Engine._setup_param_host_offload``), and
  * the model's ``apply`` runs those stacks through
    :func:`scan_streamed` (or fetches slices with :func:`fetch_slice`),
    so one layer's params occupy HBM at a time and the remat replay
    re-fetches them for the backward (the cotangent of the fetch is a
    device→host copy, landing gradients host-side).

A model opts in one of two ways:

  1. TransformerLM family: ``config.param_host_offload`` (the engine
     flips it on and the model's own scan streams — models/
     transformer.py:505).
  2. Any other model: expose ``host_param_paths`` — an iterable of
     top-level parameter-tree keys whose leaves are ``[L, ...]`` stacks.
     The engine pins those subtrees and sets
     ``model.param_host_offload = True``; the model consults that flag
     in ``apply`` and wraps its layer scan in :func:`scan_streamed`.

See tests/test_offload.py::test_offload_param_protocol_custom_model for
a complete non-TransformerLM example.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def fetch_slice(stacked_tree: Any, i) -> Any:
    """Fetch layer ``i`` of a host-pinned ``[L, ...]`` stacked tree to
    device memory. Usable inside jit/scan bodies; under remat the
    backward replay re-issues the copy instead of saving the layer."""
    return jax.tree.map(
        lambda a: jax.device_put(
            lax.dynamic_index_in_dim(a, i, keepdims=False),
            jax.memory.Space.Device),
        stacked_tree)


def scan_streamed(body: Callable[[Any, Any], Any], carry: Any,
                  stacked_tree: Any, *, length: Optional[int] = None,
                  remat: bool = True,
                  remat_policy: Optional[str] = None) -> Any:
    """``lax.scan`` over a host-pinned layer stack, fetching one slice
    per step inside the (optionally rematerialized) body.

    body(carry, layer_params) -> carry. Returns the final carry.
    ``remat=True`` is required for the memory win: without it every
    fetched layer would be saved as a backward residual and the full
    stack would materialize in HBM anyway.
    """
    if length is None:
        length = jax.tree.leaves(stacked_tree)[0].shape[0]

    def fetched(carry, i):
        return body(carry, fetch_slice(stacked_tree, i))

    if remat:
        from deepspeed_tpu.runtime.activation_checkpointing import \
            checkpoint_wrapper

        fetched = checkpoint_wrapper(fetched, policy=remat_policy)

    def scan_body(carry, i):
        return fetched(carry, i), None

    carry, _ = lax.scan(scan_body, carry, jnp.arange(length))
    return carry


def pin_to_host(tree: Any) -> Any:
    """Place a parameter subtree in pinned host memory, staged fp32
    (sub-32-bit host→device streaming is unsupported on current TPU
    runtimes; fp32 is the master precision anyway)."""
    def pin(a):
        if getattr(a.sharding, "memory_kind", None) == "pinned_host" \
                and a.dtype == jnp.float32:
            return a  # already staged (init pins the fp32 masters)
        return jax.device_put(
            a.astype(jnp.float32),
            a.sharding.with_memory_kind("pinned_host"))

    return jax.tree.map(pin, tree)
