"""Sparse gradients for embedding tables.

Reference: ``deepspeed/runtime/sparse_tensor.py`` (``SparseTensor``, 69
LoC) + ``engine.sparse_allreduce`` (engine.py:3634) — embedding-layer
gradients touch only the rows of tokens seen in the batch, so the DP
all-reduce ships (indices, values) instead of the dense [vocab, hidden]
matrix.

TPU note: XLA collectives are dense, and a data-dependent nonzero-row
count would break static shapes — so the exchange uses the *batch's
token count* as the static row bound: each rank contributes its
(unique-bounded) rows, all ranks all-gather the compact (indices,
values) pair, and scatter-add rebuilds the dense gradient. Comm volume
drops from O(vocab·h) to O(batch_tokens·h·dp) — the reference's win —
while every shape stays static.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


class SparseTensor:
    """(indices [K], values [K, H], dense_shape) — reference SparseTensor."""

    def __init__(self, indices, values, dense_shape: Tuple[int, int]):
        self.indices = indices
        self.values = values
        self.dense_shape = tuple(dense_shape)

    @classmethod
    def from_dense_rows(cls, grad, token_ids):
        """Compact an embedding gradient to the rows named by token_ids
        (static K = token count). The dense grad already holds each row's
        full contribution, so duplicates take the row once: repeat slots
        are routed to the padding row with zero values."""
        vocab, h = grad.shape
        flat = token_ids.reshape(-1)
        # sort so duplicates are adjacent; keep the first occurrence only
        s = jnp.sort(flat)
        first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
        rows = grad[s] * first[:, None].astype(grad.dtype)
        idx = jnp.where(first, s, vocab)  # dup slots → padding row
        return cls(idx, rows, (vocab, h))

    def to_dense(self):
        vocab, h = self.dense_shape
        dense = jnp.zeros((vocab + 1, h), self.values.dtype)  # +1 pad row
        dense = dense.at[self.indices].add(self.values)
        return dense[:vocab]


def sparse_allreduce(grad, token_ids, axis: str = "dp"):
    """DP all-reduce of an embedding gradient by exchanging compact rows
    (reference engine.sparse_allreduce engine.py:3634). Runs inside
    shard_map with ``token_ids`` the *local* batch's tokens; returns the
    dense summed gradient.
    """
    st = SparseTensor.from_dense_rows(grad, token_ids)
    all_idx = jax.lax.all_gather(st.indices, axis, tiled=True)
    all_val = jax.lax.all_gather(st.values, axis, tiled=True)
    return SparseTensor(all_idx, all_val, st.dense_shape).to_dense()
