"""Dynamic loss scaling for fp16 (reference: runtime/fp16/loss_scaler.py:187
``DynamicLossScaler``). bf16 training doesn't need this; it exists for
fp16 parity and engages only when ``fp16.enabled`` is set.

Implemented as a pure state transition so it lives inside the compiled
train step: scale the loss up, unscale grads, detect inf/nan, and on
overflow skip the update and halve the scale; after ``scale_window``
clean steps, double it.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jax.Array  # f32 scalar
    good_steps: jax.Array  # i32 scalar


def init_loss_scale(config) -> LossScaleState:
    """From the fp16 config block (reference fp16/loss_scaler.py:238)."""
    if config.loss_scale and config.loss_scale > 0:
        scale = float(config.loss_scale)  # static scale
    else:
        scale = float(2.0 ** config.initial_scale_power)
    return LossScaleState(
        scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
    )


def has_overflow(grads) -> jax.Array:
    """Global inf/nan scan (reference _has_inf_or_nan stage3.py:2704)."""
    leaves = jax.tree.leaves(grads)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def update_loss_scale(state: LossScaleState, overflow: jax.Array, config
                      ) -> LossScaleState:
    if config.loss_scale and config.loss_scale > 0:
        return state  # static scaling never adjusts
    window = config.loss_scale_window
    min_scale = config.min_loss_scale
    shrunk = jnp.maximum(state.scale / 2.0, min_scale)
    grown = jnp.where(state.good_steps + 1 >= window, state.scale * 2.0,
                      state.scale)
    new_scale = jnp.where(overflow, shrunk, grown)
    new_good = jnp.where(
        overflow, 0, jnp.where(state.good_steps + 1 >= window, 0,
                               state.good_steps + 1))
    return LossScaleState(new_scale, new_good.astype(jnp.int32))
