"""Asynchronous input prefetch for the training loop.

The blocking loop pays host time on the device critical path every step:
microbatches are pulled from the data iterator, ``np.stack``-ed, and
``device_put`` synchronously between two compiled steps, so the TPU
idles while the host assembles inputs. :class:`PrefetchingIterator`
moves that work to a background thread: the worker pulls items from a
producer, runs the collate/stack + host->device transfer off the
consumer thread, and parks up to ``depth`` finished items in a bounded
queue. H2D copies then overlap the previous step's compute — JAX's
async dispatch gives the rest (docs/performance.md; T3/arxiv 2401.16677
is the same overlap principle applied one level down).

Semantics:

* worker exceptions are re-raised at ``next()`` — an input-pipeline
  failure surfaces on the training thread, at the step that needed the
  data, not as a silent worker death;
* ``StopIteration`` from the producer ends the stream cleanly (each
  subsequent ``next()`` keeps raising ``StopIteration``);
* ``close()`` shuts the worker down promptly even when it is blocked on
  a full buffer, and is idempotent;
* under multi-process JAX (``jax.process_count() > 1``) the iterator
  falls back to synchronous production: every process must issue
  cross-host array assembly in lockstep with its collectives, and a
  free-running background thread cannot guarantee that ordering.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional

from deepspeed_tpu.utils.logging import logger


class _EndOfStream:
    """Queue sentinel: the producer raised StopIteration."""


class _WorkerError:
    """Queue sentinel carrying the exception the producer raised."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchingIterator:
    """Bounded background prefetch over a producer of ready batches.

    ``source`` is either an iterator (``next()`` is the producer) or a
    zero-arg callable returning the next item and raising
    ``StopIteration`` when exhausted — the engine passes a callable that
    pulls ``gas`` microbatches, stacks them, and issues the sharded
    device transfer, so the whole input assembly runs off-thread.
    """

    def __init__(self, source, depth: int = 2, name: str = "prefetch",
                 allow_multiprocess: bool = False):
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        if callable(source) and not hasattr(source, "__next__"):
            self._produce: Callable[[], Any] = source
        else:
            it: Iterator = iter(source)
            self._produce = lambda: next(it)
        self.depth = depth
        self.name = name
        self._closed = False
        self._finished = False
        # observability for resume accounting (resilience/resume.py):
        # produced - consumed = batches pulled ahead of training, i.e.
        # the work a preemption discards and auto-resume must replay
        self.produced = 0
        self.consumed = 0
        self._sync = depth == 0
        if not self._sync and not allow_multiprocess:
            try:
                import jax

                if jax.process_count() > 1:
                    logger.warning(
                        f"{name}: multi-process run — input prefetch "
                        "falls back to the synchronous path (background "
                        "transfers cannot guarantee cross-host issue "
                        "order)")
                    self._sync = True
            except Exception:
                pass
        self._queue: Optional[queue.Queue] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if not self._sync:
            self._queue = queue.Queue(maxsize=depth)
            self._thread = threading.Thread(
                target=self._worker, name=f"dstpu-{name}", daemon=True)
            self._thread.start()

    # -- worker --------------------------------------------------------
    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._produce()
            except StopIteration:
                self._put(_EndOfStream)
                return
            except BaseException as e:  # propagate at next(), not here
                self._put(_WorkerError(e))
                return
            self.produced += 1
            if not self._put(item):
                return  # closed while blocked on a full buffer

    def _put(self, item) -> bool:
        """Blocking put that still honors close(); False when closed."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------
    def __iter__(self) -> "PrefetchingIterator":
        return self

    def __next__(self):
        if self._closed:
            raise RuntimeError(f"{self.name}: next() after close()")
        if self._finished:
            raise StopIteration
        if self._sync:
            item = self._produce()  # StopIteration propagates as-is
            self.produced += 1
            self.consumed += 1
            return item
        item = self._queue.get()
        if item is _EndOfStream:
            self._finished = True
            raise StopIteration
        if isinstance(item, _WorkerError):
            self._finished = True
            raise item.exc
        self.consumed += 1
        return item

    @property
    def buffered(self) -> int:
        """Items currently parked in the bounded buffer."""
        return self._queue.qsize() if self._queue is not None else 0

    # -- lifecycle -----------------------------------------------------
    def close(self, timeout: float = 2.0) -> None:
        """Stop the worker and drop buffered items. Idempotent; safe to
        call mid-epoch (the worker unblocks even when the buffer is
        full)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._queue is not None:
            while True:  # unblock a worker waiting in _put
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                logger.warning(
                    f"{self.name}: worker did not exit within "
                    f"{timeout}s (daemon thread will die with the "
                    "process)")

    def __enter__(self) -> "PrefetchingIterator":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def __del__(self):
        try:
            self.close(timeout=0.1)
        except Exception:
            pass
