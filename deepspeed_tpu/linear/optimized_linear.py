"""LoRA linear with (optionally quantized) frozen base weights.

Reference: ``deepspeed/linear/optimized_linear.py:76``
(``LoRAOptimizedLinear``) + ``linear/quantization.py`` (``QuantizedParameter``)
— a Linear whose full-rank base weight is frozen (and int8/int4-quantized
to save memory), trained only through low-rank A·B adapters:

    y = x @ W_base + (alpha / r) · (x @ A) @ B

TPU-native: a functional layer over a param dict. The quantized base is
stored as (int8 values, fp32 block scales) from ops/pallas/quantization
and dequantized on the fly inside the forward — XLA fuses the dequant
into the matmul's operand read, so HBM traffic for the base weight drops
by ~2x (bf16→int8), the reference's motivation. ``lora_trainable_mask``
feeds ``optax.masked`` so the optimizer steps only the adapters.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.ops.pallas.quantization import (dequantize_blockwise,
                                                   quantize_blockwise)


class LoRAOptimizedLinear:
    """Functional LoRA linear.

    params layout (dict):
      base     : [in, out] bf16   (absent when quantized)
      base_q   : [in, out] int8 + base_scale [in, out/group]  (quantized)
      lora_a   : [in, r]
      lora_b   : [r, out]
    """

    def __init__(self, input_dim: int, output_dim: int,
                 lora_config: Optional[LoRAConfig] = None,
                 quantization_config: Optional[QuantizationConfig] = None,
                 dtype=jnp.bfloat16):
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.lora = lora_config or LoRAConfig()
        self.quant = quantization_config
        self.dtype = dtype
        if self.lora.lora_r > min(input_dim, output_dim):
            raise ValueError(
                f"lora_r={self.lora.lora_r} exceeds "
                f"min(in={input_dim}, out={output_dim})")

    # -- params --------------------------------------------------------
    def init(self, rng, base_weight: Optional[jax.Array] = None
             ) -> Dict[str, Any]:
        k_base, k_a = jax.random.split(rng)
        if base_weight is None:
            base_weight = jax.random.normal(
                k_base, (self.input_dim, self.output_dim),
                jnp.float32) * (self.input_dim ** -0.5)
        base_weight = jnp.asarray(base_weight)
        r = self.lora.lora_r
        params: Dict[str, Any] = {
            # Kaiming init for A, zeros for B (standard LoRA init: the
            # adapter starts as a no-op)
            "lora_a": (jax.random.normal(k_a, (self.input_dim, r),
                                         jnp.float32)
                       * (self.input_dim ** -0.5)).astype(self.dtype),
            "lora_b": jnp.zeros((r, self.output_dim), self.dtype),
        }
        if self.quant is not None:
            q, s = quantize_blockwise(base_weight.astype(jnp.float32),
                                      bits=self.quant.q_bits,
                                      block=self.quant.group_size)
            params["base_q"] = q
            params["base_scale"] = s
        else:
            params["base"] = base_weight.astype(self.dtype)
        if self.lora.offload and "base" in params:
            params["base"] = jax.device_put(
                params["base"], jax.local_devices(backend="cpu")[0]) \
                if jax.local_devices(backend="cpu") else params["base"]
        return params

    # -- forward -------------------------------------------------------
    def apply(self, params: Dict[str, Any], x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        w = self._base_weight(params)
        y = x @ w
        scaling = self.lora.lora_alpha / self.lora.lora_r
        y = y + (x @ params["lora_a"].astype(self.dtype)
                 ) @ params["lora_b"].astype(self.dtype) * scaling
        return y

    __call__ = apply

    def _base_weight(self, params) -> jax.Array:
        if "base_q" in params:
            return dequantize_blockwise(
                params["base_q"], params["base_scale"],
                bits=self.quant.q_bits, block=self.quant.group_size,
                dtype=self.dtype)
        return jax.lax.stop_gradient(params["base"]).astype(self.dtype)

    # -- utilities -------------------------------------------------------
    def merge(self, params: Dict[str, Any]) -> jax.Array:
        """Fold the adapters into a dense weight (reference hybrid-engine
        LoRA fuse; used when exporting or switching to inference)."""
        w = self._base_weight(params).astype(jnp.float32)
        scaling = self.lora.lora_alpha / self.lora.lora_r
        return (w + params["lora_a"].astype(jnp.float32)
                @ params["lora_b"].astype(jnp.float32) * scaling
                ).astype(self.dtype)


def lora_merge(layer: LoRAOptimizedLinear, params: Dict[str, Any]):
    return layer.merge(params)


def lora_trainable_mask(params) -> Any:
    """Pytree of bools marking only LoRA adapters trainable — feed to
    ``optax.masked(tx, mask)`` (reference freezes the base weight the
    same way via requires_grad)."""
    def mark(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return name.startswith("lora_")

    return jax.tree_util.tree_map_with_path(mark, params)
