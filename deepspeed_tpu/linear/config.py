"""Config dataclasses for the optimized-linear subsystem.

Reference: ``deepspeed/linear/config.py`` (``LoRAConfig`` with lora_r /
lora_alpha / base_weight_sharding, ``QuantizationConfig`` with q_bits /
group_size).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class LoRAConfig:
    lora_r: int = 64
    lora_alpha: float = 16.0
    # reference base_weight_sharding shards the frozen base across dp
    # ranks; here the equivalent is a NamedSharding on the base weight —
    # the axis name to shard the contraction dim over ('' = replicated)
    base_weight_sharding_axis: str = ""
    offload: bool = False  # keep frozen base in host memory

    def __post_init__(self):
        if self.lora_r <= 0:
            raise ValueError("lora_r must be positive")


@dataclasses.dataclass
class QuantizationConfig:
    q_bits: int = 8
    group_size: int = 128  # blockwise-quant block (reference group_size)

    def __post_init__(self):
        if self.q_bits not in (4, 8):
            raise ValueError("q_bits must be 4 or 8")
