"""Optimized linear / LoRA subsystem (reference: deepspeed/linear/)."""

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig  # noqa: F401
from deepspeed_tpu.linear.optimized_linear import (  # noqa: F401
    LoRAOptimizedLinear,
    lora_merge,
    lora_trainable_mask,
)
