"""Elastic training agent: supervise workers, restart on membership change.

Reference: ``DSElasticAgent`` (deepspeed/elasticity/elastic_agent.py:32)
extends torch-elastic's LocalElasticAgent — it monitors the worker group,
and on failure or scale-up/down event tears the group down and restarts
it against a new rendezvous, with the elastic batch config
(elasticity/elasticity.py:233) keeping the global batch size valid across
node counts.

TPU re-design: there is no torch-elastic rendezvous; group membership is
the set of reachable hosts (hostfile, callable, or TPU pod metadata), and
a "restart" relaunches the per-host processes with a fresh JAX
coordinator. Workers are expected to resume from their latest checkpoint
(engine.load_checkpoint finds the ``latest`` tag) — the agent only
manages processes and topology, exactly like the reference splits agent
(process lifecycle) from elasticity (batch-size math).
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.resilience.policy import (TRANSIENT_EXIT_CODE,
                                             RetryPolicy)
from deepspeed_tpu.utils.logging import logger


class WorkerGroupFailure(RuntimeError):
    pass


class ElasticAgent:
    """Supervises one worker process per host; restarts the whole group on
    membership change or worker failure, up to ``max_restarts`` times.

    Parameters
    ----------
    cmd_builder: (hosts, restart_count) -> list of argv lists, one per host.
        Rebuilt every (re)start so the coordinator address / world size
        track the current membership.
    membership_fn: () -> list of live hostnames. Polled every
        ``poll_interval`` seconds; any change triggers a restart.
    min_nodes / max_nodes: admissible group size (reference
        launcher/runner.py:88-102 --min_elastic_nodes/--max_elastic_nodes).
    ds_config: optional config dict; when it enables elasticity the agent
        validates each new node count against compute_elastic_config
        before restarting (invalid counts are waited out, not crashed on).
    """

    def __init__(self, cmd_builder: Callable[[Sequence[str], int],
                                             List[List[str]]],
                 membership_fn: Callable[[], List[str]],
                 min_nodes: int = 1, max_nodes: int = 64,
                 max_restarts: int = 100, poll_interval: float = 5.0,
                 ds_config: Optional[Dict] = None,
                 env: Optional[Dict[str, str]] = None,
                 restart_backoff_s: float = 1.0):
        if min_nodes < 1 or max_nodes < min_nodes:
            raise ValueError(f"bad node range [{min_nodes}, {max_nodes}]")
        self.cmd_builder = cmd_builder
        self.membership_fn = membership_fn
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.ds_config = ds_config
        self.env = dict(env or {})
        self.restart_count = 0
        self._procs: List[subprocess.Popen] = []
        self._last_membership: List[str] = []
        # failure classification for the last round (resilience/policy.py:
        # workers dying of CommTimeoutError exit with TRANSIENT_EXIT_CODE,
        # so the agent can tell "transient comm wedge → back off and
        # retry the same group" from "rank dead → restart immediately,
        # membership may have changed")
        self.last_exit_codes: List[Optional[int]] = []
        self.last_failure_kind: str = "none"
        self._backoff = RetryPolicy(backoff_base_s=restart_backoff_s,
                                    max_retries=max_restarts)

    # -- membership --------------------------------------------------------
    def _poll_membership(self) -> List[str]:
        """Current membership; a raising/transiently-broken source (e.g. a
        hostfile mid-rewrite) returns the last known good value instead of
        [] so a healthy group is not torn down on a read glitch."""
        try:
            hosts = sorted(self.membership_fn())
        except Exception as e:
            logger.warning(f"elastic agent: membership poll failed ({e}); "
                           "keeping last known membership")
            return self._last_membership
        self._last_membership = hosts
        return hosts

    def _admissible(self, hosts: Sequence[str]) -> bool:
        n = len(hosts)
        if not self.min_nodes <= n <= self.max_nodes:
            return False
        if self.ds_config and self.ds_config.get(
                "elasticity", {}).get("enabled", False):
            try:
                compute_elastic_config(self.ds_config,
                                       target_deployment_size=n)
            except Exception as e:
                logger.warning(
                    f"elastic agent: {n} nodes has no valid elastic batch "
                    f"config ({e}); waiting for membership change")
                return False
        return True

    def _wait_for_quorum(self) -> List[str]:
        while True:
            hosts = self._poll_membership()
            if self._admissible(hosts):
                return hosts
            time.sleep(self.poll_interval)

    # -- process lifecycle -------------------------------------------------
    def _start(self, hosts: Sequence[str]) -> None:
        env = dict(os.environ, **self.env)
        env["DSTPU_ELASTIC_RESTART_COUNT"] = str(self.restart_count)
        env["DSTPU_ELASTIC_WORLD"] = ",".join(hosts)
        cmds = self.cmd_builder(hosts, self.restart_count)
        self._procs = []
        try:
            for c in cmds:
                self._procs.append(subprocess.Popen(c, env=env))
        except Exception:
            self._stop()  # don't leak the workers spawned before the error
            raise
        logger.info(f"elastic agent: started {len(self._procs)} workers "
                    f"on {list(hosts)} (restart {self.restart_count})")

    def _stop(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        self._procs = []

    #: grace period for the remaining workers to exit after the first
    #: clean worker exit (a finished SPMD program drains within seconds;
    #: longer means the survivors are stuck in a collective with the
    #: departed rank and the round must be torn down)
    drain_grace = 30.0

    def _poll_group(self) -> Optional[int]:
        """None while all workers run; 0 = all exited cleanly; 1 = a
        worker failed (restart now); -1 = partial clean exit (grace)."""
        rcs = [p.poll() for p in self._procs]
        if any(rc not in (None, 0) for rc in rcs):
            self.last_exit_codes = list(rcs)
            bad = [rc for rc in rcs if rc not in (None, 0)]
            self.last_failure_kind = (
                "transient" if all(rc == TRANSIENT_EXIT_CODE for rc in bad)
                else "fatal")
            return 1
        if all(rc is not None for rc in rcs):
            return 0
        if any(rc is not None for rc in rcs):
            return -1
        return None

    # -- main loop ---------------------------------------------------------
    def run(self) -> int:
        """Supervise until clean exit; returns the final returncode."""
        while True:
            hosts = self._wait_for_quorum()
            self._start(hosts)
            try:
                rc = self._supervise(hosts)
            finally:
                self._stop()
            if rc == 0:
                logger.info("elastic agent: worker group exited cleanly")
                return 0
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                raise WorkerGroupFailure(
                    f"worker group failed {self.restart_count} times "
                    f"(max_restarts={self.max_restarts}); last failure "
                    f"kind={self.last_failure_kind} exit codes="
                    f"{self.last_exit_codes}")
            if self.last_failure_kind == "transient":
                # CommTimeoutError exits (code 75): the group wedged on a
                # slow/flaky control-plane op — same membership is worth
                # retrying, but back off so a persistently sick network
                # doesn't thrash restart cycles
                delay = self._backoff.backoff_s(self.restart_count)
                logger.warning(
                    f"elastic agent: transient comm failure (exit "
                    f"{TRANSIENT_EXIT_CODE}); backing off {delay:.1f}s "
                    f"before restart "
                    f"({self.restart_count}/{self.max_restarts})")
                time.sleep(delay)
            else:
                logger.warning(
                    f"elastic agent: restarting group "
                    f"({self.restart_count}/{self.max_restarts}, "
                    f"cause={self.last_failure_kind})")

    def _supervise(self, hosts: Sequence[str]) -> int:
        """Run one group round; returns aggregate rc (1 = needs restart)."""
        drain_deadline = None
        while True:
            rc = self._poll_group()
            if rc == 0:
                return 0
            if rc == 1:
                return 1
            if rc == -1:
                if drain_deadline is None:
                    drain_deadline = time.time() + self.drain_grace
                elif time.time() > drain_deadline:
                    logger.warning(
                        "elastic agent: workers still running "
                        f"{self.drain_grace}s after a peer exited cleanly "
                        "(likely deadlocked collective); restarting group")
                    self.last_failure_kind = "fatal"
                    return 1
            current = self._poll_membership()
            if current != list(hosts):
                logger.warning(
                    f"elastic agent: membership changed {list(hosts)} -> "
                    f"{current}; restarting group")
                self.last_failure_kind = "membership"
                return 1
            time.sleep(self.poll_interval)


def hostfile_membership(path: str) -> Callable[[], List[str]]:
    """Membership source that re-reads a hostfile each poll (hosts may be
    added/removed between rounds, the reference's scale-up/down event)."""

    def poll() -> List[str]:
        from deepspeed_tpu.launcher.runner import parse_hostfile

        # raises on a missing/mid-rewrite hostfile; the agent holds the
        # last known membership across such transients
        return list(parse_hostfile(path))

    return poll
