"""Elastic training: batch sizes that stay valid as the chip count changes.

Reference: ``deepspeed/elasticity/elasticity.py`` —
``compute_elastic_config`` (:233) picks a global train batch size that is
(a) as large as allowed, (b) highly composite, so that for *every* chip
count in ``[min_chips, max_chips]`` (times a granularity of micro-batch ×
GAS splits) the batch divides evenly. A job can then checkpoint, lose or
gain hosts, and resume with identical optimization semantics — the same
global batch, re-factored into a new micro×GAS×dp triple.

Two algorithm versions exist in the reference (v0.1 :83, v0.2 :126 — v0.2
adds ``model_parallel_size``/granularity interplay). Here a single
implementation covers both: candidate batches are built from
highly-composite multiples of (micro-batch candidates × granularity), and
compatible chip counts are whatever divides them after removing the
model-parallel factor.

On TPU the "chip count" axis is the data-parallel extent of the mesh
(total chips / (tp·pp·sp) — elasticity composes with model parallelism
exactly as the reference's v0.2 does).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    """Reference raises the same-named family of errors."""


@dataclasses.dataclass
class ElasticityConfig:
    """Reference elasticity config block (elasticity/config.py)."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: Sequence[int] = (2, 4, 6)
    min_chips: int = 1
    max_chips: int = 10000
    min_time: int = 0  # minutes per step lower bound (advisory, unused here)
    version: float = LATEST_ELASTICITY_VERSION
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True
    model_parallel_size: int = 1  # tp·pp·sp product (v0.2)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ElasticityConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        # reference key aliases
        d = dict(d)
        if "min_gpus" in d:
            d["min_chips"] = d.pop("min_gpus")
        if "max_gpus" in d:
            d["max_chips"] = d.pop("max_gpus")
        return cls(**{k: v for k, v in d.items() if k in known})


def _candidate_batches(max_batch: int,
                       micro_batches: Sequence[int]) -> List[int]:
    """Highly-composite batch candidates ≤ max_batch built as
    micro_batch × (products of small primes) — the reference's
    get_candidate_batch_sizes over its HCN table."""
    # highly composite numbers up to ~10k (reference HCN_LIST-equivalent)
    hcn = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
           1260, 1680, 2520, 5040, 7560]
    out = set()
    for mb in micro_batches:
        best = None
        for h in hcn:
            if mb * h <= max_batch:
                best = mb * h
        if best is not None:
            out.add(best)
    return sorted(out)


def _compatible_chip_counts(batch: int, micro_batches: Sequence[int],
                            min_chips: int, max_chips: int) -> List[int]:
    """All dp extents w ∈ [min,max] s.t. batch = micro × GAS × w for some
    listed micro and integer GAS (reference get_compatible_gpus)."""
    ok = []
    for w in range(min_chips, max_chips + 1):
        for mb in micro_batches:
            if batch % (mb * w) == 0:
                ok.append(w)
                break
    return ok


def get_valid_batch_sizes(max_batch: int, micro_batches: Sequence[int],
                          min_chips: int, max_chips: int
                          ) -> Dict[int, List[int]]:
    """batch → compatible dp chip counts, for every candidate batch."""
    return {b: _compatible_chip_counts(b, micro_batches, min_chips,
                                       max_chips)
            for b in _candidate_batches(max_batch, micro_batches)}


def compute_elastic_config(ds_config: Dict[str, Any],
                           target_deployment_size: Optional[int] = None,
                           return_microbatch: bool = False
                           ) -> Tuple[int, List[int], Any]:
    """Pick (final_batch_size, valid_chip_counts[, micro_batch]) —
    reference compute_elastic_config (elasticity.py:233).

    ``target_deployment_size``: the dp extent the job is actually starting
    with (world // model_parallel_size); when given, also returns the
    micro-batch for that extent.
    """
    if "elasticity" not in ds_config:
        raise ElasticityError("config has no 'elasticity' block")
    ecfg = ElasticityConfig.from_dict(ds_config["elasticity"])
    if not ecfg.enabled:
        raise ElasticityError("elasticity.enabled is false")
    if float(ecfg.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityError(
            f"unsupported elasticity version {ecfg.version} "
            f"(latest {LATEST_ELASTICITY_VERSION})")
    if not ecfg.ignore_non_elastic_batch_info:
        fixed = [k for k in ("train_batch_size",
                             "train_micro_batch_size_per_chip",
                             "train_micro_batch_size_per_gpu",
                             "gradient_accumulation_steps")
                 if ds_config.get(k) not in (None, "auto")]
        if fixed:
            raise ElasticityError(
                f"elastic mode: remove fixed batch keys {fixed} or set "
                "elasticity.ignore_non_elastic_batch_info=true")

    mp = max(int(ecfg.model_parallel_size), 1)
    min_dp = max(1, ecfg.min_chips // mp)
    max_dp = max(min_dp, ecfg.max_chips // mp)
    table = get_valid_batch_sizes(ecfg.max_train_batch_size,
                                  ecfg.micro_batch_sizes, min_dp, max_dp)
    # score: widest compatibility first; tie-break larger batch (the
    # reference's "prefer_larger" flag)
    best_batch, best_counts = None, []
    for batch, counts in table.items():
        better = len(counts) > len(best_counts) or (
            len(counts) == len(best_counts)
            and ecfg.prefer_larger_batch and (best_batch or 0) < batch)
        if counts and better:
            best_batch, best_counts = batch, counts
    if best_batch is None:
        raise ElasticityError(
            f"no batch ≤ {ecfg.max_train_batch_size} works for chips "
            f"[{ecfg.min_chips}, {ecfg.max_chips}] with micro batches "
            f"{list(ecfg.micro_batch_sizes)}")

    if target_deployment_size is not None:
        dp = target_deployment_size // mp
        if dp not in best_counts:
            raise ElasticityError(
                f"current deployment dp={dp} (chips/"
                f"{mp} mp) not compatible with elastic batch {best_batch}; "
                f"valid dp extents: {best_counts}")
        if return_microbatch:
            micro = next(mb for mb in sorted(ecfg.micro_batch_sizes,
                                             reverse=True)
                         if best_batch % (mb * dp) == 0)
            return best_batch, best_counts, micro
    if return_microbatch:
        return best_batch, best_counts, None
    return best_batch, best_counts, ElasticityConfig.from_dict(
        ds_config["elasticity"])


def main(argv=None):
    """``dstpu-elastic`` CLI (reference bin/ds_elastic): print the elastic
    batch plan for a config file."""
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="dstpu-elastic",
        description="show the elastic batch size and compatible chip "
                    "counts for a deepspeed config")
    ap.add_argument("config", help="ds_config JSON path")
    ap.add_argument("--chips", type=int, default=None,
                    help="planned deployment size (validates + picks micro)")
    args = ap.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    if args.chips is not None:
        batch, counts, micro = compute_elastic_config(
            cfg, target_deployment_size=args.chips, return_microbatch=True)
        print(json.dumps({"train_batch_size": batch,
                          "valid_dp_extents": counts,
                          "micro_batch_per_chip": micro,
                          "deployment_chips": args.chips}))
    else:
        batch, counts, _ = compute_elastic_config(cfg)
        print(json.dumps({"train_batch_size": batch,
                          "valid_dp_extents": counts}))
    return 0
