"""Elasticity subsystem (reference: deepspeed/elasticity/)."""

from deepspeed_tpu.elasticity.elasticity import (  # noqa: F401
    ElasticityConfig,
    ElasticityError,
    compute_elastic_config,
    get_valid_batch_sizes,
)
