"""Prefill/decode disaggregation: the KV-block handoff codec.

Disaggregated serving (DistServe, Splitwise; PAPERS.md) runs prefill and
decode on different replicas so a long-prompt burst never sits in front
of another request's next token — decode p99 is isolated by placement,
not by scheduling heroics. The hard part is moving the prompt's KV from
the prefill replica to the decode replica. Here the transport is the
prefix cache's own vocabulary:

* ``serialize_prefix`` — after a prefill replica finishes a request's
  first token, its full, write-complete prompt blocks are already
  registered in that replica's prefix cache under a content-hash chain
  (``ragged/prefix_cache.py``). Serialization is a lookup of that chain
  plus one host copy of the block contents — no new wire format, the
  chain keys ARE the codec.
* ``install_prefix`` — the decode replica allocates blocks, writes the
  payload into its own KV pool, and registers the same chain keys as
  *idle* cache entries. When the router then resubmits
  ``prompt + [first_token]`` to the decode replica, the ordinary
  ``StateManager.attach_prefix`` path revives the chain by content hash
  and the decode replica skips re-prefilling everything the payload
  covered — the handoff needs no special admission path at all.

Greedy bit-identity is preserved by construction: KV content for a
token depends only on the tokens before it and the (shared) params, so
installed blocks are exactly what the decode replica would have
computed; the partial tail block is recomputed locally like any other
prefix-cache hit. Every degradation (no cache, geometry mismatch, pool
too full) returns a zero-block install and the decode replica simply
prefills from scratch — disaggregation can lose its optimization but
never a request.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class KVHandoff:
    """Serialized write-complete prompt blocks from one replica's pool.

    ``block_data`` is host memory shaped
    ``[num_layers, n_blocks, block_size, 2, kv_heads, head_dim]`` —
    the pool layout of the covered blocks, in chain order. ``keys`` is
    the content-hash chain that addresses them on any replica."""

    keys: List[str]
    block_data: np.ndarray
    block_size: int

    @property
    def n_blocks(self) -> int:
        return len(self.keys)

    @property
    def n_tokens(self) -> int:
        return len(self.keys) * self.block_size


def serialize_prefix(engine, tokens,
                     max_blocks: Optional[int] = None
                     ) -> Optional[KVHandoff]:
    """Serialize the cached full-block chain covering ``tokens`` from
    ``engine``'s KV pool. Returns None when nothing is cached (short
    prompt, prefix cache off, or the chain was already evicted) — the
    caller then hands off tokens only and the target recomputes.

    The chain is ref'd for the duration of the device→host copy so KV
    pressure on the source replica cannot evict-and-recycle a block
    mid-serialization."""
    cache = getattr(engine.kv_cache, "prefix_cache", None)
    if cache is None:
        return None
    toks = np.asarray(tokens, np.int32).ravel()
    # same cap as attach_prefix: the final prompt token stays uncached
    # so admission still computes first-token logits
    keys, blocks = cache.lookup(toks, max_tokens=len(toks) - 1)
    if not keys:
        return None
    if max_blocks is not None:
        keys, blocks = keys[:max_blocks], blocks[:max_blocks]
    cache.ref(keys)
    try:
        data = np.asarray(engine.kv_cache.data[:, np.asarray(blocks)])
    finally:
        cache.unref(keys)
    return KVHandoff(keys=keys, block_data=data,
                     block_size=cache.block_size)


def install_prefix(engine, handoff: Optional[KVHandoff]
                   ) -> Tuple[int, int]:
    """Install a handoff payload into ``engine``'s pool + prefix cache.

    Returns ``(blocks_installed, tokens_attachable)`` where the token
    count covers the whole chain the target now holds (payload blocks
    plus any chain prefix it already cached from earlier traffic). A
    ``(0, 0)`` return means the handoff degraded to recompute — never
    an error.

    Must run on the thread that owns ``engine`` (the replica pump): it
    mutates the pool array and the cache registry."""
    cache = getattr(engine.kv_cache, "prefix_cache", None)
    if cache is None or handoff is None or not handoff.keys:
        return (0, 0)
    kvc = engine.kv_cache
    if (handoff.block_size != cache.block_size
            or handoff.block_data.shape[0] != kvc.data.shape[0]
            or handoff.block_data.shape[2:] != kvc.data.shape[2:]):
        return (0, 0)  # geometry mismatch: heterogeneous fleet, recompute
    # the target may already hold a chain prefix (shared system prompt
    # traffic): install only past the longest cached prefix — suffix
    # keys without their predecessors would be unreachable by lookup
    pos = 0
    while pos < len(handoff.keys) and cache.get(handoff.keys[pos]) is not None:
        pos += 1
    to_install = list(range(pos, len(handoff.keys)))
    if not to_install:
        return (0, handoff.n_tokens)
    need = len(to_install)
    if kvc.free_blocks < need:
        kvc.reclaim(need - kvc.free_blocks)
    if kvc.free_blocks < need:
        # pool under live pressure: installing would evict working-set
        # blocks of running decodes — degrade to recompute instead
        return (0, pos * handoff.block_size)

    import jax.numpy as jnp

    blocks = kvc.allocator.allocate(need)
    src = jnp.asarray(handoff.block_data[:, to_install], dtype=kvc.data.dtype)
    kvc.data = kvc.data.at[:, jnp.asarray(blocks)].set(src)
    installed: List[str] = []
    for idx, blk in zip(to_install, blocks):
        if cache.register(handoff.keys[idx], int(blk)):
            installed.append(handoff.keys[idx])
        else:  # registered concurrently under another block: keep theirs
            kvc.free([int(blk)])
    # drop the registration ref: the chain parks idle-cached, exactly
    # like a released prompt — attach_prefix revives it by content hash
    # and KV pressure can evict it, so an unused handoff costs nothing
    cache.unref(installed)
    hub = getattr(engine, "_hub", None)
    if hub is not None and installed:
        lbl = getattr(engine, "_metric_labels", None)
        hub.counter_add("serve.handoff_blocks", len(installed), labels=lbl)
        hub.counter_add("serve.handoff_tokens",
                        len(installed) * handoff.block_size, labels=lbl)
    return (len(installed), handoff.n_tokens)
