"""Prefill/decode disaggregation: the KV-block handoff codec.

Disaggregated serving (DistServe, Splitwise; PAPERS.md) runs prefill and
decode on different replicas so a long-prompt burst never sits in front
of another request's next token — decode p99 is isolated by placement,
not by scheduling heroics. The hard part is moving the prompt's KV from
the prefill replica to the decode replica. Here the transport is the
prefix cache's own vocabulary:

* ``serialize_prefix`` — after a prefill replica finishes a request's
  first token, its full, write-complete prompt blocks are already
  registered in that replica's prefix cache under a content-hash chain
  (``ragged/prefix_cache.py``). Serialization is a lookup of that chain
  plus one host copy of the block contents — no new wire format, the
  chain keys ARE the codec.
* ``install_prefix`` — the decode replica allocates blocks, writes the
  payload into its own KV pool, and registers the same chain keys as
  *idle* cache entries. When the router then resubmits
  ``prompt + [first_token]`` to the decode replica, the ordinary
  ``StateManager.attach_prefix`` path revives the chain by content hash
  and the decode replica skips re-prefilling everything the payload
  covered — the handoff needs no special admission path at all.

Greedy bit-identity is preserved by construction: KV content for a
token depends only on the tokens before it and the (shared) params, so
installed blocks are exactly what the decode replica would have
computed; the partial tail block is recomputed locally like any other
prefix-cache hit. Every degradation (no cache, geometry mismatch, pool
too full) returns a zero-block install and the decode replica simply
prefills from scratch — disaggregation can lose its optimization but
never a request.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import numpy as np

# handoff wire codec modes (engine `handoff_wire` knob / autotuner axis):
#   auto — ship the source pool's native format (quantized pool: int8
#          payload + scales as-is; bf16 pool: raw)
#   raw  — full-precision bf16 blocks (pre-quant wire format)
#   int8 — bf16 pools quantize per head vector for the wire (~0.53x)
#   int4 — as int8, then two nibbles pack per byte (~0.28x — the
#          <=0.35x-of-bf16 acceptance mode)
#   fp8  — e4m3 payload + per-vector scales (~0.53x, the quality
#          midpoint between int8 and int4) shipped NATIVELY — no bf16
#          round trip; matches the PR 17 fp8 KV pool rung
WIRE_MODES = ("auto", "raw", "int8", "int4", "fp8")


@dataclasses.dataclass
class KVHandoff:
    """Serialized write-complete prompt blocks from one replica's pool.

    ``block_data`` is host memory shaped
    ``[num_layers, n_blocks, block_size, 2, kv_heads, head_dim]`` —
    the pool layout of the covered blocks, in chain order (for the int4
    wire the last dim is ``head_dim/2`` packed bytes and ``packed`` is
    set). ``keys`` is the content-hash chain that addresses them on any
    replica. ``scales`` rides along for quantized wires: one fp32 per
    (layer, block, row, k/v, head) vector. ``src_quant_bits`` records
    the SOURCE pool's storage mode so the installer can warn on a
    fleet-wide precision mismatch (quantized pool feeding a bf16 pool
    or vice versa — silent double conversion)."""

    keys: List[str]
    block_data: np.ndarray
    block_size: int
    scales: Optional[np.ndarray] = None
    wire_bits: Optional[Any] = None   # None = full precision; 4/8/"fp8"
    packed: bool = False              # int4 nibble packing along head_dim
    src_quant_bits: Optional[Any] = None
    wire_snr_db: Optional[float] = None  # measured at wire-quantize time

    @property
    def n_blocks(self) -> int:
        return len(self.keys)

    @property
    def n_tokens(self) -> int:
        return len(self.keys) * self.block_size

    @property
    def head_dim(self) -> int:
        hd = self.block_data.shape[-1]
        return hd * 2 if self.packed else hd

    @property
    def wire_nbytes(self) -> int:
        """Bytes this payload actually puts on the wire."""
        n = int(self.block_data.nbytes)
        if self.scales is not None:
            n += int(self.scales.nbytes)
        return n

    @property
    def logical_nbytes(self) -> int:
        """Full-precision bytes of the same blocks — the pre-quant wire
        format (a raw handoff IS full precision; quantized wires compare
        against the bf16 serving pool)."""
        if self.wire_bits is None:
            return int(self.block_data.nbytes)
        return int(np.prod(self.block_data.shape[:-1])) * self.head_dim * 2


def _record_wire(engine, handoff: KVHandoff, where: str) -> None:
    """Wire-vs-logical byte accounting for one handoff: hub counters,
    a comm traced_span (flight ring + Perfetto comm lane), and — when
    quant.* collection is configured — a published kv_wire region."""
    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.observability import quant_stats

    with comm.traced_span("kv_handoff", handoff.block_data, "host",
                          f"kv_handoff_{where}"):
        pass
    # per-engine accumulators feed replica.load_report → fleet snapshot
    engine._handoff_wire_bytes = (
        getattr(engine, "_handoff_wire_bytes", 0) + handoff.wire_nbytes)
    engine._handoff_logical_bytes = (
        getattr(engine, "_handoff_logical_bytes", 0)
        + handoff.logical_nbytes)
    if handoff.wire_snr_db is not None:
        engine._last_kv_wire_snr_db = handoff.wire_snr_db
    hub = getattr(engine, "_hub", None)
    if hub is not None:
        lbl = getattr(engine, "_metric_labels", None)
        hub.counter_add("serve.handoff_wire_bytes", handoff.wire_nbytes,
                        labels=lbl)
        hub.counter_add("serve.handoff_logical_bytes",
                        handoff.logical_nbytes, labels=lbl)
        if handoff.wire_bits is not None:
            hub.gauge("quant.kv_wire.compression",
                      handoff.logical_nbytes / max(1, handoff.wire_nbytes),
                      labels=lbl)
    if quant_stats.collection_configured() and handoff.wire_bits is not None:
        st = quant_stats.QuantRegionStats(
            region="kv_wire", snr_db=handoff.wire_snr_db, max_rel_err=0.0,
            logical_bytes=handoff.logical_nbytes,
            wire_bytes=handoff.wire_nbytes,
            n_elements=int(np.prod(handoff.block_data.shape[:-1]))
            * handoff.head_dim,
            bits=handoff.wire_bits, block=handoff.head_dim,
            note=f"disagg handoff {where}: {handoff.n_blocks} blocks")
        quant_stats.publish([st], hub=hub)


def _wire_quantize(data: np.ndarray, scales: Optional[np.ndarray],
                   src_bits, wire: str):
    """Wire-side quantization for bf16 pools: convert ``data`` (+
    ``scales``) to the requested wire codec. A quantized pool ships its
    native payload untouched (its bf16 original no longer exists), so
    the conversion applies only when ``src_bits`` is None. Returns
    ``(data, scales, wire_bits, packed, wire_snr_db)`` — the SNR is
    measured HERE, the one place the full-precision original and the
    wire payload coexist."""
    # an int4 pool's native payload is already nibble-packed — mark it
    # so head_dim geometry and the installer's unpack stay correct
    wire_bits, packed, wire_snr = src_bits, src_bits == 4, None
    if src_bits is None and wire in ("int8", "int4", "fp8"):
        import jax.numpy as jnp

        from deepspeed_tpu.ops.pallas.quantization import (kv_dequantize,
                                                           kv_quantize,
                                                           pack_int4)

        bits = {"int8": 8, "int4": 4, "fp8": "fp8"}[wire]
        if bits == 4 and data.shape[-1] % 2:
            bits = 8  # nibble packing needs an even head_dim
        q, s = kv_quantize(jnp.asarray(data), bits=bits)
        err = (np.asarray(kv_dequantize(q, s, dtype=jnp.float32),
                          np.float32) - np.asarray(data, np.float32))
        sig = float(np.sum(np.asarray(data, np.float32) ** 2))
        noise = float(np.sum(err ** 2))
        wire_snr = (float("inf") if noise == 0.0
                    else 10.0 * float(np.log10(max(sig, 1e-30) / noise)))
        if bits == 4:
            q = pack_int4(q)
            packed = True
        data, scales, wire_bits = np.asarray(q), np.asarray(s), bits
    return data, scales, wire_bits, packed, wire_snr


def _pool_convert(kvc, payload, ssel, wire_bits, packed: bool):
    """Convert a wire payload (+ scales) into ``kvc``'s pool-native
    storage: the install-side half of the codec, shared by
    ``install_prefix`` and ``install_session``. ``payload``/``ssel``
    are jnp arrays (scales fp32 or None); returns ``(q, s)`` with ``q``
    in the pool dtype (nibble-packed when the pool is int4) and ``s``
    the fp32 scales or None for a bf16 pool."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.pallas.quantization import (kv_dequantize,
                                                       kv_quantize,
                                                       pack_int4,
                                                       unpack_int4)

    dst_bits = getattr(kvc, "quant_bits", None)
    if packed:
        payload = unpack_int4(payload)
    if dst_bits is not None:
        if wire_bits is None:
            # raw bf16 wire into a quantized pool: quantize-on-install
            q, s = kv_quantize(payload, bits=dst_bits)
        elif wire_bits == dst_bits:
            # wire already in the pool's own format: install directly
            q = payload if dst_bits == "fp8" else payload.astype(jnp.int8)
            s = ssel
        elif dst_bits == "fp8" or wire_bits == "fp8":
            # int<->fp8: the stored codes don't reinterpret (int grids
            # are scale*code on an integer lattice, e4m3 is a float
            # format), so round-trip through f32 onto the destination's
            # grid (the precision-mismatch warn above fired)
            q, s = kv_quantize(
                kv_dequantize(payload, ssel, dtype=jnp.float32),
                bits=dst_bits)
        elif dst_bits == 4 and wire_bits == 8:
            # int8 wire values overflow the int4 grid: requantize on the
            # coarser grid (the precision-mismatch warn above fired)
            q, s = kv_quantize(
                kv_dequantize(payload, ssel, dtype=jnp.float32), bits=4)
        else:
            # int4 values install into an int8 pool directly — dequant
            # is q*s either way, just on a coarser grid
            q, s = payload.astype(jnp.int8), ssel
        if dst_bits == 4:
            q = pack_int4(q.astype(jnp.int8))
        return q.astype(kvc.data.dtype), s
    if wire_bits is None:
        return payload.astype(kvc.data.dtype), None
    return kv_dequantize(payload, ssel, dtype=kvc.data.dtype), None


def serialize_prefix(engine, tokens,
                     max_blocks: Optional[int] = None,
                     wire: Optional[str] = None
                     ) -> Optional[KVHandoff]:
    """Serialize the cached full-block chain covering ``tokens`` from
    ``engine``'s KV pool. Returns None when nothing is cached (short
    prompt, prefix cache off, or the chain was already evicted) — the
    caller then hands off tokens only and the target recomputes.

    ``wire`` picks the codec (:data:`WIRE_MODES`; default the engine's
    ``handoff_wire`` knob). A quantized pool always ships its native
    int8 payload + scales as-is — its bf16 original no longer exists —
    so ``wire`` only selects a conversion for bf16 pools.

    The chain is ref'd for the duration of the device→host copy so KV
    pressure on the source replica cannot evict-and-recycle a block
    mid-serialization."""
    cache = getattr(engine.kv_cache, "prefix_cache", None)
    if cache is None:
        return None
    wire = wire or getattr(engine, "_handoff_wire", "auto") or "auto"
    if wire not in WIRE_MODES:
        raise ValueError(f"handoff wire mode {wire!r} "
                         f"(choose from {WIRE_MODES})")
    toks = np.asarray(tokens, np.int32).ravel()
    # same cap as attach_prefix: the final prompt token stays uncached
    # so admission still computes first-token logits
    keys, blocks = cache.lookup(toks, max_tokens=len(toks) - 1)
    if not keys:
        return None
    if max_blocks is not None:
        keys, blocks = keys[:max_blocks], blocks[:max_blocks]
    kvc = engine.kv_cache
    src_bits = getattr(kvc, "quant_bits", None)
    cache.ref(keys)
    try:
        idx = np.asarray(blocks)
        data = np.asarray(kvc.data[:, idx])
        scales = (np.asarray(kvc.scales[:, idx])
                  if getattr(kvc, "scales", None) is not None else None)
    finally:
        cache.unref(keys)
    data, scales, wire_bits, packed, wire_snr = _wire_quantize(
        data, scales, src_bits, wire)
    handoff = KVHandoff(keys=keys, block_data=data,
                        block_size=cache.block_size, scales=scales,
                        wire_bits=wire_bits, packed=packed,
                        src_quant_bits=src_bits, wire_snr_db=wire_snr)
    _record_wire(engine, handoff, "serialize")
    return handoff


def install_prefix(engine, handoff: Optional[KVHandoff]
                   ) -> Tuple[int, int]:
    """Install a handoff payload into ``engine``'s pool + prefix cache.

    Returns ``(blocks_installed, tokens_attachable)`` where the token
    count covers the whole chain the target now holds (payload blocks
    plus any chain prefix it already cached from earlier traffic). A
    ``(0, 0)`` return means the handoff degraded to recompute — never
    an error.

    Must run on the thread that owns ``engine`` (the replica pump): it
    mutates the pool array and the cache registry."""
    cache = getattr(engine.kv_cache, "prefix_cache", None)
    if cache is None or handoff is None or not handoff.keys:
        return (0, 0)
    kvc = engine.kv_cache
    dst_bits = getattr(kvc, "quant_bits", None)
    # geometry on the LOGICAL layout — an int4-packed payload halves the
    # stored head_dim, a quantized destination pool is int8 either way
    if (handoff.block_size != cache.block_size
            or handoff.block_data.shape[0] != kvc.data.shape[0]
            or handoff.block_data.shape[2:5] != kvc.data.shape[2:5]
            or handoff.head_dim != kvc.config.head_dim):
        return (0, 0)  # geometry mismatch: heterogeneous fleet, recompute
    if handoff.src_quant_bits != dst_bits:
        from deepspeed_tpu.observability.quant_stats import warn_once

        warn_once(
            f"handoff_precision:{handoff.src_quant_bits}->{dst_bits}",
            "disagg handoff precision mismatch: source pool "
            f"quant_bits={handoff.src_quant_bits} feeding destination "
            f"quant_bits={dst_bits} — every transfer pays a "
            "quantize/dequantize conversion on install; align "
            "kv_quant_bits across the fleet (or set handoff_wire) to "
            "make the wire format match the pools")
    # the target may already hold a chain prefix (shared system prompt
    # traffic): install only past the longest cached prefix — suffix
    # keys without their predecessors would be unreachable by lookup
    pos = 0
    while pos < len(handoff.keys) and cache.get(handoff.keys[pos]) is not None:
        pos += 1
    to_install = list(range(pos, len(handoff.keys)))
    if not to_install:
        return (0, handoff.n_tokens)
    need = len(to_install)
    if kvc.free_blocks < need:
        kvc.reclaim(need - kvc.free_blocks)
    if kvc.free_blocks < need:
        # pool under live pressure: installing would evict working-set
        # blocks of running decodes — degrade to recompute instead
        return (0, pos * handoff.block_size)

    import jax.numpy as jnp

    blocks = kvc.allocator.allocate(need)
    bidx = jnp.asarray(blocks)
    sel = handoff.block_data[:, to_install]
    ssel = (None if handoff.scales is None
            else jnp.asarray(handoff.scales[:, to_install], jnp.float32))
    q, s = _pool_convert(kvc, jnp.asarray(sel), ssel,
                         handoff.wire_bits, handoff.packed)
    kvc.data = kvc.data.at[:, bidx].set(q)
    if s is not None:
        kvc.scales = kvc.scales.at[:, bidx].set(s)
    installed: List[str] = []
    for idx, blk in zip(to_install, blocks):
        if cache.register(handoff.keys[idx], int(blk)):
            installed.append(handoff.keys[idx])
        else:  # registered concurrently under another block: keep theirs
            kvc.free([int(blk)])
    # drop the registration ref: the chain parks idle-cached, exactly
    # like a released prompt — attach_prefix revives it by content hash
    # and KV pressure can evict it, so an unused handoff costs nothing
    cache.unref(installed)
    hub = getattr(engine, "_hub", None)
    if hub is not None and installed:
        lbl = getattr(engine, "_metric_labels", None)
        hub.counter_add("serve.handoff_blocks", len(installed), labels=lbl)
        hub.counter_add("serve.handoff_tokens",
                        len(installed) * handoff.block_size, labels=lbl)
    if installed:
        _record_wire(engine, handoff, "install")
    return (len(installed), handoff.n_tokens)


# -- live session migration (ISSUE 20) -----------------------------------


@dataclasses.dataclass
class SessionHandoff:
    """A full mid-stream decode session on the wire: the committed KV
    blocks (partial tail block included) in the same codec as
    :class:`KVHandoff`, plus the descriptor state that resumes decode on
    the target — generated tokens, budgets, and the per-request
    spec-acceptance EWMA. Unlike a prefix handoff there is no chain-key
    addressing: the blocks belong to ONE sequence and install by block
    write, not cache registration."""

    uid: int
    input_tokens: np.ndarray
    generated: List[int]
    seen_tokens: int
    max_new_tokens: int
    prior_generated: int
    block_data: np.ndarray            # [L, n_blocks, bs, 2, H, W]
    block_size: int
    scales: Optional[np.ndarray] = None
    wire_bits: Optional[Any] = None   # None = full precision; 4/8/"fp8"
    packed: bool = False              # int4 nibble packing along head_dim
    src_quant_bits: Optional[Any] = None
    wire_snr_db: Optional[float] = None
    spec_accept_ewma: Optional[float] = None

    @property
    def n_blocks(self) -> int:
        return int(self.block_data.shape[1])

    @property
    def head_dim(self) -> int:
        hd = self.block_data.shape[-1]
        return hd * 2 if self.packed else hd

    @property
    def wire_nbytes(self) -> int:
        n = int(self.block_data.nbytes)
        if self.scales is not None:
            n += int(self.scales.nbytes)
        return n

    @property
    def logical_nbytes(self) -> int:
        if self.wire_bits is None:
            return int(self.block_data.nbytes)
        return int(np.prod(self.block_data.shape[:-1])) * self.head_dim * 2


def serialize_session(engine, uid: int,
                      wire: Optional[str] = None
                      ) -> Optional[SessionHandoff]:
    """Destructively capture ``uid``'s live decode state from ``engine``
    for migration (engine.migrate_out_session owns the capture: the
    sequence — or its host-tier parked copy — is RELEASED). The KV
    payload rides the same quantized wire as a prefix handoff (``wire``
    from :data:`WIRE_MODES`, defaulting to the engine's ``handoff_wire``
    knob; a quantized pool ships its native payload as-is). Returns None
    when nothing warm exists to capture — the caller degrades to the
    legacy fold-and-resubmit recompute path."""
    wire = wire or getattr(engine, "_handoff_wire", "auto") or "auto"
    if wire not in WIRE_MODES:
        raise ValueError(f"handoff wire mode {wire!r} "
                         f"(choose from {WIRE_MODES})")
    cap = engine.migrate_out_session(uid)
    if cap is None:
        return None
    src_bits = getattr(engine.kv_cache, "quant_bits", None)
    data, scales, wire_bits, packed, wire_snr = _wire_quantize(
        cap["payload"], cap["scales"], src_bits, wire)
    sess = SessionHandoff(
        uid=cap["uid"], input_tokens=cap["input_tokens"],
        generated=cap["generated"], seen_tokens=cap["seen_tokens"],
        max_new_tokens=cap["max_new_tokens"],
        prior_generated=cap["prior_generated"],
        block_data=data, block_size=engine.kv_cache.config.block_size,
        scales=scales, wire_bits=wire_bits, packed=packed,
        src_quant_bits=src_bits, wire_snr_db=wire_snr,
        spec_accept_ewma=cap["spec_accept_ewma"])
    _record_wire(engine, sess, "serialize_session")
    return sess


def install_session(engine, sess: Optional[SessionHandoff]) -> str:
    """Install a migrated session into ``engine`` and resume it. The
    graceful-degradation ladder (never an error, never a drop):

    * ``"resumed"``   — warm: blocks converted to the pool's native
      format and written; decode continues with zero re-prefill FLOPs;
    * ``"paged"``     — target HBM full: warm bytes park in the host
      tier, readmission warm-resumes later (still zero re-prefill);
    * ``"recompute"`` — geometry mismatch / unknown wire / no payload /
      no tier room: the folded token history queues for ordinary
      prefix-recompute admission;
    * ``"duplicate"`` / ``"truncated"`` — see
      ``engine.install_migrated_session``.

    Must run on the thread that owns ``engine`` (the replica pump)."""
    if sess is None:
        return "recompute"
    from deepspeed_tpu.inference.ragged.kv_tier import PagedSession

    kvc = engine.kv_cache
    dst_bits = getattr(kvc, "quant_bits", None)
    pool_payload = pool_scales = None
    geometry_ok = (
        sess.block_data is not None and sess.n_blocks > 0
        and sess.block_size == kvc.config.block_size
        and sess.block_data.shape[0] == kvc.data.shape[0]
        and sess.block_data.shape[2:5] == kvc.data.shape[2:5]
        and sess.head_dim == kvc.config.head_dim
        and sess.wire_bits in (None, 4, 8, "fp8"))
    if geometry_ok:
        if sess.src_quant_bits != dst_bits:
            from deepspeed_tpu.observability.quant_stats import warn_once

            warn_once(
                f"handoff_precision:{sess.src_quant_bits}->{dst_bits}",
                "disagg handoff precision mismatch: source pool "
                f"quant_bits={sess.src_quant_bits} feeding destination "
                f"quant_bits={dst_bits} — every transfer pays a "
                "quantize/dequantize conversion on install; align "
                "kv_quant_bits across the fleet (or set handoff_wire) "
                "to make the wire format match the pools")
        import jax.numpy as jnp

        q, s = _pool_convert(
            kvc, jnp.asarray(sess.block_data),
            None if sess.scales is None
            else jnp.asarray(sess.scales, jnp.float32),
            sess.wire_bits, sess.packed)
        pool_payload = np.asarray(q)
        pool_scales = None if s is None else np.asarray(s, np.float32)
    paged = PagedSession(
        uid=sess.uid,
        input_tokens=np.asarray(sess.input_tokens, np.int32),
        generated=list(sess.generated),
        seen_tokens=int(sess.seen_tokens),
        max_new_tokens=int(sess.max_new_tokens),
        prior_generated=int(sess.prior_generated),
        payload=pool_payload, scales=pool_scales,
        spec_accept_ewma=sess.spec_accept_ewma)
    rung = engine.install_migrated_session(paged)
    if rung in ("resumed", "paged"):
        _record_wire(engine, sess, "install_session")
    return rung
