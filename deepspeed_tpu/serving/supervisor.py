"""Process supervisor: real replica processes behind the same router.

:class:`RemoteReplica` is the router-side stub for a replica living in
another process — it satisfies the exact surface the in-process
:class:`ServingReplica` exposes (``submit``/``load_report``/
``load_score``/``alive``/``serialize_handoff``/``engine.tracer``), so
:class:`FleetRouter` routes, hands off, and fails over without knowing
which side of a socket each replica is on. What changes is *where*
things run: emissions arrive on the supervisor's per-replica receive
threads instead of pump threads, and the KV-serialize step of a
disaggregated handoff becomes an async request/reply (the continuation
passed to ``serialize_handoff`` fires when the payload message lands).

:class:`ReplicaSupervisor` owns the process lifecycle:

* **spawn** — write a worker spec, fork ``python -m
  deepspeed_tpu.serving.proc_worker``, wait for the ready file, connect
  (with backoff — the connect races worker startup), start the receive
  thread, and hand the ``RemoteReplica`` to the router;
* **restart** — a worker that exits without being asked to is a crash:
  the stub is marked failed (so the router's next health check declares
  it dead and resubmits its in-flight requests — the zero-drop failover
  path, unchanged), and a replacement spawns under a *new* replica id.
  Replacements inherit the crashed worker's *lineage*: repeat restarts
  back off exponentially (``restart_policy``, a resilience RetryPolicy),
  and a lineage crashing more than ``max_restarts_per_window`` times
  inside ``restart_window_s`` trips the circuit breaker — it is
  **quarantined** (recorded in the decision history, never respawned;
  replacing its capacity becomes the autoscale signal's job) instead of
  being restarted unboundedly. ``drain`` refuses to shrink the fleet
  below ``min_healthy`` live workers (``drain_refused`` in the act log);
* **autoscale acts** — the PR 10 signal stops being metrics-only: when
  ``desired`` exceeds the live count the supervisor spins up, when it
  drops below it picks a victim, stops new admissions
  (``router.remove_replica``), and sends ``drain`` — the worker
  finishes its in-flight work and exits 0. Every act is recorded into
  the autoscale decision history next to the desires that caused it.

Every worker publishes its load report both over the channel (routing)
and through ``ReplicaPublisher`` into ``<run_dir>/replicas/`` —
:meth:`write_fleet_snapshot` merges channel-side state into
``<run_dir>/fleet_snapshot.json`` for ``serve_top --fleet``.

Clock note: worker-side wall timestamps (load-report ``ts``, trace
spans) are rebased into the supervisor's clock domain via the
per-channel NTP-style offset estimator
(observability/clocksync.ClockSyncEstimator, attached to each channel
at spawn, re-synced by :meth:`ReplicaSupervisor.maintain`). With
``clock_sync=False`` — or before an estimator has its minimum sample
count — the raw timestamps pass through untouched, bit-exact with the
pre-clocksync behavior that assumed localhost's shared ``time.time()``.
Liveness never depends on wall clocks either way: heartbeat ages use
``time.monotonic()`` on the supervisor side only.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.observability.clocksync import wall_time
from deepspeed_tpu.observability.journal import get_journal
from deepspeed_tpu.serving.replica import Submission
from deepspeed_tpu.serving.transport import (ChannelError, FileChannel,
                                             connect_with_backoff,
                                             decode_handoff, decode_session,
                                             encode_handoff, encode_session)


_WARNED_LEGACY_CONNECT = False


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _KVConfigView:
    def __init__(self, block_size: int):
        self.block_size = int(block_size)


class _KVAllocatorView:
    def __init__(self, total_blocks: int):
        self.total_blocks = int(total_blocks)


class _KVCacheView:
    """Just enough KV-cache geometry for the router's admission math
    (``_check_fits``/``_affinity_key``) — numbers from the worker's
    first report, never the blocks themselves."""

    def __init__(self, block_size: int, total_blocks: int):
        self.config = _KVConfigView(block_size)
        self.allocator = _KVAllocatorView(total_blocks)

    def blocks_needed(self, n_tokens: int) -> int:
        bs = self.config.block_size
        return (int(n_tokens) + bs - 1) // bs


class RemoteEngineView:
    """The router touches ``replica.engine`` for exactly two things:
    KV geometry and the tracer. This view provides both — the tracer is
    a real :class:`RequestTracer` fed from the worker's shipped trace
    dicts, so fleet SLO attribution and Perfetto export work unchanged
    across the process boundary."""

    def __init__(self, block_size: int, total_blocks: int,
                 max_blocks_per_seq: int):
        from deepspeed_tpu.observability.request_trace import RequestTracer

        self.kv_cache = _KVCacheView(block_size, total_blocks)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.tracer = RequestTracer(enabled=True, sample_rate=1.0)
        # per-channel ClockSyncEstimator + domain label, set by the
        # owning RemoteReplica; None means ingest raw (bit-exact)
        self.clock = None
        self.clock_domain: Optional[str] = None

    def update_geometry(self, geo: Dict[str, Any]) -> None:
        self.kv_cache.config.block_size = int(geo["block_size"])
        self.kv_cache.allocator.total_blocks = int(geo["total_blocks"])
        self.max_blocks_per_seq = int(geo["max_blocks_per_seq"])

    def ingest_traces(self, docs: List[Dict[str, Any]]) -> None:
        from deepspeed_tpu.observability.request_trace import RequestTrace

        clk = self.clock
        rebase = clk is not None and clk.synced
        if rebase:
            # one estimate per batch: spans from one emit must land in
            # one coherent shift, not straddle a mid-batch re-sync
            off, unc = clk.offset_s, clk.uncertainty_s
        t = self.tracer
        with t._lock:
            for d in docs:
                tr = RequestTrace.from_dict(d)
                if rebase:
                    tr.rebase(off, unc, domain=self.clock_domain)
                t._ring.append(tr)
                t.stats["finished"] += 1
                t.stats["kept"] += 1
                if t.alerter is not None:
                    t.alerter.observe_trace(tr)


def _empty_report(replica_id: int, role: str) -> Dict[str, Any]:
    return {"replica": replica_id, "role": role, "ts": 0.0, "steps": 0,
            "queue_wait_depth": 0, "live_seqs": 0, "inflight": 0,
            "kv_free_blocks": 0, "kv_free_frac": 1.0,
            "goodput_tokens_per_s": 0.0, "killed": False,
            "kv_quant_bits": None, "handoff_wire": "auto",
            "handoff_wire_bytes": 0, "handoff_logical_bytes": 0,
            "kv_wire_snr_db": None}


class RemoteReplica:
    """Router-side stub for one worker process."""

    def __init__(self, replica_id: int, role: str, channel,
                 block_size: int, total_blocks: int,
                 max_blocks_per_seq: int,
                 handoff_timeout_s: float = 15.0):
        self.replica_id = int(replica_id)
        self.name = f"r{self.replica_id}"
        self.role = role
        self.channel = channel
        self.engine = RemoteEngineView(block_size, total_blocks,
                                       max_blocks_per_seq)
        # the channel's ClockSyncEstimator (attached by the supervisor
        # before construction when clock_sync is on; the channel layer
        # defaults it to None) drives trace/report rebasing
        self.engine.clock = getattr(channel, "clock", None)
        self.engine.clock_domain = self.name
        # FleetMetricsPlane fed by the metrics the worker piggybacks on
        # heartbeats (set by the supervisor; None drops them)
        self.metrics_plane = None
        self.emit_callback: Optional[Callable] = None
        self.killed = False
        self.draining = False
        self.exited = False  # worker announced a clean drain-exit
        self._send_failed = False
        # consecutive channel errors; reset by any successful inbound
        # message — the router's health state machine reads this
        self.transport_errors = 0
        self._report = _empty_report(self.replica_id, role)
        self._report_ts = time.time()  # display only (report ts)
        self._report_mono = time.monotonic()  # liveness decisions
        self._sent_submits = 0  # vs the report's received_submits
        self._lock = threading.Lock()
        self._handoff_timeout_s = float(handoff_timeout_s)
        self._handoff_cbs: Dict[int, Tuple[Callable, float]] = {}
        # live-migration + hot-swap RPCs share the handoff timeout/
        # expiry discipline: an orphaned continuation fires with None
        self._migrate_cbs: Dict[int, Tuple[Callable, float]] = {}
        self._reload_cbs: Dict[int, Tuple[Callable, float]] = {}
        self._next_req = 0

    # -- the ServingReplica surface ------------------------------------
    def heartbeat_age(self, now: Optional[float] = None) -> float:
        """Seconds since the last inbound report, on the *monotonic*
        clock — a stepped wall clock must never fail a healthy worker
        over. ``now``, when given, is a ``time.monotonic()`` stamp."""
        now = time.monotonic() if now is None else now
        return now - self._report_mono

    def alive(self, now: Optional[float] = None,
              stale_after: float = 5.0) -> bool:
        """Liveness = recent heartbeat over a working channel. A dead
        worker stops reporting; a broken channel flips ``_send_failed``
        immediately — either way the router's health check fails the
        replica over without waiting on process state. ``now`` is
        monotonic (see heartbeat_age)."""
        if self._send_failed:
            return False
        return self.heartbeat_age(now) < stale_after

    def _unacked(self, r: Dict[str, Any]) -> int:
        """Submissions on the wire the worker's report can't see yet.
        Monotone counters on both sides (sent here, received in the
        report) — a report generated *before* a submission landed
        cannot erase the pending window the way a reset-on-report
        scheme would. Caller holds the lock."""
        return max(0, self._sent_submits
                   - int(r.get("received_submits", 0)))

    def load_report(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Last heartbeat report, with ``inflight`` bumped by the
        unacked sends — the worker can't see them yet, but the router's
        TTFT predictor must, or every submit inside one heartbeat
        window reads the same stale depth and piles onto a single
        worker."""
        with self._lock:
            r = dict(self._report)
            r["inflight"] = int(r.get("inflight", 0)) + self._unacked(r)
            return r

    def load_score(self) -> float:
        """Same cost shape as the local replica, plus the unacked
        in-flight window."""
        with self._lock:
            r = self._report
            return (r["queue_wait_depth"] + r["live_seqs"]
                    + self._unacked(r) + (1.0 - r["kv_free_frac"]))

    def submit(self, sub: Submission) -> None:
        if sub.session is not None:
            # live migration install: the SessionHandoff rides its own
            # message type; tokens carry the recompute fallback the
            # worker degrades to if the payload can't land
            msg = {"type": "install_session", "uid": int(sub.uid),
                   "tokens": np.asarray(sub.tokens, np.int32),
                   "max_new_tokens": int(sub.max_new_tokens),
                   "span_notes": [[k, dict(f)]
                                  for k, f in sub.span_notes],
                   "session": encode_session(sub.session)}
        else:
            msg = {"type": "submit", "uid": int(sub.uid),
                   "tokens": np.asarray(sub.tokens, np.int32),
                   "max_new_tokens": int(sub.max_new_tokens),
                   "span_notes": [[k, dict(f)]
                                  for k, f in sub.span_notes],
                   "handoff": encode_handoff(sub.handoff)}
        try:
            self.channel.send(msg)
        except ChannelError:
            # the stale-heartbeat path will resubmit this request
            # elsewhere; losing the send is exactly a replica crash
            self.transport_errors += 1
            self._send_failed = True
            return
        with self._lock:
            self._sent_submits += 1

    def serialize_handoff(self, tokens: np.ndarray,
                          cb: Callable[[Optional[Any]], None]) -> None:
        """Async serialize RPC: the reply (``handoff_payload``) invokes
        ``cb`` on the receive thread; a dead channel or an expired wait
        degrades to ``cb(None)`` — the install side's recompute path."""
        with self._lock:
            req = self._next_req
            self._next_req += 1
            self._handoff_cbs[req] = (
                cb, time.monotonic() + self._handoff_timeout_s)
        try:
            self.channel.send({"type": "serialize", "req": req,
                               "tokens": np.asarray(tokens, np.int32)})
        except ChannelError:
            self.transport_errors += 1
            self._send_failed = True
            with self._lock:
                self._handoff_cbs.pop(req, None)
            cb(None)

    def migrate_out(self, uid: int,
                    cb: Callable[[Optional[Any]], None],
                    wire: Optional[str] = None) -> None:
        """Async live-migration capture RPC: the worker captures and
        releases session ``uid``'s full decode state; the reply
        (``session_payload``) invokes ``cb`` on the receive thread. A
        dead channel or an expired wait degrades to ``cb(None)`` — the
        router's fold-and-resubmit recompute path. Channel FIFO
        guarantees every emission the session produced arrives before
        the capture, so the caller's folded tokens are complete."""
        with self._lock:
            req = self._next_req
            self._next_req += 1
            self._migrate_cbs[req] = (
                cb, time.monotonic() + self._handoff_timeout_s)
        try:
            self.channel.send({"type": "migrate_out", "req": req,
                               "uid": int(uid), "wire": wire})
        except ChannelError:
            self.transport_errors += 1
            self._send_failed = True
            with self._lock:
                self._migrate_cbs.pop(req, None)
            cb(None)

    def reload(self, cb: Callable[[Optional[Dict[str, Any]]], None],
               ckpt_dir: Optional[str] = None,
               seed: Optional[int] = None,
               timeout_s: Optional[float] = None) -> None:
        """Async weight hot-swap RPC: the worker validates the
        checkpoint manifest, reloads params, runs the canary prompt
        set, and replies ``reload_done`` (which invokes ``cb`` with the
        reply dict). ``cb(None)`` = channel death or timeout — the
        rolling-swap driver treats it like a failed parity gate."""
        with self._lock:
            req = self._next_req
            self._next_req += 1
            self._reload_cbs[req] = (
                cb, time.monotonic()
                + float(timeout_s or self._handoff_timeout_s))
        try:
            self.channel.send({"type": "reload", "req": req,
                               "ckpt_dir": ckpt_dir, "seed": seed})
        except ChannelError:
            self.transport_errors += 1
            self._send_failed = True
            with self._lock:
                self._reload_cbs.pop(req, None)
            cb(None)

    def transport_bytes(self) -> Tuple[int, int]:
        return (int(self.channel.bytes_sent),
                int(self.channel.bytes_received))

    def clock_info(self) -> Optional[Dict[str, Any]]:
        """The channel clock estimate (None with clock sync off)."""
        clk = getattr(self.channel, "clock", None)
        return clk.to_dict() if clk is not None else None

    def kill(self) -> None:
        self.killed = True

    def pump(self, eos_token_id=None) -> Dict[int, List[int]]:
        return {}  # the worker pumps itself

    def start(self, **kw) -> None:
        pass

    def stop(self) -> None:
        pass

    # -- receive path (supervisor rx thread) ---------------------------
    def handle_message(self, msg: Dict[str, Any]) -> None:
        kind = msg.get("type")
        if kind == "emit":
            rep = dict(msg.get("report") or self._report)
            clk = getattr(self.channel, "clock", None)
            if clk is not None and clk.synced and rep.get("ts"):
                # worker wall time -> supervisor wall time; the raw
                # stamp survives as ts_worker for cross-checks. With
                # clock sync off/unsynced the dict is untouched.
                rep["ts_worker"] = rep["ts"]
                rep["ts"] = clk.rebase(rep["ts"])
            with self._lock:
                self._report = rep
                self._report_ts = time.time()
                self._report_mono = time.monotonic()
            self.transport_errors = 0  # channel demonstrably works
            metrics = msg.get("metrics")
            if metrics and self.metrics_plane is not None:
                self.metrics_plane.ingest(self.name, metrics)
            geo = msg.get("geometry")
            if geo:
                self.engine.update_geometry(geo)
            traces = msg.get("traces")
            if traces:
                self.engine.ingest_traces(traces)
            emitted = {int(u): [int(t) for t in toks]
                       for u, toks in (msg.get("emitted") or {}).items()}
            if emitted and self.emit_callback is not None:
                self.emit_callback(self, emitted)
        elif kind == "handoff_payload":
            with self._lock:
                entry = self._handoff_cbs.pop(int(msg["req"]), None)
            if entry is not None:
                entry[0](decode_handoff(msg.get("handoff")))
        elif kind == "session_payload":
            with self._lock:
                entry = self._migrate_cbs.pop(int(msg["req"]), None)
            if entry is not None:
                entry[0](decode_session(msg.get("session")))
        elif kind == "reload_done":
            with self._lock:
                entry = self._reload_cbs.pop(int(msg["req"]), None)
            if entry is not None:
                entry[0](msg)
        elif kind == "exiting":
            self.exited = True

    def expire_handoffs(self, now: Optional[float] = None) -> int:
        """Time out serialize/migrate/reload RPCs whose worker died
        mid-reply: each orphaned continuation fires with None (the
        caller's documented degraded path — recompute for handoffs and
        migrations, swap-abort for reloads). ``now`` is monotonic.
        Returns how many expired."""
        now = time.monotonic() if now is None else now
        expired = []
        with self._lock:
            for cbs in (self._handoff_cbs, self._migrate_cbs,
                        self._reload_cbs):
                for req, (cb, deadline) in list(cbs.items()):
                    if now >= deadline:
                        expired.append(cb)
                        del cbs[req]
        for cb in expired:
            cb(None)
        return len(expired)


class ReplicaSupervisor:
    """Spawns, connects, restarts, and scales worker processes.

    Construction fixes the fleet-wide spec (model, engine keywords,
    channel kind, seed); :meth:`spawn` instantiates workers from it.
    Attach the router after building it from the spawned stubs —
    :meth:`maintain` needs it for add/remove and the autoscale signal.
    """

    def __init__(self, run_dir: str,
                 model: Optional[Dict[str, Any]] = None,
                 engine: Optional[Dict[str, Any]] = None,
                 channel: str = "socket",
                 seed: int = 0,
                 eos_token_id: Optional[int] = None,
                 heartbeat_s: float = 0.05,
                 max_frame_mb: int = 64,
                 connect_retries: int = 40,
                 connect_backoff_s: float = 0.05,
                 spawn_timeout_s: float = 60.0,
                 default_role: str = "unified",
                 jax_platform: str = "cpu",
                 python: Optional[str] = None,
                 connect_policy=None,
                 restart_policy=None,
                 max_restarts_per_window: int = 3,
                 restart_window_s: float = 30.0,
                 min_healthy: int = 1,
                 clock_sync: bool = True,
                 clock_sync_rounds: int = 8,
                 clock_resync_s: float = 5.0):
        if channel not in ("socket", "file"):
            raise ValueError(
                f"channel must be socket|file, got {channel!r}")
        self.run_dir = run_dir
        self.model = dict(model or {"name": "tiny"})
        self.engine = dict(engine or {})
        self.channel_kind = channel
        self.seed = int(seed)
        self.eos_token_id = eos_token_id
        self.heartbeat_s = float(heartbeat_s)
        self.max_frame_mb = int(max_frame_mb)
        self.connect_retries = int(connect_retries)
        self.connect_backoff_s = float(connect_backoff_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.default_role = default_role
        self.jax_platform = jax_platform
        self.python = python or sys.executable
        self.router = None  # attach after building FleetRouter
        self.replicas: Dict[int, RemoteReplica] = {}
        self._procs: Dict[int, subprocess.Popen] = {}
        self._rx_threads: Dict[int, threading.Thread] = {}
        self._rx_stop: Dict[int, threading.Event] = {}
        self._next_id = 0
        # (ts, action, replica_id) —
        # spawn | restart | drain | quarantine | drain_refused
        self.actions: List[Tuple[float, str, int]] = []
        # crash-loop containment (see class docstring)
        from deepspeed_tpu.resilience.policy import RetryPolicy
        self.connect_policy = connect_policy
        if connect_policy is None and (
                int(connect_retries) != 40
                or float(connect_backoff_s) != 0.05):
            global _WARNED_LEGACY_CONNECT
            if not _WARNED_LEGACY_CONNECT:
                _WARNED_LEGACY_CONNECT = True
                import warnings
                warnings.warn(
                    "connect_retries/connect_backoff_s are legacy "
                    "aliases; pass connect_policy= (a resilience "
                    "RetryPolicy, e.g. RouterConfig."
                    "connect_retry_policy()) instead",
                    DeprecationWarning, stacklevel=2)
        # jitter=0: restart timing must be deterministic for the chaos
        # gates (and drift does nothing useful on a single host)
        self.restart_policy = restart_policy or RetryPolicy(
            max_retries=max(1, int(max_restarts_per_window)),
            backoff_base_s=0.25, backoff_max_s=5.0, jitter=0.0)
        self.max_restarts_per_window = int(max_restarts_per_window)
        self.restart_window_s = float(restart_window_s)
        self.min_healthy = max(1, int(min_healthy))
        # rid -> lineage id (the first spawn's rid, carried through
        # restarts so the breaker sees one crash-looping identity)
        self._lineage: Dict[int, int] = {}
        self._lineage_crashes: Dict[int, List[float]] = {}  # monotonic
        self.quarantined: set = set()  # lineage ids
        self._pending_restarts: List[Dict[str, Any]] = []
        # spawn-time knobs remembered so restarts reproduce the worker
        # (env carries e.g. the DSTPU_CHAOS spec of a chaos drill)
        self._env_extra: Dict[int, Dict[str, str]] = {}
        self._step_delay: Dict[int, float] = {}
        # fleet observability: per-channel clock sync + the transport-
        # borne metrics plane (no shared filesystem required)
        self.clock_sync = bool(clock_sync)
        self.clock_sync_rounds = max(1, int(clock_sync_rounds))
        self.clock_resync_s = float(clock_resync_s)
        from deepspeed_tpu.observability.fleet_metrics import \
            FleetMetricsPlane
        self.metrics_plane = FleetMetricsPlane(
            stale_after_s=max(1.0, 20.0 * self.heartbeat_s))
        for sub in ("specs", "ready", "logs", "spool", "replicas"):
            os.makedirs(os.path.join(run_dir, sub), exist_ok=True)

    # -- geometry defaults (valid before the first worker report) ------
    def _engine_geometry(self) -> Tuple[int, int, int]:
        block_size = int(self.engine.get("kv_block_size", 16))
        total = int(self.engine.get("kv_blocks", 256))
        max_per_seq = int(self.engine.get("max_blocks_per_seq",
                                          total))
        return block_size, total, max_per_seq

    # -- act log + black box -------------------------------------------
    def _act(self, action: str, replica_id: int,
             now: Optional[float] = None, **fields: Any) -> None:
        """One supervisor act: appended to the in-memory decision
        history (the fleet snapshot's ``supervisor.actions``) and, when
        the black box is recording, journaled as a SUPERVISOR decision
        with the state that triggered it."""
        now = wall_time() if now is None else now
        self.actions.append((now, action, replica_id))
        jr = get_journal()
        if jr is not None:
            jr.decision("SUPERVISOR", ts=now, action=action,
                        replica=replica_id, **fields)

    # -- spawn ---------------------------------------------------------
    def spawn(self, role: Optional[str] = None,
              replica_id: Optional[int] = None,
              step_delay_ms: float = 0.0,
              env_extra: Optional[Dict[str, str]] = None,
              action: str = "spawn",
              lineage: Optional[int] = None) -> RemoteReplica:
        rid = self._next_id if replica_id is None else int(replica_id)
        self._next_id = max(self._next_id, rid + 1)
        role = role or self.default_role
        self._lineage[rid] = rid if lineage is None else int(lineage)
        self._env_extra[rid] = dict(env_extra or {})
        self._step_delay[rid] = float(step_delay_ms)
        spool = os.path.join(self.run_dir, "spool", f"replica_{rid}")
        ready = os.path.join(self.run_dir, "ready",
                             f"replica_{rid}.json")
        if os.path.exists(ready):
            os.unlink(ready)
        spec = {
            "replica_id": rid, "role": role, "run_dir": self.run_dir,
            "ready_path": ready, "channel": self.channel_kind,
            "spool_dir": spool, "max_frame_mb": self.max_frame_mb,
            "model": self.model, "engine": self.engine,
            "seed": self.seed, "eos_token_id": self.eos_token_id,
            "step_delay_ms": float(step_delay_ms),
            "heartbeat_s": self.heartbeat_s,
            "jax_platform": self.jax_platform,
        }
        spec_path = os.path.join(self.run_dir, "specs",
                                 f"replica_{rid}.json")
        _atomic_write_json(spec_path, spec)
        env = dict(os.environ)
        env.update(env_extra or {})
        log_path = os.path.join(self.run_dir, "logs",
                                f"replica_{rid}.log")
        log = open(log_path, "ab")
        proc = subprocess.Popen(
            [self.python, "-m", "deepspeed_tpu.serving.proc_worker",
             spec_path],
            stdout=log, stderr=subprocess.STDOUT, env=env)
        log.close()
        try:
            chan = self._connect(proc, ready, spool, rid)
        except Exception:
            proc.kill()
            raise
        if self.clock_sync:
            from deepspeed_tpu.observability.clocksync import \
                ClockSyncEstimator
            chan.clock = ClockSyncEstimator()
        bs, total, mps = self._engine_geometry()
        remote = RemoteReplica(rid, role, chan, bs, total, mps)
        remote.metrics_plane = self.metrics_plane
        self.replicas[rid] = remote
        self._procs[rid] = proc
        self._start_rx(remote)
        if self.clock_sync:
            # initial burst: the estimator is synced (min_samples) well
            # before the first routed request; pongs land on the rx
            # thread just started above
            for _ in range(self.clock_sync_rounds):
                try:
                    chan.ping_clock()
                except ChannelError:
                    break
        self._act(action, rid, role=role,
                  lineage=self._lineage.get(rid, rid))
        return remote

    def _connect(self, proc: subprocess.Popen, ready_path: str,
                 spool: str, rid: int):
        deadline = time.monotonic() + self.spawn_timeout_s
        while not os.path.exists(ready_path):
            if proc.poll() is not None:
                raise ChannelError(
                    f"worker exited with {proc.returncode} before "
                    f"publishing its ready file (see logs/)")
            if time.monotonic() >= deadline:
                raise ChannelError(
                    f"worker not ready within {self.spawn_timeout_s}s")
            time.sleep(0.01)
        with open(ready_path) as f:
            ready = json.load(f)
        max_frame = self.max_frame_mb << 20
        if ready.get("channel") == "socket":
            return connect_with_backoff(
                "127.0.0.1", int(ready["port"]),
                retries=self.connect_retries,
                backoff_s=self.connect_backoff_s,
                max_frame_bytes=max_frame,
                policy=self.connect_policy, peer_id=rid)
        return FileChannel(spool, side="a", max_frame_bytes=max_frame,
                           peer_id=rid)

    def _start_rx(self, remote: RemoteReplica) -> None:
        stop = threading.Event()

        def _loop():
            while not stop.is_set():
                try:
                    msg = remote.channel.recv(timeout=0.1)
                except ChannelError:
                    remote.transport_errors += 1
                    remote._send_failed = True
                    return
                if msg is not None:
                    remote.handle_message(msg)

        t = threading.Thread(target=_loop, daemon=True,
                             name=f"rx-{remote.name}")
        t.start()
        self._rx_threads[remote.replica_id] = t
        self._rx_stop[remote.replica_id] = stop

    # -- lifecycle -----------------------------------------------------
    def _live_ids(self) -> List[int]:
        return [rid for rid, r in self.replicas.items()
                if not r.draining and not r.exited
                and self._procs[rid].poll() is None]

    def maintain(self, now: Optional[float] = None) -> Dict[str, int]:
        """One supervision round: contain crashes (failover now,
        restart after backoff, quarantine a crash-looper), act on the
        autoscale signal, expire orphaned handoff RPCs, refresh the
        merged fleet snapshot. Call it from the serving loop at
        health-check cadence. ``now`` (wall clock) stamps the decision
        history only — scheduling runs on the monotonic clock. Returns
        counts of the actions taken."""
        now = wall_time() if now is None else now
        mono = time.monotonic()
        acted = {"restarted": 0, "spawned": 0, "drained": 0,
                 "quarantined": 0, "handoffs_expired": 0}
        autoscale = getattr(self.router, "autoscale", None) \
            if self.router is not None else None

        if self.clock_sync:
            # periodic re-sync: drift and NTP steps on the worker side
            # show up within one resync period, not at the next spawn
            for remote in self.replicas.values():
                clk = getattr(remote.channel, "clock", None)
                if (clk is None or remote._send_failed
                        or remote.draining or remote.exited):
                    continue
                if mono - clk.last_sync_mono >= self.clock_resync_s:
                    try:
                        remote.channel.ping_clock()
                    except ChannelError:
                        remote.transport_errors += 1
                        remote._send_failed = True

        for rid in list(self.replicas):
            remote = self.replicas[rid]
            proc = self._procs[rid]
            if proc.poll() is None:
                acted["handoffs_expired"] += remote.expire_handoffs(mono)
                continue
            if remote.draining or remote.exited:
                continue  # asked to leave; clean exit, nothing to heal
            # crash: fail the stub now (fast failover) — the dead id
            # stays dead, its in-flight work is the router's resubmit
            # problem, not the replacement's
            remote._send_failed = True
            remote.draining = True
            if self.router is not None:
                self.router.check_health()  # declares rid dead
            lineage = self._lineage.get(rid, rid)
            crashes = self._lineage_crashes.setdefault(lineage, [])
            crashes.append(mono)
            crashes[:] = [t for t in crashes
                          if mono - t <= self.restart_window_s]
            attempt = len(crashes)
            if attempt > self.max_restarts_per_window:
                # circuit breaker: this lineage crashes faster than it
                # serves — stop feeding it restarts; the autoscale
                # desired-vs-live path owns replacing its capacity
                if lineage not in self.quarantined:
                    self.quarantined.add(lineage)
                    self._act("quarantine", rid, now, lineage=lineage,
                              crashes_in_window=attempt,
                              window_s=self.restart_window_s)
                    if autoscale is not None:
                        autoscale.record_action("quarantine", rid, now)
                    acted["quarantined"] += 1
                continue
            # first crash restarts immediately (the pre-breaker
            # behavior); repeats back off exponentially
            delay = (0.0 if attempt <= 1
                     else self.restart_policy.backoff_s(attempt - 1))
            self._pending_restarts.append({
                "due_mono": mono + delay, "role": remote.role,
                "lineage": lineage,
                "env": self._env_extra.get(rid) or None,
                "step_delay_ms": self._step_delay.get(rid, 0.0)})

        still_pending = []
        for plan in self._pending_restarts:
            if plan["due_mono"] > time.monotonic():
                still_pending.append(plan)
                continue
            replacement = self.spawn(
                role=plan["role"], action="restart",
                env_extra=plan["env"],
                step_delay_ms=plan["step_delay_ms"],
                lineage=plan["lineage"])
            if self.router is not None:
                self.router.add_replica(replacement)
            if autoscale is not None:
                autoscale.record_action("restart",
                                        replacement.replica_id, now)
            acted["restarted"] += 1
        self._pending_restarts = still_pending

        if autoscale is not None and autoscale.desired is not None:
            live = self._live_ids()
            if autoscale.desired > len(live):
                replacement = self.spawn(action="spawn")
                self.router.add_replica(replacement)
                autoscale.record_action("spawn",
                                        replacement.replica_id, now,
                                        live=len(live) + 1,
                                        direction="up")
                acted["spawned"] += 1
            elif autoscale.desired < len(live) and len(live) > 1:
                victim = self.replicas[max(live)]
                # migration-backed scale-down: the victim's live
                # sessions move warm before the worker drains
                if self.drain(victim.replica_id, reason="scale_down"):
                    st = getattr(self.router, "stats", {})
                    autoscale.record_action(
                        "drain", victim.replica_id, now,
                        live=len(live) - 1, direction="down",
                        migrations=int(st.get("migrations", 0)))
                    acted["drained"] += 1
        self.write_fleet_snapshot()
        return acted

    def drain(self, replica_id: int, migrate: bool = True,
              reason: str = "drain") -> bool:
        """Graceful scale-down: no new admissions, live sessions
        migrate out warm (when the router supports it), the worker
        finishes whatever could not move and exits 0. Refuses (returns
        False, with a ``drain_refused`` act recorded) when draining
        would leave the fleet below its ``min_healthy`` floor.

        Ordering is what makes this zero-drop: remove_replica stops new
        admissions first, migrate_sessions then sends the capture RPCs,
        and the ``drain`` flag goes on the SAME channel afterwards —
        FIFO means the worker processes every capture while still
        serving, and any session the migration ladder left behind is
        simply finished in place before the clean exit."""
        live = len(self._live_ids())
        if live - 1 < self.min_healthy:
            self._act("drain_refused", replica_id, live=live,
                      min_healthy=self.min_healthy)
            return False
        remote = self.replicas[replica_id]
        remote.draining = True
        migrated: Dict[str, int] = {}
        if self.router is not None:
            self.router.remove_replica(replica_id)
            if migrate and hasattr(self.router, "migrate_sessions"):
                migrated = self.router.migrate_sessions(
                    replica_id, reason=reason)
        try:
            remote.channel.send({"type": "drain"})
        except ChannelError:
            remote.transport_errors += 1
            remote._send_failed = True
        self._act("drain", replica_id, **(
            {"migrate": migrated} if migrated else {}))
        return True

    def kill(self, replica_id: int,
             sig: int = signal.SIGKILL) -> None:
        """Hard-kill a worker (chaos drills / tests)."""
        proc = self._procs.get(replica_id)
        if proc is not None and proc.poll() is None:
            proc.send_signal(sig)

    def run_until_drained(self, timeout_s: float = 120.0,
                          poll_s: float = 0.02) -> None:
        """Drive the attached router to completion with supervision:
        the process-fleet analog of ``FleetRouter.drain``."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            self.maintain()
            self.router.check_health()
            if self.router.pending() == 0:
                return
            time.sleep(poll_s)
        raise TimeoutError(
            f"process fleet did not drain in {timeout_s}s "
            f"({self.router.pending()} requests pending)")

    # -- rolling weight hot-swap (ISSUE 20) ----------------------------
    def compute_canary_chains(self, prompts: List[List[int]],
                              gen: int = 8,
                              seed: Optional[int] = None
                              ) -> Dict[str, List[int]]:
        """Expected A/B-parity chains for a canary prompt set: build a
        throwaway replica from the SAME model+engine spec the workers
        use (engine config affects numerics, so it must match), decode
        the canaries greedily, and checksum-chain the streams. The
        publisher bakes these into weights.json; each swapped worker
        must reproduce them before it rejoins."""
        import numpy as np  # noqa: F811 (module-level alias)

        from deepspeed_tpu.observability.journal import chain_tokens
        from deepspeed_tpu.serving.proc_worker import build_replica

        rep = build_replica({"replica_id": 9_999, "role": "unified",
                             "model": self.model, "engine": self.engine,
                             "seed": int(self.seed if seed is None
                                         else seed)})
        eng = rep.engine
        uids = [3_000_000 + i for i in range(len(prompts))]
        eng.put(uids, [np.asarray(p, np.int32) for p in prompts],
                max_new_tokens=int(gen))
        out = eng.generate_all(eos_token_id=self.eos_token_id)
        return {str(i): chain_tokens(out.get(uid, []))
                for i, uid in enumerate(uids)}

    def publish_weights(self, tag: str,
                        seed: Optional[int] = None,
                        canary_prompts: Optional[List[List[int]]] = None,
                        canary_gen: int = 8,
                        canary_chains: Optional[Dict[str, List[int]]]
                        = None) -> str:
        """Publish a weight release the fleet can roll onto:
        ``<run_dir>/weights/<tag>/weights.json`` (seed + canary prompt
        set + expected token chains) sealed by a checksum manifest
        (resilience/manifest.py — a torn or tampered release fails
        validation before any worker touches it). ``canary_chains``
        overrides the computed expectation — tests use it to publish a
        release whose parity gate MUST fail. Returns the release dir."""
        ckpt_dir = os.path.join(self.run_dir, "weights", str(tag))
        os.makedirs(ckpt_dir, exist_ok=True)
        seed = int(self.seed if seed is None else seed)
        canary: Dict[str, Any] = {}
        if canary_prompts:
            if canary_chains is None:
                canary_chains = self.compute_canary_chains(
                    canary_prompts, gen=canary_gen, seed=seed)
            canary = {"prompts": [[int(t) for t in p]
                                  for p in canary_prompts],
                      "gen": int(canary_gen),
                      "chains": {str(k): [int(c) for c in v]
                                 for k, v in canary_chains.items()}}
        _atomic_write_json(os.path.join(ckpt_dir, "weights.json"),
                           {"tag": str(tag), "seed": seed,
                            "canary": canary})
        from deepspeed_tpu.resilience.manifest import write_manifest

        write_manifest(ckpt_dir, str(tag))
        self._act("publish", -1, tag=str(tag), seed=seed,
                  canaries=len(canary_prompts or []))
        return ckpt_dir

    def _reload_sync(self, remote: RemoteReplica,
                     ckpt_dir: Optional[str], seed: Optional[int],
                     timeout_s: float) -> Optional[Dict[str, Any]]:
        """Blocking wrapper over the async reload RPC (None = channel
        death or timeout)."""
        box: Dict[str, Any] = {}
        ev = threading.Event()

        def _cb(reply):
            box["reply"] = reply
            ev.set()

        remote.reload(_cb, ckpt_dir=ckpt_dir, seed=seed,
                      timeout_s=timeout_s)
        ev.wait(timeout_s + 5.0)
        return box.get("reply")

    def _quiesce(self, remote: RemoteReplica, timeout_s: float) -> bool:
        """Wait for a router-removed replica to go empty (live sessions
        migrated or finished, queue drained)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            r = remote.load_report()
            if int(r.get("inflight", 0)) == 0:
                return True
            if remote._send_failed:
                return False
            time.sleep(0.01)
        return False

    def rolling_swap(self, tag: str,
                     timeout_s: float = 60.0) -> Dict[str, Any]:
        """Zero-downtime weight rollout, replica by replica: quiesce
        (admissions off + live sessions migrate out warm) -> reload the
        manifest-validated release -> A/B token-parity gate on the
        published canary chains -> rejoin. A parity failure (or reload
        error / timeout) ABORTS the rollout: the failing replica rolls
        back to the running weights and rejoins, and no further replica
        is touched. The ``min_healthy`` floor is respected throughout —
        at most one replica is ever out of the fleet.

        Only after EVERY replica swaps does ``self.seed`` advance, so
        crash restarts spawn with the new weights; an aborted rollout
        leaves restarts on the old ones — the fleet stays coherent
        either way."""
        from deepspeed_tpu.resilience.manifest import (
            CheckpointCorruptError, validate_manifest)

        jr = get_journal()
        result: Dict[str, Any] = {"tag": str(tag), "swapped": 0,
                                  "rolled_back": 0, "refused": 0,
                                  "aborted": False, "parity_ok": True,
                                  "error": None}

        def _swap_rec(stage: str, rid: int, **fields: Any) -> None:
            if jr is not None:
                jr.decision("SWAP", ts=wall_time(), tag=str(tag),
                            replica=rid, stage=stage, **fields)

        ckpt_dir = os.path.join(self.run_dir, "weights", str(tag))
        try:
            # supervisor-side gate: a torn/tampered release aborts the
            # rollout before any replica is touched
            validate_manifest(ckpt_dir)
            with open(os.path.join(ckpt_dir, "weights.json")) as f:
                wdoc = json.load(f)
        except (CheckpointCorruptError, OSError, ValueError) as exc:
            result["aborted"] = True
            result["error"] = f"{type(exc).__name__}: {exc}"
            _swap_rec("manifest", -1, ok=False, error=result["error"])
            self._act("swap_abort", -1, tag=str(tag),
                      error=result["error"])
            return result
        expected = {str(k): [int(c) for c in v] for k, v in
                    ((wdoc.get("canary") or {}).get("chains")
                     or {}).items()}
        new_seed = int(wdoc.get("seed", self.seed))
        _swap_rec("manifest", -1, ok=True, seed=new_seed,
                  canaries=len(expected))

        for rid in sorted(self._live_ids()):
            remote = self.replicas.get(rid)
            if remote is None or remote.draining or remote.exited:
                continue
            live = len(self._live_ids())
            if live - 1 < self.min_healthy:
                result["refused"] += 1
                result["aborted"] = True
                self._act("swap_refused", rid, live=live,
                          min_healthy=self.min_healthy)
                _swap_rec("quiesce", rid, ok=False,
                          reason="min_healthy")
                break
            # quiesce: admissions off, live sessions migrate out warm
            self._act("swap_quiesce", rid, tag=str(tag))
            migrated: Dict[str, int] = {}
            if self.router is not None:
                self.router.remove_replica(rid)
                if hasattr(self.router, "migrate_sessions"):
                    migrated = self.router.migrate_sessions(
                        rid, reason="swap")
            quiet = self._quiesce(remote, timeout_s)
            _swap_rec("quiesce", rid, ok=quiet, migrate=migrated)
            reply = self._reload_sync(remote, ckpt_dir, None, timeout_s)
            if reply is None or not reply.get("ok"):
                # reload failed (corrupt release seen worker-side,
                # channel death, timeout): abort + roll this replica
                # back to the running weights before it rejoins
                err = None if reply is None else reply.get("error")
                _swap_rec("reload", rid, ok=False, error=err)
                result["aborted"] = True
                result["error"] = err or "reload timeout"
                if reply is not None:
                    rb = self._reload_sync(remote, None, self.seed,
                                           timeout_s)
                    if rb is not None and rb.get("ok"):
                        result["rolled_back"] += 1
                        if self.router is not None:
                            self.router.add_replica(remote)
                        self._act("swap_rollback", rid, tag=str(tag))
                else:
                    remote._send_failed = True  # crash containment
                break
            measured = {str(k): [int(c) for c in v] for k, v in
                        (reply.get("canary_chains") or {}).items()}
            parity = measured == expected
            divergent = sorted(k for k in expected
                               if measured.get(k) != expected[k])
            _swap_rec("parity", rid, ok=parity,
                      canaries=len(expected),
                      divergent=divergent[:8])
            if not parity:
                # THE gate: the new weights do not reproduce the
                # published canary streams on this replica — abort the
                # rollout and put the old weights back before rejoin
                result["aborted"] = True
                result["parity_ok"] = False
                result["error"] = (f"canary parity failed on r{rid}: "
                                   f"canaries {divergent[:8]} diverged")
                rb = self._reload_sync(remote, None, self.seed,
                                       timeout_s)
                if rb is not None and rb.get("ok"):
                    result["rolled_back"] += 1
                    if self.router is not None:
                        self.router.add_replica(remote)
                    self._act("swap_rollback", rid, tag=str(tag),
                              divergent=divergent[:8])
                else:
                    remote._send_failed = True
                break
            if self.router is not None:
                self.router.add_replica(remote)
            result["swapped"] += 1
            self._act("swap", rid, tag=str(tag))
            _swap_rec("done", rid, ok=True)

        if not result["aborted"] and result["swapped"] > 0:
            self.seed = new_seed  # restarts now reproduce the release
        _swap_rec("rollout", -1, ok=not result["aborted"],
                  swapped=result["swapped"],
                  rolled_back=result["rolled_back"])
        self._act("swap_done" if not result["aborted"]
                  else "swap_abort", -1, tag=str(tag),
                  swapped=result["swapped"],
                  rolled_back=result["rolled_back"])
        return result

    def shutdown(self, timeout_s: float = 10.0) -> None:
        """SIGTERM everyone, wait, SIGKILL stragglers, stop rx threads."""
        for rid, proc in self._procs.items():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + timeout_s
        for proc in self._procs.values():
            left = max(deadline - time.time(), 0.1)
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        for stop in self._rx_stop.values():
            stop.set()
        for t in self._rx_threads.values():
            t.join(timeout=2.0)
        for r in self.replicas.values():
            try:
                r.channel.close()
            except Exception:
                pass

    # -- fleet snapshot (serve_top --fleet) ----------------------------
    def write_fleet_snapshot(self) -> str:
        """Merge channel-side fleet state into one document the
        cross-process ``serve_top --fleet`` can read without importing
        jax or joining any socket."""
        path = os.path.join(self.run_dir, "fleet_snapshot.json")
        if self.router is not None:
            snap = self.router.fleet_snapshot()
        else:
            snap = {"schema": "serving_fleet/v3", "ts": wall_time(),
                    "replicas": [r.load_report()
                                 for r in self.replicas.values()]}
            jr = get_journal()
            if jr is not None:
                snap["journal"] = jr.snapshot()
        snap["supervisor"] = {
            "actions": [{"ts": ts, "action": act, "replica": rid}
                        for ts, act, rid in self.actions[-64:]],
            "restarts": sum(1 for _, act, _r in self.actions
                            if act == "restart"),
            "quarantined": sorted(self.quarantined),
            "pending_restarts": len(self._pending_restarts),
            "min_healthy": self.min_healthy,
            "procs": {str(rid): {
                "pid": p.pid,
                "running": p.poll() is None,
                "returncode": p.poll(),
            } for rid, p in self._procs.items()},
            "transport": {str(rid): {
                "tx_bytes": r.channel.bytes_sent,
                "rx_bytes": r.channel.bytes_received,
                "transport_errors": r.transport_errors,
                "dup_frames": getattr(r.channel, "dup_frames", 0),
            } for rid, r in self.replicas.items()},
        }
        if self.clock_sync:
            snap["clock"] = {
                str(rid): info for rid, r in self.replicas.items()
                if (info := r.clock_info()) is not None}
        if self.metrics_plane.ingested:
            # the transport-borne metrics plane: per-worker hub values
            # merged with no shared run dir (workers may be remote)
            snap["fleet_metrics"] = self.metrics_plane.merged()
        _atomic_write_json(path, snap)
        return path
