"""Serving replica: one engine_v2 instance wrapped for fleet duty.

A replica owns exactly one :class:`InferenceEngineV2` and adds what the
router needs to treat N of them as a fleet:

* a **role** — ``unified`` (prefill + decode), ``prefill``, or
  ``decode`` (the disaggregated pools, serving/disagg.py);
* an **inbox** of submissions, so every engine mutation happens on the
  replica's own pump thread (the engine is single-threaded by design;
  the inbox is the concurrency boundary);
* a **heartbeat** updated on every pump and a **load report** (queue
  depth, KV-pool pressure, in-flight sequences, goodput EWMA) — the
  router's routing and stale-heartbeat failover inputs, optionally
  published through the PR 3 fleet machinery
  (``observability/fleet.py`` ``ReplicaPublisher``) for external
  ``serve_top --fleet`` consumers;
* ``kill()`` — a simulated crash for failover tests and drills: the
  pump stops mid-flight *without* draining, the heartbeat goes stale,
  and the router's health check must recover the in-flight requests.

The engine is constructed with ``metric_labels={"replica": "rN"}`` so
every ``serve.*`` hub series carries the replica id — fleet dashboards
aggregate across labels instead of collapsing N replicas into one line.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.observability.clocksync import wall_time

ROLES = ("unified", "prefill", "decode")


@dataclasses.dataclass
class Submission:
    """One routed request on its way into a replica's engine. Applied
    on the pump thread: install the handoff payload (if any), ``put``,
    then record the routing span notes on the replica's tracer."""

    uid: int
    tokens: np.ndarray
    max_new_tokens: int
    span_notes: List[Tuple[str, Dict[str, Any]]] = \
        dataclasses.field(default_factory=list)
    handoff: Optional[Any] = None  # disagg.KVHandoff
    # disagg.SessionHandoff — a live-migrated mid-stream session. When
    # set, install replaces put(): the migrated KV blocks, generated
    # tokens, and spec EWMA land through install_session and decode
    # resumes warm (zero re-prefill). ``tokens``/``max_new_tokens``
    # then describe the RECOMPUTE fallback the installer degrades to
    # if the payload can't land (pool full, geometry mismatch, ...).
    session: Optional[Any] = None


@dataclasses.dataclass
class _MigrateOut:
    """Inbox marker: capture+release session ``uid`` on the pump thread
    (the only thread allowed to touch the engine) and hand the
    SessionHandoff — or None if the session is gone — to ``cb``."""

    uid: int
    cb: Callable[[Optional[Any]], None]
    wire: Optional[str] = None


class ServingReplica:
    def __init__(self, engine, replica_id: int, role: str = "unified",
                 publisher=None, goodput_alpha: float = 0.25):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.engine = engine
        self.replica_id = int(replica_id)
        self.name = f"r{self.replica_id}"
        self.role = role
        self.publisher = publisher
        self.inbox: "queue.Queue[Submission]" = queue.Queue()
        # router wires this to its emission handler; called on the pump
        # thread with (replica, {uid: [tokens]}) after each serve round
        self.emit_callback: Optional[Callable] = None
        # load_report ts: this process's wall clock (skew-aware, so a
        # cross-process supervisor can rebase it like any other stamp)
        self.last_heartbeat = wall_time()
        self.last_heartbeat_mono = time.monotonic()  # liveness decisions
        self.transport_errors = 0  # in-process replicas have no wire
        self.killed = False
        self.steps = 0
        self.goodput_ewma = 0.0
        self._alpha = float(goodput_alpha)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @classmethod
    def create(cls, model, replica_id: int, role: str = "unified",
               run_dir: Optional[str] = None, **engine_kw
               ) -> "ServingReplica":
        """Build the replica AND its engine, injecting the per-replica
        metric labels and (when a run dir is given) the fleet-layer
        load-report publisher."""
        from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

        engine_kw.setdefault("metric_labels",
                             {"replica": f"r{int(replica_id)}"})
        engine = InferenceEngineV2(model, **engine_kw)
        publisher = None
        if run_dir:
            from deepspeed_tpu.observability.fleet import ReplicaPublisher

            publisher = ReplicaPublisher(run_dir, replica_id)
        return cls(engine, replica_id, role=role, publisher=publisher)

    # -- liveness ------------------------------------------------------
    def heartbeat_age(self, now: Optional[float] = None) -> float:
        """Seconds since the last pump, on the *monotonic* clock — a
        stepped wall clock (NTP slew, manual reset) must never make a
        healthy replica look dead. ``now``, when given, is a
        ``time.monotonic()`` timestamp."""
        now = time.monotonic() if now is None else now
        return now - self.last_heartbeat_mono

    def alive(self, now: Optional[float] = None,
              stale_after: float = 5.0) -> bool:
        """Stale-heartbeat liveness — the same contract as the fleet
        aggregator's dead-rank detection: a killed replica is not dead
        until its heartbeat *ages out*, which is exactly what a real
        crashed process looks like to a router that can only observe
        published state. ``now`` is monotonic (see heartbeat_age)."""
        return self.heartbeat_age(now) < stale_after

    def kill(self) -> None:
        """Simulated crash: stop pumping (and heartbeating) immediately,
        leaving the inbox and the engine's in-flight sequences wedged —
        recovery is entirely the router's failover problem."""
        self.killed = True
        self._stop.set()

    # -- the serve round ----------------------------------------------
    def pump(self, eos_token_id: Optional[int] = None
             ) -> Dict[int, List[int]]:
        """One serve round: drain the inbox into the engine, run one
        ``serve_step``, heartbeat, and hand emissions to the router.
        The ONLY code path that touches the engine — callers on other
        threads go through :meth:`submit`."""
        if self.killed:
            return {}
        t0 = time.perf_counter()
        while True:
            try:
                sub = self.inbox.get_nowait()
            except queue.Empty:
                break
            if isinstance(sub, _MigrateOut):
                self._migrate_out(sub)
            else:
                self._apply(sub)
        busy = bool(self.engine.state.seqs) or bool(self.engine._queue)
        emitted = self.engine.serve_step(eos_token_id=eos_token_id) \
            if busy else {}
        self.steps += 1
        now = wall_time()
        self.last_heartbeat = now
        self.last_heartbeat_mono = time.monotonic()
        dt = max(time.perf_counter() - t0, 1e-9)
        rate = sum(len(v) for v in emitted.values()) / dt
        self.goodput_ewma = (self._alpha * rate
                             + (1.0 - self._alpha) * self.goodput_ewma)
        if self.publisher is not None:
            self.publisher.publish(self.load_report(now))
        if emitted and self.emit_callback is not None:
            self.emit_callback(self, emitted)
        return emitted

    def _apply(self, sub: Submission) -> None:
        if sub.session is not None:
            # live migration install: the payload carries the session's
            # KV blocks + descriptor state, so install replaces put()
            # entirely — install_session enqueues/admits internally and
            # degrades (paged / recompute) on its own when the warm
            # path can't land, using the folded tokens in the payload.
            from deepspeed_tpu.serving.disagg import install_session

            rung = install_session(self.engine, sub.session)
            sub.span_notes.append(("MIGRATE", {
                "stage": "install", "rung": rung,
                "blocks": sub.session.n_blocks
                if sub.session.block_data is not None else 0}))
            for kind, fields in sub.span_notes:
                fields.setdefault("replica_id", self.replica_id)
                self.engine.tracer.note(sub.uid, kind, **fields)
            return
        if sub.handoff is not None:
            from deepspeed_tpu.serving.disagg import install_prefix

            blocks, tokens = install_prefix(self.engine, sub.handoff)
            # tokens>0 with blocks==0 means the chain was already
            # installed here by an earlier handoff — still the KV path
            sub.span_notes.append(("HANDOFF", {
                "blocks": blocks, "tokens": tokens,
                "mode": "kv_blocks" if tokens else "recompute"}))
        self.engine.put([sub.uid], [sub.tokens],
                        max_new_tokens=sub.max_new_tokens)
        for kind, fields in sub.span_notes:
            # stamp which replica actually applied the span: routers and
            # supervisors attach notes from their own process, and the
            # cross-process trace merge needs the executing replica id
            fields.setdefault("replica_id", self.replica_id)
            self.engine.tracer.note(sub.uid, kind, **fields)

    def submit(self, sub: Submission) -> None:
        self.inbox.put(sub)

    def serialize_handoff(self, tokens: np.ndarray,
                          cb: Callable[[Optional[Any]], None]) -> None:
        """Serialize this replica's KV prefix for ``tokens`` and hand
        the payload to ``cb`` (None = degrade to recompute). Local
        replicas run it synchronously — _handoff is called on THIS
        replica's pump thread, so reading its KV pool here is race-free,
        the pre-transport semantics. RemoteReplica overrides this with a
        serialize RPC whose reply invokes ``cb`` later."""
        from deepspeed_tpu.serving.disagg import serialize_prefix

        cb(serialize_prefix(self.engine, tokens))

    def migrate_out(self, uid: int,
                    cb: Callable[[Optional[Any]], None],
                    wire: Optional[str] = None) -> None:
        """Capture session ``uid``'s full decode state (committed KV
        blocks, partial tail block, generated tokens, spec EWMA) as a
        SessionHandoff, release it here, and hand the payload to ``cb``
        (None = session gone or un-capturable; the caller degrades to
        fold-and-resubmit recompute). The capture is enqueued as an
        inbox marker so it runs on the pump thread — the engine is
        single-threaded, and migrate-out both reads the KV pool and
        mutates sequence state. A killed replica never pumps, so its
        callbacks never fire; callers must pair this with the same
        stale-heartbeat failover that covers ordinary requests.
        RemoteReplica overrides with a migrate RPC (deadline-expired)."""
        self.inbox.put(_MigrateOut(uid=int(uid), cb=cb, wire=wire))

    def _migrate_out(self, mo: "_MigrateOut") -> None:
        """Pump-thread half of migrate_out."""
        from deepspeed_tpu.serving.disagg import serialize_session

        try:
            sess = serialize_session(self.engine, mo.uid, wire=mo.wire)
        except Exception:
            sess = None  # degrade, never wedge the pump
        mo.cb(sess)

    # -- load report ---------------------------------------------------
    def load_report(self, now: Optional[float] = None) -> Dict[str, Any]:
        e = self.engine
        live = [s for s in e.state.seqs.values() if not s.done]
        total = e.kv_cache.allocator.total_blocks
        free = e.kv_cache.free_blocks
        tier = getattr(e.kv_cache, "host_tier", None)
        return {
            "replica": self.replica_id,
            "role": self.role,
            "ts": self.last_heartbeat if now is None else now,
            "steps": self.steps,
            "queue_wait_depth": len(e._queue),
            "live_seqs": len(live),
            "inflight": len(live) + len(e._queue) + self.inbox.qsize(),
            "kv_free_blocks": free,
            "kv_free_frac": free / max(1, total),
            "goodput_tokens_per_s": round(self.goodput_ewma, 3),
            "killed": self.killed,
            # serving-quant data plane (ISSUE 12): pool storage mode,
            # handoff codec, cumulative wire-vs-logical handoff bytes,
            # and the last measured wire SNR (None until a quantized
            # handoff leaves/enters this replica)
            "kv_quant_bits": getattr(e.kv_cache, "quant_bits", None),
            "handoff_wire": getattr(e, "_handoff_wire", "auto"),
            "handoff_wire_bytes": getattr(e, "_handoff_wire_bytes", 0),
            "handoff_logical_bytes": getattr(
                e, "_handoff_logical_bytes", 0),
            "kv_wire_snr_db": getattr(e, "_last_kv_wire_snr_db", None),
            # adaptive speculation + host KV tier (ISSUE 17): measured
            # acceptance EWMA + rejected-verify-row count drive the
            # per-request draft-length controller; the host-tier gauges
            # show how much session state lives below HBM (and
            # paged_out/paged_in how often decode warm-resumes)
            "spec_accept_ewma": getattr(e, "_spec_accept_ewma", None),
            "spec_wasted_verify_tokens": getattr(
                e, "_spec_wasted_verify_tokens", 0),
            "host_tier_bytes": (0 if tier is None else tier.used_bytes),
            "host_tier_blocks": (0 if tier is None else tier.total_blocks),
            "host_tier_sessions": (0 if tier is None
                                   else tier.session_count),
            "paged_out": e.stats.get("paged_out", 0),
            "paged_in": e.stats.get("paged_in", 0),
            # live migration (ISSUE 20): warm sessions shipped out/in
            # plus the degradation-ladder counters (host-tier page-out,
            # legacy recompute) — the drill's "zero cold resumes" gate
            # reads these across the fleet
            "migrated_out": e.stats.get("migrated_out", 0),
            "migrated_in": e.stats.get("migrated_in", 0),
            "migrate_paged": e.stats.get("migrate_paged", 0),
            "migrate_recompute": e.stats.get("migrate_recompute", 0),
        }

    def holds_prefix(self, tokens) -> int:
        """Full prefix blocks of ``tokens`` this replica can serve
        without prefill (HBM prefix cache + host tier) — the router's
        session-affinity probe. RemoteReplica proxies don't implement
        this; the router getattr-guards the call."""
        fn = getattr(self.engine, "holds_prefix_blocks", None)
        return 0 if fn is None else fn(tokens)

    def load_score(self) -> float:
        """Routing cost: queued + live work, plus KV-pool pressure as a
        tiebreaker (two idle replicas: prefer the emptier pool, where a
        new prompt is least likely to trigger evictions)."""
        r = self.load_report()
        return (r["queue_wait_depth"] + r["live_seqs"]
                + self.inbox.qsize() + (1.0 - r["kv_free_frac"]))

    # -- threaded mode -------------------------------------------------
    def start(self, eos_token_id: Optional[int] = None,
              idle_sleep_s: float = 0.001) -> None:
        """Run the pump on a dedicated thread (the bench's in-process
        fleet). Synchronous callers (tests) skip this and drive
        :meth:`pump` directly."""
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                emitted = self.pump(eos_token_id=eos_token_id)
                if not emitted and self.inbox.empty():
                    time.sleep(idle_sleep_s)

        self._thread = threading.Thread(
            target=_loop, name=f"replica-{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
