"""Subprocess replica entrypoint: ``python -m deepspeed_tpu.serving.proc_worker spec.json``.

One OS process = one :class:`ServingReplica`. The supervisor
(serving/supervisor.py) writes a JSON spec, spawns this module, and
talks to it over a transport channel; everything engine-side reuses the
in-process replica unchanged — the worker is a thin message loop around
``replica.pump()``.

Startup handshake: the worker binds its socket (or opens its spool
lanes), atomically writes a ready file ``{"pid", "port", "channel"}``
next to the spec, and accepts the supervisor's connection. Determinism
across processes comes from the spec's ``seed``: every worker builds
the same model and calls ``model.init(PRNGKey(seed))``, so N processes
serve one set of weights without shipping arrays over the wire.

Message protocol (all dicts through transport/messages.py):

  supervisor -> worker
    {"type": "submit", "uid", "tokens", "max_new_tokens",
     "span_notes", "handoff"}      routed request (handoff: encoded
                                   KVHandoff or None)
    {"type": "serialize", "req", "tokens"}
                                   serialize this worker's KV prefix;
                                   reply carries the same req id
    {"type": "migrate_out", "req", "uid", "wire"}
                                   capture + release a live session's
                                   full decode state (ISSUE 20);
                                   reply: session_payload (session may
                                   be None = already finished/gone)
    {"type": "install_session", "uid", "tokens", "max_new_tokens",
     "span_notes", "session"}      install a migrated session (encoded
                                   SessionHandoff); tokens carry the
                                   recompute fallback
    {"type": "reload", "req", "ckpt_dir", "seed"}
                                   rolling weight hot-swap: validate
                                   the manifest, reload params, run the
                                   canary prompt set, reply reload_done
                                   with the measured token chains
    {"type": "drain"}              stop = finish in-flight, then exit 0
    {"type": "ping"}               liveness probe -> {"type": "pong"}

  worker -> supervisor
    {"type": "emit", "emitted", "report", "traces", "geometry"}
                                   per-round emissions + load report
                                   (also sent bare as the heartbeat)
    {"type": "handoff_payload", "req", "handoff"}
    {"type": "session_payload", "req", "session"}
    {"type": "reload_done", "req", "ok", "error", "tag", "seed",
     "canary_chains"}
    {"type": "exiting", "replica"} drain complete, about to exit

Channel FIFO is what makes migrate-then-drain race-free: the
supervisor sends every ``migrate_out`` before the ``drain`` flag, so
the worker captures sessions while still serving; and every emission
sent before a ``session_payload`` reply arrived first, so the
supervisor's folded token state is complete when the capture lands.

Graceful drain is SIGTERM *or* the drain message: both flip the same
flag, the worker stops admitting, finishes what it holds, announces
``exiting``, and leaves. Chaos drills reuse the training-side
``DSTPU_CHAOS`` spec (resilience/chaos.py): ``kill_rank`` is matched
against the replica id and ``kill_step`` against *busy* serve rounds,
so the kill lands mid-request — the supervisor's restart path and the
router's zero-drop failover are what the drill measures.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict, Optional


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _resolve_dtypes(d: Dict[str, Any]) -> Dict[str, Any]:
    """Map dtype names back to jnp dtypes ("float32" came over JSON)."""
    import jax.numpy as jnp

    out = dict(d)
    for k, v in d.items():
        if k.endswith("dtype") and isinstance(v, str):
            out[k] = getattr(jnp, v)
    return out


def build_replica(spec: Dict[str, Any]):
    """Model + params + ServingReplica from the spec — deterministic:
    same spec seed => bit-identical params in every process."""
    import jax
    import jax.numpy as jnp  # noqa: F401  (dtype resolution)

    from deepspeed_tpu.models.zoo import get_model
    from deepspeed_tpu.serving.replica import ServingReplica

    mspec = spec.get("model") or {"name": "tiny"}
    model = get_model(mspec.get("name", "tiny"),
                      **_resolve_dtypes(mspec.get("overrides") or {}))
    params = model.init(jax.random.PRNGKey(int(spec.get("seed", 0))))
    engine_kw = _resolve_dtypes(spec.get("engine") or {})
    return ServingReplica.create(
        model, int(spec["replica_id"]), role=spec.get("role", "unified"),
        run_dir=spec.get("run_dir"), params=params, **engine_kw)


def open_channel(spec: Dict[str, Any]):
    """Bind the transport, publish the ready file, return the connected
    channel. Socket is the primary; the file channel is the degraded
    fallback for socketless sandboxes (docs/serving.md matrix)."""
    from deepspeed_tpu.serving.transport import (FileChannel, SocketServer)

    max_frame = int(spec.get("max_frame_mb", 64)) << 20
    kind = spec.get("channel", "socket")
    ready = {"pid": os.getpid(), "channel": kind, "port": None}
    if kind == "socket":
        srv = SocketServer(max_frame_bytes=max_frame)
        ready["port"] = srv.port
        _atomic_write_json(spec["ready_path"], ready)
        chan = srv.accept(timeout=60.0)
        srv.close()  # one supervisor per worker; stop listening
        return chan
    if kind == "file":
        chan = FileChannel(spec["spool_dir"], side="b",
                           max_frame_bytes=max_frame)
        _atomic_write_json(spec["ready_path"], ready)
        return chan
    raise ValueError(f"unknown channel kind {kind!r}")


class WorkerLoop:
    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.replica = build_replica(spec)
        self.channel = open_channel(spec)
        self.eos_token_id = spec.get("eos_token_id")
        self.step_delay_s = float(spec.get("step_delay_ms", 0.0)) / 1e3
        self.heartbeat_s = float(spec.get("heartbeat_s", 0.1))
        self.draining = False
        self._last_send = 0.0  # time.monotonic(); cadence only
        self._sent_traces: set = set()
        self._busy_steps = 0
        self._received_submits = 0  # acked back in every report
        from deepspeed_tpu.resilience.chaos import ChaosInjector, ChaosSpec

        self.chaos = ChaosInjector(ChaosSpec.from_env(),
                                   rank=self.replica.replica_id)
        signal.signal(signal.SIGTERM, self._on_sigterm)
        # heartbeats come from their own thread so liveness survives a
        # long engine step — the first serve round JIT-compiles for
        # seconds, and a heartbeat gap that long reads as a dead
        # replica to the router's staleness check
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"hb-r{self.replica.replica_id}")

    def _on_sigterm(self, signum, frame) -> None:
        self.draining = True

    # -- inbound -------------------------------------------------------
    def _drain_channel(self) -> None:
        from deepspeed_tpu.serving.replica import Submission
        from deepspeed_tpu.serving.transport import (decode_handoff,
                                                     decode_session)

        while True:
            msg = self.channel.recv(timeout=0.0)
            if msg is None:
                return
            kind = msg.get("type")
            if kind == "submit":
                self._received_submits += 1
                notes = [(str(k), dict(f))
                         for k, f in msg.get("span_notes") or []]
                self.replica.submit(Submission(
                    uid=int(msg["uid"]), tokens=msg["tokens"],
                    max_new_tokens=int(msg["max_new_tokens"]),
                    span_notes=notes,
                    handoff=decode_handoff(msg.get("handoff"))))
            elif kind == "serialize":
                self._serialize(msg)
            elif kind == "migrate_out":
                self._migrate_out(msg)
            elif kind == "install_session":
                self._received_submits += 1
                notes = [(str(k), dict(f))
                         for k, f in msg.get("span_notes") or []]
                self.replica.submit(Submission(
                    uid=int(msg["uid"]), tokens=msg["tokens"],
                    max_new_tokens=int(msg["max_new_tokens"]),
                    span_notes=notes,
                    session=decode_session(msg.get("session"))))
            elif kind == "reload":
                self._reload(msg)
            elif kind == "drain":
                self.draining = True
            elif kind == "ping":
                self.channel.send({"type": "pong",
                                   "replica": self.replica.replica_id})

    def _serialize(self, msg: Dict[str, Any]) -> None:
        from deepspeed_tpu.serving.disagg import serialize_prefix
        from deepspeed_tpu.serving.transport import encode_handoff

        payload = serialize_prefix(self.replica.engine, msg["tokens"])
        self.channel.send({"type": "handoff_payload",
                           "req": msg["req"],
                           "handoff": encode_handoff(payload)})

    def _migrate_out(self, msg: Dict[str, Any]) -> None:
        """Capture + release a live session on this (the pump) thread.
        Runs directly — _drain_channel and pump share the worker main
        thread, so the engine is quiescent here. Every emission this
        session produced was sent before this reply (channel FIFO), so
        the supervisor's folded token state is complete."""
        from deepspeed_tpu.serving.disagg import serialize_session
        from deepspeed_tpu.serving.transport import encode_session

        try:
            sess = serialize_session(self.replica.engine,
                                     int(msg["uid"]),
                                     wire=msg.get("wire"))
        except Exception:
            sess = None  # degrade to recompute, never wedge the worker
        self.channel.send({"type": "session_payload",
                           "req": msg["req"],
                           "session": encode_session(sess)})

    def _reload(self, msg: Dict[str, Any]) -> None:
        """Rolling weight hot-swap, worker side: validate the published
        checkpoint's manifest, rebuild params (zero recompilation — all
        step functions take params as arguments), then re-measure the
        canary prompt set and reply with its token checksum chains. The
        supervisor compares them against the publisher's expected
        chains (A/B token parity) before letting this replica rejoin.
        The caller drained us first, so the engine is empty; canary
        uids live in the 3_000_000+ range and are flushed after."""
        from deepspeed_tpu.observability.journal import chain_tokens
        from deepspeed_tpu.resilience.manifest import validate_manifest

        req = msg.get("req")
        reply: Dict[str, Any] = {"type": "reload_done", "req": req,
                                 "ok": False, "error": None, "tag": None,
                                 "seed": None, "canary_chains": {}}
        try:
            ckpt_dir = msg.get("ckpt_dir")
            seed = msg.get("seed")
            canary = {}
            if ckpt_dir:
                validate_manifest(ckpt_dir)  # raises on torn/corrupt
                with open(os.path.join(ckpt_dir, "weights.json")) as f:
                    wdoc = json.load(f)
                reply["tag"] = wdoc.get("tag")
                seed = wdoc.get("seed", seed)
                canary = wdoc.get("canary") or {}
            eng = self.replica.engine
            eng.reload_params(seed=int(seed or 0))
            reply["seed"] = int(seed or 0)
            prompts = canary.get("prompts") or []
            if prompts:
                import numpy as np

                gen = int(canary.get("gen", 8))
                uids = [3_000_000 + i for i in range(len(prompts))]
                eng.put(uids, [np.asarray(p, np.int32) for p in prompts],
                        max_new_tokens=gen)
                out = eng.generate_all(eos_token_id=self.eos_token_id)
                eng.flush(uids)
                reply["canary_chains"] = {
                    str(i): chain_tokens(out.get(uid, []))
                    for i, uid in enumerate(uids)}
            reply["ok"] = True
        except Exception as exc:  # parity gate aborts on any failure
            reply["error"] = f"{type(exc).__name__}: {exc}"
        self.channel.send(reply)

    # -- outbound ------------------------------------------------------
    def _geometry(self) -> Dict[str, Any]:
        e = self.replica.engine
        return {"block_size": int(e.kv_cache.config.block_size),
                "total_blocks": int(e.kv_cache.allocator.total_blocks),
                "max_blocks_per_seq": int(e.max_blocks_per_seq)}

    def _new_traces(self):
        out = []
        for t in self.replica.engine.tracer.finished():
            if t.trace_id not in self._sent_traces:
                self._sent_traces.add(t.trace_id)
                out.append(t.to_dict())
        return out

    def _report(self) -> Dict[str, Any]:
        """Load report with the submit ack counter: the supervisor's
        stub subtracts it from its own sent counter to size the
        still-on-the-wire window (RemoteReplica._unacked)."""
        rep = self.replica.load_report()
        rep["received_submits"] = self._received_submits
        return rep

    def _metrics(self) -> Dict[str, Any]:
        """Compact snapshot of this process's hub, piggybacked on every
        emit so the supervisor's fleet metrics plane needs no shared
        filesystem. Empty (and omitted from the wire message) when the
        hub has nothing under the serving prefixes."""
        from deepspeed_tpu.observability.fleet_metrics import \
            compact_snapshot
        from deepspeed_tpu.observability.hub import peek_hub

        return compact_snapshot(peek_hub())

    def _send_emit(self, emitted: Dict[int, list]) -> None:
        msg = {
            "type": "emit",
            "emitted": {str(u): [int(t) for t in toks]
                        for u, toks in emitted.items()},
            "report": self._report(),
            "traces": self._new_traces(),
            "geometry": self._geometry(),
        }
        metrics = self._metrics()
        if metrics:
            msg["metrics"] = metrics
        self.channel.send(msg)
        self._last_send = time.monotonic()

    def _heartbeat_loop(self) -> None:
        """Report-only sends at heartbeat cadence (monotonic clock — a
        wall-clock step must not stall or burst the heartbeat); no
        emissions or traces, so the main loop stays the only writer of
        those."""
        while not self._hb_stop.is_set():
            if (time.monotonic() - self._last_send) >= self.heartbeat_s:
                try:
                    msg = {"type": "emit", "emitted": {},
                           "report": self._report(),
                           "traces": [], "geometry": self._geometry()}
                    metrics = self._metrics()
                    if metrics:
                        msg["metrics"] = metrics
                    self.channel.send(msg)
                    self._last_send = time.monotonic()
                except Exception:
                    return  # channel gone; the main loop exits too
            self._hb_stop.wait(self.heartbeat_s / 4.0)

    # -- the loop ------------------------------------------------------
    def _idle(self) -> bool:
        e = self.replica.engine
        return (not e.state.seqs and not e._queue
                and self.replica.inbox.empty())

    def run(self) -> int:
        self._hb_thread.start()
        try:
            return self._run()
        finally:
            self._hb_stop.set()

    def _run(self) -> int:
        while True:
            try:
                self._drain_channel()
            except Exception:
                # supervisor gone: nothing to serve for; exit loud so
                # the (possibly new) supervisor sees a non-zero status
                return 1
            emitted = self.replica.pump(eos_token_id=self.eos_token_id)
            if emitted:
                self._busy_steps += 1
                # chaos drills count busy rounds so the kill lands
                # mid-request, not during warmup idle
                self.chaos.on_step(self._busy_steps)
            if self.step_delay_s > 0.0:
                time.sleep(self.step_delay_s)  # simulated degradation
            now = time.monotonic()
            if emitted or (now - self._last_send) >= self.heartbeat_s:
                try:
                    self._send_emit(emitted)
                except Exception:
                    return 1
            if self.draining and self._idle():
                try:
                    self._send_emit({})
                    self.channel.send({"type": "exiting",
                                       "replica": self.replica.replica_id})
                except Exception:
                    pass
                return 0
            if not emitted:
                time.sleep(0.001)


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m deepspeed_tpu.serving.proc_worker "
              "<spec.json>", file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        spec = json.load(f)
    # the spec pins the platform before jax import — fleet workers are
    # host processes; the accelerator belongs to the engine they host
    os.environ.setdefault("JAX_PLATFORMS",
                          spec.get("jax_platform", "cpu"))
    return WorkerLoop(spec).run()


if __name__ == "__main__":
    sys.exit(main())
