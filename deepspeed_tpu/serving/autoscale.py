"""Autoscaling signals: desired replica count as a metric, not an act.

The router knows everything an autoscaler needs — fleet queue depth,
SLO-miss rate, goodput trend — but provisioning is an infrastructure
concern (k8s HPA, GKE, a TPU pod reservation system). So this module
only *derives the signal*: a desired-replica-count gauge with
hysteresis, exported through the hub like every other metric
(``serve.fleet.desired_replicas`` on the Prometheus page), for an
external controller to act on. This is the same shape as
node-exporter-style "recommendation" metrics and keeps the repo free of
any cloud-API dependency.

Inputs per evaluation (the router calls :meth:`update` from its health
check):

* per-replica queue pressure — waiting requests per alive replica;
* SLO-miss rate — misses / finishes in the window (the tracer's
  fleet-level counters);
* goodput slope — EWMA of the fleet goodput delta, so a *rising* load
  blocks scale-down even while the queue is momentarily empty.

Hysteresis: a scale decision needs ``hysteresis_rounds`` *consecutive*
evaluations on the same side of the thresholds, and any contrary
evaluation resets the streak — the classic guard against flapping on a
bursty arrival process.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from deepspeed_tpu.observability.clocksync import wall_time
from deepspeed_tpu.observability.journal import get_journal


class AutoscaleSignal:
    def __init__(self, min_replicas: int = 1, max_replicas: int = 8,
                 queue_high: float = 4.0, queue_low: float = 0.5,
                 slo_miss_high: float = 0.1,
                 hysteresis_rounds: int = 3,
                 goodput_alpha: float = 0.25, hub=None):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"({min_replicas}, {max_replicas})")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.slo_miss_high = float(slo_miss_high)
        self.hysteresis_rounds = max(1, int(hysteresis_rounds))
        self._alpha = float(goodput_alpha)
        self.desired: Optional[int] = None
        self.goodput_slope = 0.0
        self._last_goodput: Optional[float] = None
        self._up_streak = 0
        self._down_streak = 0
        self._hub = hub
        self.history = []  # (ts, desired) decision log for the report

    def update(self, n_replicas: int, queue_wait_depth: float,
               slo_miss_rate: float, goodput_tokens_per_s: float,
               now: Optional[float] = None) -> int:
        """One evaluation; returns the (possibly unchanged) desired
        replica count and mirrors every signal into hub gauges."""
        now = wall_time() if now is None else now
        n = max(1, int(n_replicas))
        if self.desired is None:
            self.desired = min(max(n, self.min_replicas), self.max_replicas)
        pressure = float(queue_wait_depth) / n
        if self._last_goodput is not None:
            delta = float(goodput_tokens_per_s) - self._last_goodput
            self.goodput_slope = (self._alpha * delta
                                  + (1.0 - self._alpha) * self.goodput_slope)
        self._last_goodput = float(goodput_tokens_per_s)

        hot = (pressure > self.queue_high
               or float(slo_miss_rate) > self.slo_miss_high)
        # scale-down also requires non-rising goodput: a draining queue
        # with climbing throughput means load is arriving, not leaving
        cold = (pressure < self.queue_low
                and float(slo_miss_rate) <= self.slo_miss_high / 4.0
                and self.goodput_slope <= 0.0)
        if hot:
            self._up_streak += 1
            self._down_streak = 0
            if self._up_streak >= self.hysteresis_rounds:
                self.desired = min(self.max_replicas, self.desired + 1)
                self._up_streak = 0
                self.history.append((now, self.desired))
                self._journal("up", now, pressure, slo_miss_rate)
        elif cold:
            self._down_streak += 1
            self._up_streak = 0
            if self._down_streak >= self.hysteresis_rounds:
                if self.desired > self.min_replicas:
                    self.desired = self.desired - 1
                    self.history.append((now, self.desired))
                    self._journal("down", now, pressure, slo_miss_rate)
                self._down_streak = 0
        else:
            self._up_streak = 0
            self._down_streak = 0

        if self._hub is not None:
            self._hub.gauge("serve.fleet.desired_replicas", self.desired)
            self._hub.gauge("serve.fleet.queue_pressure", pressure)
            self._hub.gauge("serve.fleet.slo_miss_rate",
                            float(slo_miss_rate))
            self._hub.gauge("serve.fleet.goodput_slope", self.goodput_slope)
        return self.desired

    def _journal(self, direction: str, now: float, pressure: float,
                 slo_miss_rate: float) -> None:
        """One AUTOSCALE decision with the state that triggered it —
        the black-box record an incident review audits against the
        thresholds."""
        jr = get_journal()
        if jr is not None:
            jr.decision(
                "AUTOSCALE", ts=now, direction=direction,
                desired=self.desired,
                queue_pressure=round(float(pressure), 4),
                slo_miss_rate=round(float(slo_miss_rate), 4),
                goodput_slope=round(self.goodput_slope, 4),
                thresholds={"queue_high": self.queue_high,
                            "queue_low": self.queue_low,
                            "slo_miss_high": self.slo_miss_high,
                            "hysteresis_rounds": self.hysteresis_rounds})

    def record_action(self, action: str, replica_id: int,
                      now: Optional[float] = None,
                      live: Optional[int] = None,
                      **fields: Any) -> None:
        """Log an *act* on the signal into the decision history — the
        process supervisor is the first in-repo controller that actually
        provisions (spawn/drain/restart), and its acts belong on the
        same timeline as the desires that caused them. Action entries
        are ``(ts, desired, "action:rN")`` 3-tuples next to the
        ``(ts, desired)`` decision 2-tuples.

        Provisioning acts (spawn/drain) additionally journal a SCALE
        decision carrying desired-vs-actual and whatever the caller
        measured (e.g. how many sessions migrated out of a drained
        victim) — the forensics record ``serve_top --journal`` renders
        and ``tools/replay.py`` replays."""
        now = wall_time() if now is None else now
        self.history.append((now, self.desired, f"{action}:r{replica_id}"))
        if action in ("spawn", "drain"):
            jr = get_journal()
            if jr is not None:
                jr.decision("SCALE", ts=now, action=action,
                            replica=replica_id, desired=self.desired,
                            live=live, **fields)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "desired_replicas": self.desired,
            "goodput_slope": round(self.goodput_slope, 3),
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "decisions": list(self.history[-32:]),
        }
